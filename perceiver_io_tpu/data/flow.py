"""Optical-flow data module (Sintel layout + synthetic stand-in).

The reference has no flow data layer; this module feeds the flow extension
(BASELINE.md's Sintel config). Reads the MPI-Sintel directory layout
(``training/clean/<scene>/frame_NNNN.png`` with ``training/flow/<scene>/
frame_NNNN.flo``) when present — this box has zero egress, so there is no
downloader — and ``synthetic=True`` generates smooth random flow fields with
``frame2 = warp(frame1, flow)``, so smoke training has real signal to fit.
"""

from __future__ import annotations

import glob
import os
import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from perceiver_io_tpu.data.pipeline import DataLoader

_FLO_MAGIC = 202021.25


def read_flo(path: str) -> np.ndarray:
    """Middlebury .flo reader: (H, W, 2) float32."""
    with open(path, "rb") as f:
        magic = struct.unpack("<f", f.read(4))[0]
        if abs(magic - _FLO_MAGIC) > 1e-3:
            raise ValueError(f"{path}: bad .flo magic {magic}")
        w, h = struct.unpack("<ii", f.read(8))
        data = np.frombuffer(f.read(h * w * 2 * 4), dtype="<f4")
    return data.reshape(h, w, 2)


def _smooth_field(rng, h: int, w: int, channels: int, scale: float) -> np.ndarray:
    """Low-frequency random field: coarse noise, bilinearly upsampled."""
    ch, cw = max(h // 8, 2), max(w // 8, 2)
    coarse = rng.normal(0, scale, (ch, cw, channels)).astype(np.float32)
    ys = np.linspace(0, ch - 1, h)
    xs = np.linspace(0, cw - 1, w)
    y0 = np.clip(ys.astype(int), 0, ch - 2)
    x0 = np.clip(xs.astype(int), 0, cw - 2)
    fy = (ys - y0)[:, None, None]
    fx = (xs - x0)[None, :, None]
    c00 = coarse[y0][:, x0]
    c01 = coarse[y0][:, x0 + 1]
    c10 = coarse[y0 + 1][:, x0]
    c11 = coarse[y0 + 1][:, x0 + 1]
    return (
        c00 * (1 - fy) * (1 - fx)
        + c01 * (1 - fy) * fx
        + c10 * fy * (1 - fx)
        + c11 * fy * fx
    )


def warp_backward(image: np.ndarray, flow: np.ndarray) -> np.ndarray:
    """Bilinear backward warp: out(p) = image(p + flow(p)), border-clamped."""
    h, w, _ = image.shape
    gy, gx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    sy = np.clip(gy + flow[..., 1], 0, h - 1)
    sx = np.clip(gx + flow[..., 0], 0, w - 1)
    y0 = np.clip(sy.astype(int), 0, h - 2)
    x0 = np.clip(sx.astype(int), 0, w - 2)
    fy = (sy - y0)[..., None]
    fx = (sx - x0)[..., None]
    return (
        image[y0, x0] * (1 - fy) * (1 - fx)
        + image[y0, x0 + 1] * (1 - fy) * fx
        + image[y0 + 1, x0] * fy * (1 - fx)
        + image[y0 + 1, x0 + 1] * fy * fx
    ).astype(np.float32)


def synthetic_flow_pairs(
    n: int, image_shape: Tuple[int, int, int], seed: int = 0, max_disp: float = 3.0
) -> Tuple[np.ndarray, np.ndarray]:
    """(frames (N, 2, H, W, C), flows (N, H, W, 2)) with frame2 consistent
    with the flow field — learnable signal for smoke training."""
    h, w, c = image_shape
    rng = np.random.default_rng(seed)
    frames = np.empty((n, 2, h, w, c), np.float32)
    flows = np.empty((n, h, w, 2), np.float32)
    for i in range(n):
        frame1 = _smooth_field(rng, h, w, c, 1.0)
        flow = np.clip(_smooth_field(rng, h, w, 2, max_disp), -max_disp, max_disp)
        frames[i, 0] = frame1
        frames[i, 1] = warp_backward(frame1, flow)
        flows[i] = flow
    return frames, flows


class FlowDataset:
    def __init__(self, frames: np.ndarray, flows: np.ndarray):
        assert len(frames) == len(flows)
        self.frames = frames
        self.flows = flows

    def __len__(self) -> int:
        return len(self.frames)

    def __getitem__(self, i: int) -> Tuple[np.ndarray, np.ndarray]:
        return self.frames[i], self.flows[i]


def _collate(batch: Sequence[Tuple[np.ndarray, np.ndarray]]) -> Dict[str, np.ndarray]:
    return {
        "frames": np.stack([f for f, _ in batch]),
        "flow": np.stack([g for _, g in batch]),
    }


def load_sintel(
    root: str, image_shape: Tuple[int, int, int], split: str = "clean"
) -> Tuple[np.ndarray, np.ndarray]:
    """Read MPI-Sintel frame pairs + ground-truth flow, center-cropped to
    ``image_shape``. Requires PIL (shipped with torchvision) for the PNGs."""
    from PIL import Image

    h, w, _ = image_shape
    frames_list: List[np.ndarray] = []
    flows_list: List[np.ndarray] = []
    scenes = sorted(glob.glob(os.path.join(root, "training", split, "*")))
    if not scenes:
        raise FileNotFoundError(
            f"no Sintel scenes under {root}/training/{split} — place the "
            "MPI-Sintel tree there, or use synthetic=True"
        )
    split_dir = os.path.join(root, "training", split)
    flow_dir = os.path.join(root, "training", "flow")
    for scene in scenes:
        pngs = sorted(glob.glob(os.path.join(scene, "frame_*.png")))
        for first, second in zip(pngs, pngs[1:]):
            # map <root>/training/<split>/<scene>/frame_X.png to the flow tree
            # by relative path, so a root that itself contains '/clean/' or
            # '/flow/' segments can't corrupt the substitution
            rel = os.path.relpath(first, split_dir)
            flo = os.path.join(flow_dir, rel[: -len(".png")] + ".flo")
            if not os.path.exists(flo):
                continue
            img1 = np.asarray(Image.open(first), np.float32) / 255.0
            img2 = np.asarray(Image.open(second), np.float32) / 255.0
            flow = read_flo(flo)
            ih, iw = img1.shape[:2]
            if ih < h or iw < w:
                continue
            top, left = (ih - h) // 2, (iw - w) // 2
            sl = np.s_[top : top + h, left : left + w]
            frames_list.append(np.stack([img1[sl], img2[sl]]))
            flows_list.append(flow[sl])
    if not frames_list:
        raise FileNotFoundError(
            f"no usable Sintel pairs under {split_dir}: every frame pair was "
            f"skipped (missing .flo under {flow_dir}, or source frames smaller "
            f"than the requested {h}x{w} crop)"
        )
    return np.stack(frames_list), np.stack(flows_list)


class FlowDataModule:
    """prepare/setup/loader surface matching the other data modules."""

    def __init__(
        self,
        root: str = ".cache",
        image_shape: Tuple[int, int, int] = (368, 496, 3),
        batch_size: int = 8,
        synthetic: bool = False,
        synthetic_size: int = 512,
        seed: int = 0,
        shard_id: int = 0,
        num_shards: int = 1,
    ):
        self.root = root
        self.image_shape = image_shape
        self.batch_size = batch_size
        self.synthetic = synthetic
        self.synthetic_size = synthetic_size
        self.seed = seed
        self.shard_id = shard_id
        self.num_shards = num_shards
        self.ds_train: Optional[FlowDataset] = None
        self.ds_valid: Optional[FlowDataset] = None

    def prepare_data(self):
        if not self.synthetic:
            sintel = os.path.join(self.root, "Sintel")
            if not os.path.isdir(os.path.join(sintel, "training")):
                raise FileNotFoundError(
                    f"no Sintel data under {sintel} — place the MPI-Sintel "
                    "tree there, or use synthetic=True"
                )

    def setup(self):
        if self.synthetic:
            frames, flows = synthetic_flow_pairs(
                self.synthetic_size, self.image_shape, seed=self.seed
            )
            val = max(self.synthetic_size // 8, 4)
        else:
            frames, flows = load_sintel(
                os.path.join(self.root, "Sintel"), self.image_shape
            )
            val = max(len(frames) // 10, 1)
        if len(frames) < 2:
            raise ValueError(
                f"need at least 2 flow pairs to split train/val, got {len(frames)}"
            )
        val = min(val, len(frames) - 1)  # keep the training set non-empty
        split = len(frames) - val
        self.ds_train = FlowDataset(frames[:split], flows[:split])
        self.ds_valid = FlowDataset(frames[split:], flows[split:])

    def train_dataloader(self) -> DataLoader:
        return DataLoader(
            self.ds_train, self.batch_size, _collate, shuffle=True,
            seed=self.seed, shard_id=self.shard_id, num_shards=self.num_shards,
        )

    def val_dataloader(self) -> DataLoader:
        return DataLoader(
            self.ds_valid, self.batch_size, _collate, shuffle=False,
            drop_last=self.num_shards > 1,
            shard_id=self.shard_id, num_shards=self.num_shards,
        )
