"""Fill-mask inference: the reference's ``predict_samples`` path
(``train/train_mlm.py:14-35``) promoted from a training-loop logging hook to a
standalone serving API, checkpoint-loadable.
"""

from __future__ import annotations

from types import SimpleNamespace
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np

from perceiver_io_tpu.data.tokenizer import MASK_TOKEN, PAD_TOKEN, WordPieceTokenizer
from perceiver_io_tpu.inference.predictor import Predictor, bucket_size

Array = jax.Array


def masked_token_ids(tokenizer: WordPieceTokenizer, text: str) -> List[int]:
    """Token ids for one raw string containing the ``[MASK]`` literal,
    splicing in the mask token id (the tokenizer treats specials as plain
    text). Natural length — no padding or truncation; callers pick a width
    (the serving engine buckets on ``len()`` so each text tokenizes ONCE)."""
    mask_id = tokenizer.token_to_id(MASK_TOKEN)
    ids: List[int] = []
    for i, piece in enumerate(text.split(MASK_TOKEN)):
        if i > 0:
            ids.append(mask_id)
        if piece.strip():
            ids.extend(tokenizer.encode_ids(piece))
    return ids


def pad_token_rows(
    rows: Sequence[Sequence[int]], width: int, pad_id: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Rows of ids → ``(token_ids, pad_mask)`` at fixed ``width`` (rows
    longer than ``width`` truncate)."""
    token_ids = np.full((len(rows), width), pad_id, dtype=np.int32)
    for i, ids in enumerate(rows):
        token_ids[i, : min(len(ids), width)] = ids[:width]
    return token_ids, token_ids == pad_id


def encode_masked_texts(
    tokenizer: WordPieceTokenizer, texts: Sequence[str], max_seq_len: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Encode raw strings containing the ``[MASK]`` literal. Returns
    ``(token_ids, pad_mask)`` at fixed width ``max_seq_len``."""
    pad_id = tokenizer.token_to_id(PAD_TOKEN)
    rows = [masked_token_ids(tokenizer, text) for text in texts]
    return pad_token_rows(rows, max_seq_len, pad_id)


def load_mlm_checkpoint(
    checkpoint_dir: str,
    tokenizer: WordPieceTokenizer,
    step: Optional[int] = None,
    dtype: Optional[str] = None,
):
    """Rebuild a ``PerceiverMLM`` from the hparams embedded in a checkpoint
    and restore its best/chosen step. Returns ``(model, params, max_seq_len)``
    — the shared loading path of :class:`MLMPredictor` and the serving
    engine's ``cli/serve.py``.

    ``dtype`` overrides the COMPUTE dtype of the rebuilt model (e.g.
    ``'bfloat16'`` for the bf16 serving path); None keeps the checkpoint's
    recorded dtype or the float32 golden-parity default.
    """
    from perceiver_io_tpu.cli import common
    from perceiver_io_tpu.training.checkpoint import load_hparams, restore_params

    hparams = load_hparams(checkpoint_dir)
    # Framework-only knobs absent from older / imported-reference
    # checkpoints (a torch .ckpt's hparams carry only the reference's
    # argparse surface); the checkpoint's own values override. dtype is
    # DELIBERATELY float32 (not the CLI's bf16 training default):
    # imported weights come from an f32 torch model and f32 is the
    # golden-parity inference path.
    defaults = {
        "dtype": "float32", "attn_impl": "auto", "remat": False,
        "dropout": 0.0,
    }
    args = SimpleNamespace(**{**defaults, **hparams})
    if dtype is not None:
        args.dtype = dtype
    vocab_size = tokenizer.get_vocab_size()
    max_seq_len = hparams["max_seq_len"]
    model = common.build_mlm(args, vocab_size, max_seq_len)

    ids = np.zeros((1, max_seq_len), np.int32)
    pad = np.zeros((1, max_seq_len), bool)
    like = jax.eval_shape(
        lambda: model.init(
            {"params": jax.random.key(0), "masking": jax.random.key(1)},
            ids, pad,
        )
    )["params"]
    params = restore_params(checkpoint_dir, like, step=step)
    return model, params, max_seq_len


class MLMPredictor:
    """Top-k fill-mask predictions from a ``PerceiverMLM`` + tokenizer."""

    def __init__(
        self,
        model,
        params,
        tokenizer: WordPieceTokenizer,
        max_seq_len: int,
        max_batch: int = 64,
    ):
        self.model = model
        self.tokenizer = tokenizer
        self.max_seq_len = max_seq_len
        self.mask_id = tokenizer.token_to_id(MASK_TOKEN)
        self._predictor = Predictor.for_model(
            model, params, max_batch=max_batch, masking=False
        )

        # gathered decode: logits at explicit positions only — (B, K, vocab)
        # instead of (B, L, vocab), which at long L is a GB-scale tensor for
        # a handful of [MASK] slots. K is bucketed to powers of two by the
        # caller, so each (batch-bucket, K-bucket) pair compiles once.
        def gathered_apply(p, token_ids, pad_mask, positions):
            return model.apply(
                {"params": p}, token_ids, pad_mask, masking=False,
                deterministic=True, positions=positions,
            )

        self._gathered = Predictor(gathered_apply, params, max_batch=max_batch)

    @classmethod
    def from_checkpoint(
        cls,
        checkpoint_dir: str,
        tokenizer: WordPieceTokenizer,
        step: Optional[int] = None,
        max_batch: int = 64,
    ) -> "MLMPredictor":
        """Rebuild the model from the hparams embedded in the checkpoint
        (``save_hyperparameters`` parity) and restore its best/chosen step."""
        model, params, max_seq_len = load_mlm_checkpoint(
            checkpoint_dir, tokenizer, step=step
        )
        return cls(model, params, tokenizer, max_seq_len, max_batch=max_batch)

    def logits(self, texts: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
        """(logits (B, L, vocab), token_ids (B, L)) for raw masked texts."""
        token_ids, pad_mask = encode_masked_texts(
            self.tokenizer, texts, self.max_seq_len
        )
        logits, _ = self._predictor(token_ids, pad_mask)
        return np.asarray(logits, np.float32), token_ids

    def fill_masks(self, texts: Sequence[str], k: int = 5) -> List[List[List[str]]]:
        """Per text, per ``[MASK]`` occurrence (in order), the top-k predicted
        tokens (reference ``train_mlm.py:24-35`` semantics).

        Decodes ONLY the mask positions (the decoder's gathered decode —
        each output query attends to the latents independently, so these are
        exactly the corresponding rows of the full decode): the device never
        builds the (B, L, vocab) logits tensor, which at long L dwarfs the
        handful of positions actually needed. The position count is bucketed
        to powers of two so compiles stay bounded."""
        token_ids, pad_mask = encode_masked_texts(
            self.tokenizer, texts, self.max_seq_len
        )
        mask_pos = [np.nonzero(row == self.mask_id)[0] for row in token_ids]
        n_max = max((len(p) for p in mask_pos), default=0)
        if n_max == 0:
            return [[] for _ in texts]
        cap = bucket_size(n_max, self.max_seq_len)  # cap >= n_max always
        # filler slots repeat position 0; their logits are never read
        positions = np.zeros((len(texts), cap), np.int32)
        for row, pos in enumerate(mask_pos):
            positions[row, : len(pos)] = pos
        logits, _ = self._gathered(token_ids, pad_mask, positions)
        logits = np.asarray(logits, np.float32)
        out: List[List[List[str]]] = []
        for row, pos in enumerate(mask_pos):
            row_preds = []
            for slot in range(len(pos)):
                top = np.argsort(-logits[row, slot])[:k]
                row_preds.append([self.tokenizer.id_to_token(int(t)) for t in top])
            out.append(row_preds)
        return out
