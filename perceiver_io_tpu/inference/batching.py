"""Continuous batching for Perceiver-AR decode: a slotted cache arena plus
ONE batched step dispatch covering every active stream.

r18's :class:`~perceiver_io_tpu.inference.generate.ARGenerator` is correct
but serves each session on its own dispatch chain: at any concurrency the
chip runs batch-1 matmuls over the full weight stream per token, and the
serving roofline (PERF.md) says that path is HBM-WEIGHT-bound — the weights
are read once per step regardless of how many streams want a token. This
module amortizes that read:

- **slotted cache arena** (:class:`ContinuousBatcher` internals): the
  per-session fixed-capacity cache rings are pooled into ONE donated device
  buffer per episode width, leading axis = slot = session. Install is a
  ``dynamic_update_slice`` of a prefilled ring into its slot; retirement is
  free (the slot is simply re-labeled resident/free — nothing round-trips).
- **one batched step dispatch**: every active slot advances through a
  single ``lax.fori_loop`` chunk whose body is the *vmapped* per-session
  ``PerceiverARLM.step`` — the same module method the per-session engine
  chains, so incremental-vs-dense parity carries over unchanged. Per-slot
  ``steps_left`` masks exhausted/idle/free slots with ``where`` selects:
  inactive slots pass through bit-identically and cost no correctness.
- **continuous scheduling**: sessions are admitted and retired at CHUNK
  boundaries without breaking the running dispatch chain — a dedicated
  dispatcher thread owns the arena, caller threads enqueue streams and
  drain their own token queues (delivery stays on the caller's thread, so
  one slow consumer cannot stall the batch).
- **finite program family**: prefill widths already live on the fixed
  episode grid; arena capacities are power-of-two-bucketed; and per-slot
  sampling params (temperature/top_k/seed) are TRACED operands, so one
  decode program per (width, slots) serves every chunk fill, every partial
  budget, and every sampling shape — strictly smaller than the per-session
  chunk×sampling family, and AOT-warmable through the r10
  :class:`~perceiver_io_tpu.aot.ExecutableCache`.

Determinism contract: the position-folded sampling keys are reproduced
EXACTLY (``sample_logits_rows`` is value-identical to the per-session
``sample_logits`` — pinned by tests), so a stream decoded through the arena,
through a per-session chain, or re-encoded on another replica after a
mid-stream kill produces the identical token sequence — the r18 chaos
contract (``lost_accepted=0`` by content) is preserved verbatim.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from queue import SimpleQueue
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.inference.generate import (
    ARGenerator,
    SamplingConfig,
)
from perceiver_io_tpu.resilience import faults


def sample_logits_rows(logits, keys, temperature, top_k):
    """Per-row, fully-traced twin of :func:`generate.sample_logits`: one
    compiled program serves EVERY (temperature, top_k, greedy) combination
    — the per-slot sampling params ride as operands, never as program
    statics. Value-identical to the per-session path row by row (same
    greedy argmax over raw f32 logits, same ``max(t, 1e-6)`` scaling, same
    k-th-largest threshold mask, same ``jax.random.categorical`` draw from
    the same position-folded key), which is what lets a stream cross
    between the arena and a per-session chain without a token of drift."""
    import jax
    import jax.numpy as jnp

    logits = logits.astype(jnp.float32)
    vocab = logits.shape[-1]
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    # k-th largest per row with TRACED k: descending sort + gather equals
    # lax.top_k(x, k)[0][..., -1] for every k (the value is order-stable
    # under ties), without k shaping the program
    sorted_desc = -jnp.sort(-scaled, axis=-1)
    k_idx = jnp.clip(top_k - 1, 0, vocab - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)
    use_topk = ((top_k > 0) & (top_k < vocab))[:, None]
    masked = jnp.where(use_topk & (scaled < kth),
                       jnp.finfo(jnp.float32).min, scaled)
    sampled = jax.vmap(jax.random.categorical)(keys, masked)
    return jnp.where(temperature == 0.0, greedy_tok,
                     sampled.astype(jnp.int32))


class ArenaSession:
    """Host handle for a RESIDENT arena continuation: the accepted sequence
    plus a (width, slot, epoch) claim on the rings that encode it. The
    epoch is the staleness check — the arena bumps it whenever the slot is
    reclaimed or adopted, so a stored session whose slot moved on simply
    re-encodes from its prefix (the same spill path a dead replica takes).
    Duck-typed to :class:`generate.GenSession` where the session store and
    replica care (``seq``/``width``/``seed``/``remaining``)."""

    __slots__ = ("seq", "width", "seed", "steps", "slot", "epoch")

    def __init__(self, seq: List[int], width: int, seed: int, steps: int,
                 slot: int, epoch: int):
        self.seq = seq
        self.width = width
        self.seed = seed
        self.steps = steps
        self.slot = slot
        self.epoch = epoch

    def remaining(self) -> int:
        return self.width - len(self.seq)


_FREE, _ACTIVE, _RESIDENT = "free", "active", "resident"


class _Slot:
    __slots__ = ("state", "epoch", "stream", "last")

    def __init__(self):
        self.state = _FREE
        self.epoch = 0
        self.stream = None          # the _Stream while _ACTIVE
        self.last = 0.0             # LRU stamp for resident reclamation


class _Arena:
    """One episode width's pooled rings: the device buffer (leading axis =
    slot) plus the host slot table and the per-slot sampling operands.
    Touched ONLY by the dispatcher thread (device halves) or under the
    batcher's lock (host halves)."""

    __slots__ = ("width", "n_slots", "buf", "slots", "temp", "top_k",
                 "seeds")

    def __init__(self, width: int, n_slots: int, buf):
        self.width = width
        self.n_slots = n_slots
        self.buf = buf
        self.slots = [_Slot() for _ in range(n_slots)]
        self.temp = np.zeros((n_slots,), np.float32)
        self.top_k = np.zeros((n_slots,), np.int32)
        self.seeds = np.zeros((n_slots,), np.int32)


class _Stream:
    """One in-flight continuation: the dispatcher-side authoritative state
    (tokens produced, current placement) and the caller-side event queue
    (token chunks, then done/error) the ``generate()`` thread drains."""

    __slots__ = ("prefix", "max_new", "sampling", "adopt", "q", "tokens",
                 "width", "slot", "placed", "cancelled", "session_out",
                 "t_start", "wants_chunks", "t_queued", "t_bind",
                 "t_install", "t_first", "t_prev", "ctx")

    def __init__(self, prefix: List[int], max_new: int,
                 sampling: SamplingConfig, adopt: Optional[ArenaSession],
                 wants_chunks: bool = True, ctx=None):
        self.prefix = prefix
        self.max_new = max_new
        self.sampling = sampling
        self.adopt = adopt          # a valid resident session to resume
        self.q: "SimpleQueue" = SimpleQueue()
        self.tokens: List[int] = []  # dispatcher-authoritative
        self.width = 0
        self.slot = -1
        self.placed = False
        self.cancelled = False
        self.session_out: Optional[ArenaSession] = None
        self.t_start = time.monotonic()
        # lifecycle stamps (all monotonic): enqueue -> slot bind ->
        # prefill install -> first token -> per-chunk. t_queued resets at
        # every re-placement (episode boundary), so queue-wait observations
        # measure each wait, not the stream's whole life.
        self.t_queued = self.t_start
        self.t_bind = 0.0
        self.t_install = 0.0
        self.t_first: Optional[float] = None
        self.t_prev = self.t_start
        self.ctx = ctx              # per-stream TraceContext (or None)
        # no on_chunk consumer -> skip per-chunk queue events entirely; the
        # done event carries the full token list. On a shared-core host the
        # per-round caller wakeups are pure context-switch overhead.
        self.wants_chunks = wants_chunks

    def cur_len(self) -> int:
        return len(self.prefix) + len(self.tokens)


# admission waves bucket to powers of two up to this many prefills per
# dispatch — with the episode-grid widths this closes the prefill/install
# program family at (widths × 4 buckets)
_MAX_PREFILL_ROWS = 8


def _round_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


#: every reason an arena slot can sit idle for a scheduler round — the
#: closed cause vocabulary the flight recorder attributes with (the
#: acceptance bar: >=95% of idle slot-rounds carry one of these).
FLIGHT_CAUSES = ("no_pending", "width_mismatch", "arena_full", "draining")


def parse_flight_row(row: str) -> Dict[str, Any]:
    """Decode one packed flight-recorder row (the single definition of the
    row grammar — ``tools/decode_flight.py`` and the drill tests parse
    through here). Row kinds:

    - ``R|seq|t_ms|pending|admits|retires|W:slots:active:resident:c=n+c=n,…``
      — one scheduler round: queue depth after admission, admit/retire
      deltas, and per-arena occupancy with idle-slot cause attribution;
    - ``E|t_ms|reason|width|slot|steps`` — a resident eviction / stream
      kill freeing a slot (the kill-drill needle);
    - ``G|t_ms|width|slots`` — arena growth (doubling commit).
    """
    parts = row.split("|")
    kind = parts[0]
    if kind == "R":
        arenas = []
        if len(parts) > 6 and parts[6]:
            for blob in parts[6].split(","):
                w, n, act, res, causes_s = blob.split(":")
                causes = {}
                if causes_s:
                    for kv in causes_s.split("+"):
                        c, cnt = kv.split("=")
                        causes[c] = int(cnt)
                arenas.append({"width": int(w), "slots": int(n),
                               "active": int(act), "resident": int(res),
                               "causes": causes})
        return {"kind": "round", "seq": int(parts[1]),
                "t_ms": float(parts[2]), "pending": int(parts[3]),
                "admits": int(parts[4]), "retires": int(parts[5]),
                "arenas": arenas}
    if kind == "E":
        return {"kind": "evict", "t_ms": float(parts[1]),
                "reason": parts[2], "width": int(parts[3]),
                "slot": int(parts[4]), "steps": int(parts[5])}
    if kind == "G":
        return {"kind": "grow", "t_ms": float(parts[1]),
                "width": int(parts[2]), "slots": int(parts[3])}
    raise ValueError(f"unknown flight row kind {kind!r}")


class DecodeFlightRecorder:
    """Bounded ring of per-round scheduler decisions — the decode
    scheduler's black box. Each round the dispatcher records queue depth,
    admit/retire deltas, and per-arena occupancy with every idle slot
    attributed to a cause from :data:`FLIGHT_CAUSES`; evictions and arena
    growth land as their own rows. Rows are packed strings (one grammar,
    :func:`parse_flight_row`) so the ring costs bytes, not dicts.

    Spooling rides the ``request_phases_batch`` precedent: every
    ``spool_every`` rows one ``decode_flight_batch`` event carries the
    batch to the async event log (serialization amortized; nothing blocks
    the dispatcher). ``dump(reason)`` emits the ring tail as ONE
    ``decode_flight_dump`` event — the watchdog-stall / SIGTERM hook.
    """

    # pitlint PIT-LOCK: the ring is appended by the dispatcher but evict
    # rows arrive from RPC caller threads (session-store callbacks) and
    # stats/statz pollers read the aggregates — only under _lock.
    _guarded_by = {"_ring": "_lock", "_agg": "_lock", "_unspooled": "_lock"}

    def __init__(self, engine: str, capacity: int = 512,
                 spool_every: int = 64):
        self.engine = engine
        self.spool_every = spool_every
        self._lock = threading.Lock()
        self._ring: "deque[str]" = deque(maxlen=capacity)
        self._unspooled: List[str] = []
        self._seq = 0
        self._last = {"admits": 0, "retires": 0}
        self._agg = {
            "rounds": 0, "slot_rounds": 0, "idle_slot_rounds": 0,
            "attributed": 0, "causes": {c: 0 for c in FLIGHT_CAUSES},
            "evicts": {}, "grows": 0, "pending_max": 0,
        }

    def _push_locked(self, row: str) -> Optional[List[str]]:
        self._ring.append(row)
        self._unspooled.append(row)
        if len(self._unspooled) >= self.spool_every:
            batch, self._unspooled = self._unspooled, []
            return batch
        return None

    def _emit(self, batch: Optional[List[str]]) -> None:
        if batch:
            obs.event("decode_flight_batch", engine=self.engine,
                      n=len(batch), parts=";".join(batch))

    def record_round(self, pending: int, admitted: int, retired: int,
                     arenas: List[Tuple[int, int, int, int,
                                        Dict[str, int]]]) -> None:
        """One scheduler round, post-admission. ``arenas`` rows are
        ``(width, slots, active, resident, causes)`` with ``causes``
        attributing that arena's idle slots."""
        blobs = []
        for w, n, act, res, causes in arenas:
            causes_s = "+".join(f"{c}={k}" for c, k in sorted(causes.items()))
            blobs.append(f"{w}:{n}:{act}:{res}:{causes_s}")
        with self._lock:
            admits = admitted - self._last["admits"]
            retires = retired - self._last["retires"]
            self._last = {"admits": admitted, "retires": retired}
            self._seq += 1
            row = (f"R|{self._seq}|{time.monotonic() * 1e3:.1f}|{pending}"
                   f"|{admits}|{retires}|{','.join(blobs)}")
            agg = self._agg
            agg["rounds"] += 1
            agg["pending_max"] = max(agg["pending_max"], pending)
            for w, n, act, res, causes in arenas:
                agg["slot_rounds"] += n
                idle = n - act
                agg["idle_slot_rounds"] += idle
                for c, k in causes.items():
                    agg["causes"][c] = agg["causes"].get(c, 0) + k
                    agg["attributed"] += k
            batch = self._push_locked(row)
        self._emit(batch)

    def record_evict(self, reason: str, width: int, slot: int,
                     steps: int) -> None:
        with self._lock:
            self._agg["evicts"][reason] = (
                self._agg["evicts"].get(reason, 0) + 1)
            batch = self._push_locked(
                f"E|{time.monotonic() * 1e3:.1f}|{reason}|{width}|{slot}"
                f"|{steps}")
        self._emit(batch)

    def record_grow(self, width: int, slots: int) -> None:
        with self._lock:
            self._agg["grows"] += 1
            batch = self._push_locked(
                f"G|{time.monotonic() * 1e3:.1f}|{width}|{slots}")
        self._emit(batch)

    def tail(self, n: int = 64) -> List[str]:
        with self._lock:
            rows = list(self._ring)
        return rows[-n:]

    def summary(self) -> Dict[str, Any]:
        """Cumulative attribution aggregates (rides ``stats()`` /statz)."""
        with self._lock:
            agg = {**self._agg, "causes": dict(self._agg["causes"]),
                   "evicts": dict(self._agg["evicts"])}
        idle = agg["idle_slot_rounds"]
        agg["attribution_frac"] = (
            round(agg["attributed"] / idle, 4) if idle else 1.0)
        return agg

    def flush(self) -> None:
        """Spool any unbatched rows now (close/test determinism)."""
        with self._lock:
            batch, self._unspooled = self._unspooled, []
        self._emit(batch)

    def dump(self, reason: str, n: int = 128) -> Dict[str, Any]:
        """Emit the ring tail + aggregates as one ``decode_flight_dump``
        event (watchdog stall, SIGTERM) and return the same payload."""
        rows = self.tail(n)
        payload = {"engine": self.engine, "reason": reason,
                   "summary": self.summary(), "rows": rows}
        obs.event("decode_flight_dump", engine=self.engine, reason=reason,
                  n=len(rows), parts=";".join(rows))
        return payload


class ContinuousBatcher(ARGenerator):
    """Continuous-batching decode engine over one ``PerceiverARLM`` — the
    drop-in replacement for :class:`ARGenerator` wherever a replica serves
    concurrent streams. Same ``generate(prefix, max_new, sampling,
    on_chunk=..., session=...)`` surface, same streamed-chunk callbacks,
    same episode/width planning (inherited), same token streams (pinned);
    the difference is purely WHO runs the steps: a dispatcher thread packs
    every active stream's next chunk into one batched dispatch per arena.

    ``slots`` is the initial arena capacity per episode width
    (power-of-two-bucketed); arenas grow by doubling up to ``max_slots``
    when admissions outrun retirements, each growth step a new warmable
    (width, slots) program. A full arena queues admissions at the chunk
    boundary — open-loop honesty lives in the serving tier's admission
    control, not here.
    """

    # pitlint PIT-LOCK: the slot tables, admission queue, and dispatch
    # aggregates are shared between RPC caller threads and the dispatcher —
    # only under the condition's lock. Device buffers (arena.buf) are
    # dispatcher-owned and never touched by callers.
    _guarded_by = {"_arenas": "_cv", "_pending": "_cv", "_stats": "_cv"}
    _assumes_locked = ("_has_work", "_claim_slot", "_retire_slot",
                       "_bind_slot")

    def __init__(
        self,
        model,
        params,
        max_seq_len: int,
        chunk: int = 8,
        slots: int = 8,
        max_slots: int = 64,
        compute_dtype: Optional[str] = None,
        quantize: Optional[str] = None,
        group_size: Optional[int] = None,
        name: str = "generate",
        registry: Optional[obs.MetricsRegistry] = None,
        compile_cache: Optional[str] = None,
        heartbeat_deadline_s: Optional[float] = None,
    ):
        import jax
        import jax.numpy as jnp

        from perceiver_io_tpu.quant import apply_operands

        super().__init__(model, params, max_seq_len, chunk=chunk,
                         compute_dtype=compute_dtype, quantize=quantize,
                         group_size=group_size, name=name,
                         registry=registry)
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.slots = _round_pow2(slots)
        self.max_slots = max(_round_pow2(max_slots), self.slots)
        self._cv = threading.Condition()
        self._arenas: Dict[int, _Arena] = {}
        self._pending: "deque[_Stream]" = deque()
        self._stats = {"dispatches": 0, "steps": 0, "fill_sum": 0.0,
                       "admitted": 0, "retired": 0}
        self._closed = threading.Event()
        self.flight = DecodeFlightRecorder(name)
        # the dispatcher's watchdog: a wedged round (device hang, tunnel
        # stall) dumps the flight-recorder tail with the thread stacks —
        # the "why was my stream stuck" evidence. None = no monitor.
        self._hb = obs.Heartbeat(
            f"{name}-arena-dispatch", deadline_s=heartbeat_deadline_s,
            diagnostics=lambda: {"flight": self.flight.summary(),
                                 "flight_tail": self.flight.tail(16)},
            on_stall=lambda: self.flight.dump("watchdog_stall"))

        reg = registry if registry is not None else obs.get_registry()
        labels = {"engine": name, "task": "generate"}
        self._m_occupancy = reg.gauge(
            "ar_decode_slot_occupancy",
            "active arena slots at the last batched dispatch (the decode "
            "batch fill the weight stream amortizes over)", labels)
        self._m_slots_total = reg.gauge(
            "ar_decode_slots", "allocated arena slots across widths", labels)
        self._m_admitted = reg.counter(
            "ar_arena_admitted_total",
            "streams admitted into an arena slot (prefill-install or "
            "resident-adopt)", labels)
        self._m_retired = reg.counter(
            "ar_arena_retired_total",
            "streams retired from their slot at a chunk boundary", labels)
        self._m_steps_per_dispatch = reg.histogram(
            "ar_decode_steps_per_dispatch",
            "decode steps advanced by one batched dispatch (sum over "
            "active slots)", labels)
        self._m_queue = reg.gauge(
            "ar_arena_admission_queue",
            "streams waiting for a slot at the next chunk boundary", labels)

        # -- the batched device programs (managed Compiled table: the
        # dispatch calls executables directly, so warmup/AOT and the live
        # path share exactly one build per (width, slots)) ------------------
        donate_decode = (1,) if jax.default_backend() == "tpu" else ()
        donate_install = (0,) if jax.default_backend() == "tpu" else ()

        def step_one(p, cache, token):
            # re-batch one slot to the (B=1, ...) shapes PerceiverARLM.step
            # was written for; vmap strips/restores the slot axis. The ring
            # length is the one SCALAR leaf (no batch axis in the session
            # cache — step's dynamic-slice indices need it 0-d), so it
            # passes through unbatched both ways.
            cache1 = {k: (v if k == "len"
                          else jax.tree.map(lambda x: x[None], v))
                      for k, v in cache.items()}
            logits, new = model.apply({"params": p}, cache1,
                                      token[None, None], method="step")
            new = {k: (v if k == "len"
                       else jax.tree.map(lambda x: x[0], v))
                   for k, v in new.items()}
            return logits[0].astype(jnp.float32), new

        def arena_decode_fn(p, buf, temperature, top_k, seeds, steps_left):
            n_slots = steps_left.shape[0]
            # quantized tree -> QKernel operands ONCE per dispatch; the
            # vmapped per-slot steps then share one int-byte weight stream —
            # batched decode over quantized weights is exactly the
            # compounding play (weight stream ~= the whole decode bill)
            p = apply_operands(p)

            def body(i, carry):
                buf_c, out = carry
                cache, logits = buf_c["cache"], buf_c["logits"]
                active = i < steps_left                       # (S,)
                pos = cache["len"]                            # (S,)
                keys = jax.vmap(
                    lambda sd, q: jax.random.fold_in(jax.random.key(sd), q)
                )(seeds, pos)
                tok = sample_logits_rows(logits, keys, temperature, top_k)
                new_logits, new_cache = jax.vmap(
                    step_one, in_axes=(None, 0, 0))(p, cache, tok)

                def sel(new, old):
                    mask = jnp.reshape(
                        active, (n_slots,) + (1,) * (new.ndim - 1))
                    return jnp.where(mask, new, old)

                out = out.at[:, i].set(jnp.where(active, tok, -1))
                return ({"cache": jax.tree.map(sel, new_cache, cache),
                         "logits": jnp.where(active[:, None], new_logits,
                                             logits)},
                        out)

            out0 = jnp.full((n_slots, self.chunk), -1, jnp.int32)
            return jax.lax.fori_loop(0, self.chunk, body, (buf, out0))

        def arena_install_fn(buf, cache, logits, slot):
            def put(b, c):
                val = jnp.reshape(c, (1,) + b.shape[1:]).astype(b.dtype)
                return jax.lax.dynamic_update_slice(
                    b, val, (slot,) + (0,) * (b.ndim - 1))

            return {
                "cache": jax.tree.map(put, buf["cache"], cache),
                "logits": jax.lax.dynamic_update_slice(
                    buf["logits"], logits.astype(buf["logits"].dtype),
                    (slot, 0)),
            }

        prefill_raw = self._prefill.__wrapped__  # unjitted, vmap-able

        def prefill_rows_fn(p, ids, pad, lengths):
            # one admission wave: (K, W) prompts with per-row true lengths
            # -> per-row next-token logits (K, 1, vocab) and session cache
            # leaves stacked on a leading K axis ((K,) for the scalar ring
            # length). ONE dispatch encodes the whole wave — on every
            # backend the K prompts share the weight stream the way the
            # decode arena shares it across slots.
            return jax.vmap(
                lambda i, m, le: prefill_raw(p, i[None], m[None], le),
                in_axes=(0, 0, 0))(ids, pad, lengths)

        def arena_install_rows_fn(buf, bcache, blogits, slots):
            # row-scatter a whole admission wave into the arena: K
            # (dynamic_update_slice) writes in ONE program instead of K
            # install dispatches. Pad rows repeat a real row's
            # (slot, content) pair — an idempotent duplicate write.
            def put(b, c, slot):
                val = jnp.reshape(c, (1,) + b.shape[1:]).astype(b.dtype)
                return jax.lax.dynamic_update_slice(
                    b, val, (slot,) + (0,) * (b.ndim - 1))

            for k in range(blogits.shape[0]):
                row = jax.tree.map(lambda x: x[k], bcache)
                buf = {
                    "cache": jax.tree.map(
                        lambda b, c: put(b, c, slots[k]),
                        buf["cache"], row),
                    "logits": jax.lax.dynamic_update_slice(
                        buf["logits"],
                        blogits[k].astype(buf["logits"].dtype),
                        (slots[k], 0)),
                }
            return buf

        self._jit_decode = jax.jit(arena_decode_fn,
                                   donate_argnums=donate_decode)
        self._jit_install = jax.jit(arena_install_fn,
                                    donate_argnums=donate_install)
        self._jit_prefill_rows = jax.jit(prefill_rows_fn)
        self._jit_install_rows = jax.jit(arena_install_rows_fn,
                                         donate_argnums=donate_install)
        self._prog_lock = threading.Lock()
        self._programs: Dict[Tuple[str, int, int], Any] = {}
        self._exec_cache = None
        self._fp_base: Optional[Dict[str, Any]] = None
        if compile_cache:
            from perceiver_io_tpu.aot import ExecutableCache

            self._exec_cache = ExecutableCache.open(compile_cache,
                                                    registry=reg)
        self._thread = threading.Thread(
            target=self._loop, name=f"{name}-arena-dispatch", daemon=True)
        self._thread.start()

    # -- program table -------------------------------------------------------

    def _program(self, kind: str, width: int, n_slots: int, example_args):
        """The compiled executable for one (kind, width, slots) point —
        from memory, the AOT disk cache, or a fresh lower+compile (then
        persisted). The whole batched family is closed and warmable: one
        decode + one install program per (width, slots bucket)."""
        import jax

        key = (kind, width, n_slots)
        with self._prog_lock:
            compiled = self._programs.get(key)
            if compiled is not None:
                return compiled
            jitted = (self._jit_decode if kind == "decode"
                      else self._jit_prefill_rows if kind == "prefill"
                      else self._jit_install_rows
                      if kind.startswith("install_rows")
                      else self._jit_install)
            if self._exec_cache is not None:
                from perceiver_io_tpu.aot import compile_via_cache

                compiled = compile_via_cache(
                    jitted, example_args, self._exec_cache,
                    self._fingerprint_base(),
                    extra=(kind, str(width), str(n_slots)))
            else:
                avals = jax.tree.map(
                    lambda x: jax.ShapeDtypeStruct(
                        np.shape(x), x.dtype,
                        sharding=getattr(x, "sharding", None)),
                    tuple(example_args))
                compiled = jitted.lower(*avals).compile()
            self._programs[key] = compiled
            return compiled

    def _fingerprint_base(self) -> Dict[str, Any]:
        if self._fp_base is None:
            from perceiver_io_tpu.aot import (
                callable_sources,
                environment_fingerprint,
            )

            base = dict(environment_fingerprint())
            base.update(chunk=self.chunk,
                        quantize=str(self.quantize),
                        group_size=str(self.group_size),
                        sources=tuple(callable_sources(self.model.apply)))
            self._fp_base = base
        return self._fp_base

    # -- arena allocation ----------------------------------------------------

    def _arena_zeros(self, width: int, n_slots: int):
        """Allocate a width's pooled buffer from eval_shape avals — no
        device prefill needed to learn the ring geometry."""
        import jax
        import jax.numpy as jnp

        ids = jax.ShapeDtypeStruct((1, width), jnp.int32)
        pad = jax.ShapeDtypeStruct((1, width), jnp.bool_)
        length = jax.ShapeDtypeStruct((), jnp.int32)
        logits_s, cache_s = jax.eval_shape(
            self._prefill, self.params, ids, pad, length)

        def z(s):
            return jnp.zeros((n_slots,) + tuple(s.shape[1:]), s.dtype)

        return {"cache": jax.tree.map(z, cache_s),
                "logits": jnp.zeros((n_slots,) + tuple(logits_s.shape[1:]),
                                    jnp.float32)}

    def _ensure_arena(self, width: int) -> _Arena:
        with self._cv:
            arena = self._arenas.get(width)
        if arena is not None:
            return arena
        buf = self._arena_zeros(width, self.slots)
        fresh = _Arena(width, self.slots, buf)
        with self._cv:
            arena = self._arenas.setdefault(width, fresh)
            self._m_slots_total.set(
                sum(a.n_slots for a in self._arenas.values()))
        return arena

    def _grow(self, arena: _Arena) -> bool:
        """Double the arena (power-of-two bucket) up to ``max_slots``.
        Dispatcher-thread only: the buffer is rebuilt outside the lock, the
        slot table commit is inside it."""
        import jax
        import jax.numpy as jnp

        if arena.n_slots >= self.max_slots:
            return False
        new_n = min(arena.n_slots * 2, self.max_slots)
        pad_n = new_n - arena.n_slots
        new_buf = jax.tree.map(
            lambda x: jnp.concatenate(
                [x, jnp.zeros((pad_n,) + tuple(x.shape[1:]), x.dtype)]),
            arena.buf)
        with self._cv:
            arena.buf = new_buf
            arena.n_slots = new_n
            arena.slots.extend(_Slot() for _ in range(pad_n))
            arena.temp = np.concatenate(
                [arena.temp, np.zeros((pad_n,), np.float32)])
            arena.top_k = np.concatenate(
                [arena.top_k, np.zeros((pad_n,), np.int32)])
            arena.seeds = np.concatenate(
                [arena.seeds, np.zeros((pad_n,), np.int32)])
            self._m_slots_total.set(
                sum(a.n_slots for a in self._arenas.values()))
        obs.event("arena_grow", engine=self.name, width=arena.width,
                  slots=new_n)
        self.flight.record_grow(arena.width, new_n)
        return True

    # -- slot lifecycle (all under self._cv — see _guarded_by) ---------------

    def _claim_slot(self, arena: _Arena) -> Optional[int]:
        for i, s in enumerate(arena.slots):
            if s.state == _FREE:
                s.epoch += 1
                return i
        # reclaim the least-recently-used resident (its session re-encodes
        # on return — the standing spill path, exercised constantly)
        lru, lru_t = None, None
        for i, s in enumerate(arena.slots):
            if s.state == _RESIDENT and (lru_t is None or s.last < lru_t):
                lru, lru_t = i, s.last
        if lru is None:
            return None
        s = arena.slots[lru]
        s.state = _FREE
        s.epoch += 1
        s.stream = None
        return lru

    def _bind_slot(self, arena: _Arena, slot: int, st: _Stream) -> None:
        s = arena.slots[slot]
        s.state = _ACTIVE
        s.epoch += 1           # stale out any stored handle to this slot
        s.stream = st
        s.last = time.monotonic()
        st.t_bind = s.last
        self._m_queue_wait_s.observe(
            s.last - st.t_queued,
            exemplar=st.ctx.trace_id if st.ctx is not None else None)
        arena.temp[slot] = st.sampling.temperature
        arena.top_k[slot] = st.sampling.top_k
        arena.seeds[slot] = st.sampling.seed
        st.width = arena.width
        st.slot = slot
        st.placed = True
        self._stats["admitted"] += 1

    def _retire_slot(self, arena: _Arena, slot: int,
                     resident: bool) -> None:
        s = arena.slots[slot]
        s.stream = None
        s.state = _RESIDENT if resident else _FREE
        if not resident:
            s.epoch += 1
        s.last = time.monotonic()
        self._stats["retired"] += 1

    def release_session(self, session, reason: str = "evicted") -> None:
        """Free the arena slot behind a stored :class:`ArenaSession` — the
        session store's eviction callback (FIFO overflow, kill wipe,
        finished retire). Epoch-checked: a stale handle no-ops."""
        if not isinstance(session, ArenaSession):
            return
        with self._cv:
            arena = self._arenas.get(session.width)
            if arena is None or session.slot >= arena.n_slots:
                return
            s = arena.slots[session.slot]
            freed = s.state == _RESIDENT and s.epoch == session.epoch
            if freed:
                s.state = _FREE
                s.epoch += 1
        if freed and reason != "finished":
            # the resident rings behind a would-be follow-up are gone: the
            # decode work they encode is wasted (an overlapping goodput
            # dimension — the tokens themselves WERE delivered)
            self._m_tokens["wasted_evicted"].inc(int(session.steps))
            self.flight.record_evict(reason, session.width, session.slot,
                                     int(session.steps))

    # -- warmup / AOT --------------------------------------------------------

    def warmup(self, widths: Optional[Sequence[int]] = None,
               sampling: SamplingConfig = SamplingConfig()) -> int:
        """Compile the admission-wave prefill/install family plus ONE
        batched decode program per (width, slots): per-slot sampling
        params are traced operands and partial chunks are masked, so —
        unlike the per-session engine's chunk×sampling family — this is
        the ENTIRE decode program set. Wave buckets are powers of two up
        to ``_MAX_PREFILL_ROWS``. ``sampling`` is accepted for signature
        parity with :class:`ARGenerator` (it does not shape any arena
        program). With ``compile_cache`` set, programs come from / go to
        the :class:`~perceiver_io_tpu.aot.ExecutableCache`
        (zero-recompile restarts). Returns the number of programs
        readied."""
        import jax

        del sampling  # traced per-slot: no sampling-shaped programs
        count = 0
        for w in widths if widths is not None else self.widths:
            arena = self._ensure_arena(w)
            n = arena.n_slots
            k_n = 1
            while k_n <= _MAX_PREFILL_ROWS:
                ids = np.zeros((k_n, w), np.int32)
                pad = np.zeros((k_n, w), bool)
                lengths = np.full((k_n,), max(1, w - self.capacity + 1),
                                  np.int32)
                prefill = self._program("prefill", w, k_n,
                                        (self.params, ids, pad, lengths))
                # execute (cheap) so the install program sees real avals
                blogits, bcache = prefill(self.params, ids, pad, lengths)
                jax.block_until_ready(blogits)
                slots_arr = np.zeros((k_n,), np.int32)
                self._program(f"install_rows{k_n}", w, n,
                              (arena.buf, bcache, blogits, slots_arr))
                count += 2
                k_n *= 2
            ops = (np.zeros((n,), np.float32), np.zeros((n,), np.int32),
                   np.zeros((n,), np.int32), np.zeros((n,), np.int32))
            self._program("decode", w, n, (self.params, arena.buf) + ops)
            count += 1
        obs.event("generate_warmup", engine=self.name, programs=count,
                  batched=True)
        return count

    # -- the serving surface -------------------------------------------------

    def generate(
        self,
        prefix: Sequence[int],
        max_new: int,
        sampling: Optional[SamplingConfig] = None,
        on_chunk: Optional[Callable[[List[int], Dict[str, Any]], None]] = None,
        session=None,
        trace: Optional[obs.TraceContext] = None,
    ) -> Tuple[List[int], Optional[ArenaSession]]:
        """Same contract as :meth:`ARGenerator.generate` — tokens stream
        through ``on_chunk`` on THIS thread, episodes re-prefill on the
        fixed grid, a valid resident ``session`` resumes without a prefix
        encode — but the steps run inside the shared batched dispatch.
        ``trace`` attaches a ``decode_stream`` span (chunk children are
        recorded dispatcher-side at dispatch completion). The returned
        session is an :class:`ArenaSession` slot claim."""
        if self._closed.is_set():
            raise RuntimeError(f"batcher {self.name!r} is closed")
        sampling = (sampling or SamplingConfig()).normalized()
        prefix = [int(t) for t in prefix]
        if len(prefix) < 1:
            raise ValueError("generation needs a non-empty prefix")
        adopt = None
        if (isinstance(session, ArenaSession) and session.seq == prefix
                and session.seed == sampling.seed):
            adopt = session
        if adopt is None:
            self._m_sessions.inc()
        if max_new <= 0:
            return [], adopt
        ctx = trace.child() if trace is not None else None
        st = _Stream(prefix, max_new, sampling, adopt,
                     wants_chunks=on_chunk is not None, ctx=ctx)
        with self._cv:
            self._pending.append(st)
            self._m_queue.set(len(self._pending))
            self._cv.notify_all()
        produced: List[int] = []
        ok = False
        try:
            while True:
                kind, payload = st.q.get()
                if kind == "tokens":
                    tokens, info = payload
                    produced.extend(tokens)
                    if on_chunk is not None:
                        try:
                            on_chunk(tokens, info)
                        except BaseException:
                            # consumer died (a killed replica's gated frame
                            # callback): cancel OUR stream; the batch sails
                            # on
                            self.cancel(st)
                            raise
                elif kind == "done":
                    # the done payload is the dispatcher-authoritative
                    # token list — for no-on_chunk streams no per-chunk
                    # events flowed
                    ok = True
                    return payload, st.session_out
                else:  # "error"
                    raise payload
        finally:
            if ctx is not None:
                obs.record_span(
                    "decode_stream", ctx, st.t_start,
                    time.monotonic() - st.t_start, engine=self.name,
                    tokens=len(st.tokens), ok=ok,
                    queue_wait_s=(round(st.t_bind - st.t_start, 6)
                                  if st.t_bind else None),
                    ttft_s=(round(st.t_first - st.t_start, 6)
                            if st.t_first is not None else None))

    def cancel(self, st: _Stream) -> None:
        with self._cv:
            st.cancelled = True
            self._cv.notify_all()

    def close(self, timeout_s: float = 5.0) -> None:
        self._closed.set()
        with self._cv:
            self._cv.notify_all()
        self._thread.join(timeout=timeout_s)
        self.flight.flush()
        self._hb.close()

    def stats(self) -> Dict[str, Any]:
        """Cumulative dispatch aggregates (load_bench's record block) plus
        the goodput counters and the flight recorder's attribution summary
        (the /statz queryable view)."""
        with self._cv:
            d = dict(self._stats)
            d["slots"] = sum(a.n_slots for a in self._arenas.values())
        d["slot_occupancy_mean"] = (
            round(d.pop("fill_sum") / d["dispatches"], 4)
            if d["dispatches"] else None)
        d["steps_per_dispatch_mean"] = (
            round(d["steps"] / d["dispatches"], 3)
            if d["dispatches"] else None)
        d.update(self.token_stats())
        d["flight"] = self.flight.summary()
        return d

    def peek_logits(self, session: ArenaSession) -> Optional[np.ndarray]:
        """The resident next-token logits row for a session, or None when
        its slot moved on — the parity probe (tests pin these against the
        dense oracle at 2e-5)."""
        with self._cv:
            arena = self._arenas.get(session.width)
            if arena is None or session.slot >= arena.n_slots:
                return None
            s = arena.slots[session.slot]
            if s.state != _RESIDENT or s.epoch != session.epoch:
                return None
            row = arena.buf["logits"][session.slot]
        try:
            return np.asarray(row, np.float32)
        except RuntimeError:
            # the dispatcher donated this buffer between our ref-grab and
            # the fetch (TPU path) — same answer as a moved slot
            return None

    # -- the dispatcher ------------------------------------------------------

    def _has_work(self) -> bool:
        if self._pending:
            return True
        return any(s.state == _ACTIVE
                   for a in self._arenas.values() for s in a.slots)

    def _loop(self) -> None:
        self._hb.arm()
        while True:
            with self._cv:
                while not self._closed.is_set() and not self._has_work():
                    self._hb.disarm()
                    self._cv.wait(timeout=0.5)
                if self._closed.is_set():
                    pending = list(self._pending)
                    self._pending.clear()
                    actives = [s.stream for a in self._arenas.values()
                               for s in a.slots
                               if s.state == _ACTIVE and s.stream is not None]
                    break
            self._hb.arm()
            try:
                self._admit()
                self._flight_round()
                self._dispatch_round()
            except BaseException as e:  # defensive: fail streams, not the loop
                self._fail_all(e)
            self._hb.beat()
        self._hb.disarm()
        err = RuntimeError(f"batcher {self.name!r} closed")
        killed = 0
        for st in pending + actives:
            killed += len(st.tokens)
            self.flight.record_evict("draining", st.width, st.slot,
                                     len(st.tokens))
            st.q.put(("error", err))
        if killed:
            self._m_tokens["wasted_killed"].inc(killed)

    def _flight_round(self) -> None:
        """Record this scheduler round: post-admission queue depth plus
        per-arena occupancy, with every idle slot attributed to a cause
        (the decision tree is exhaustive over :data:`FLIGHT_CAUSES`, which
        is what makes the >=95% attribution bar structural, not lucky)."""
        with self._cv:
            draining = self._closed.is_set()
            pending_widths = set()
            for st in self._pending:
                try:
                    pending_widths.add(self.plan_width(st.cur_len()))
                except ValueError:
                    pass  # finishes at the next admit pass
            rows = []
            for w in sorted(self._arenas):
                a = self._arenas[w]
                active = sum(1 for s in a.slots if s.state == _ACTIVE)
                resident = sum(1 for s in a.slots if s.state == _RESIDENT)
                idle = a.n_slots - active
                causes: Dict[str, int] = {}
                if idle:
                    if draining:
                        causes["draining"] = idle
                    elif not pending_widths:
                        causes["no_pending"] = idle
                    elif w not in pending_widths:
                        causes["width_mismatch"] = idle
                    else:
                        # pending wants THIS width yet slots sit idle —
                        # the transient between a blocked claim and the
                        # next admit pass; the steady state is full-ACTIVE
                        causes["arena_full"] = idle
                rows.append((w, a.n_slots, active, resident, causes))
            pending_n = len(self._pending)
            admitted = self._stats["admitted"]
            retired = self._stats["retired"]
        if rows:
            self.flight.record_round(pending_n, admitted, retired, rows)

    def _fail_all(self, e: BaseException) -> None:
        with self._cv:
            streams = [s.stream for a in self._arenas.values()
                       for s in a.slots
                       if s.state == _ACTIVE and s.stream is not None]
            for a in self._arenas.values():
                for i, s in enumerate(a.slots):
                    if s.state == _ACTIVE:
                        self._retire_slot(a, i, resident=False)
            streams += list(self._pending)
            self._pending.clear()
            self._m_queue.set(0)
        killed = 0
        for st in streams:
            killed += len(st.tokens)
            self.flight.record_evict("killed", st.width, st.slot,
                                     len(st.tokens))
            st.q.put(("error", e))
        if killed:
            self._m_tokens["wasted_killed"].inc(killed)

    def _admit(self) -> None:
        """Place every pending stream it can: adopt a valid resident slot,
        or prefix-encode and install into a claimed slot. Same-width fresh
        encodes are grouped into ADMISSION WAVES — one vmapped prefill
        dispatch plus one row-scatter install per wave of up to
        ``_MAX_PREFILL_ROWS`` streams, instead of a dispatch pair per
        stream. Runs at chunk boundaries only (between dispatches) —
        admission never interrupts the running batch."""
        blocked: List[_Stream] = []
        while True:
            with self._cv:
                batch = list(self._pending)
                self._pending.clear()
                if not batch:
                    self._pending.extend(blocked)
                    self._m_queue.set(len(self._pending))
                    return
                self._m_queue.set(0)
            fresh: Dict[int, List[Tuple[_Stream, List[int]]]] = {}
            for st in batch:
                if st.cancelled:
                    self._m_tokens["wasted_cancelled"].inc(len(st.tokens))
                    st.q.put(("error", RuntimeError("stream cancelled")))
                    continue
                if st.adopt is not None and self._try_adopt(st):
                    continue
                cur = st.prefix + st.tokens
                if (len(cur) >= self.max_seq_len
                        or len(st.tokens) >= st.max_new):
                    self._finish(st, resident_ok=False)
                    continue
                fresh.setdefault(self.plan_width(len(cur)),
                                 []).append((st, cur))
            for width, items in fresh.items():
                arena = self._ensure_arena(width)
                placed: List[Tuple[_Stream, List[int], int]] = []
                for st, cur in items:
                    while True:
                        with self._cv:
                            slot = self._claim_slot(arena)
                            if slot is not None:
                                # reserve NOW: the wave claims several
                                # slots before any of them is bound
                                arena.slots[slot].state = _ACTIVE
                        if slot is not None:
                            placed.append((st, cur, slot))
                            break
                        if not self._grow(arena):
                            blocked.append(st)
                            break
                for lo in range(0, len(placed), _MAX_PREFILL_ROWS):
                    self._encode_group(arena,
                                       placed[lo:lo + _MAX_PREFILL_ROWS])

    def _try_adopt(self, st: _Stream) -> bool:
        """Resume onto the resident slot without a prefix encode; False =
        stale/exhausted handle (caller falls through to a fresh encode)."""
        ses = st.adopt
        st.adopt = None  # one shot — episode moves re-place normally
        with self._cv:
            arena = self._arenas.get(ses.width)
            s = (arena.slots[ses.slot]
                 if arena is not None and ses.slot < arena.n_slots
                 else None)
            if (s is not None and s.state == _RESIDENT
                    and s.epoch == ses.epoch
                    and ses.remaining() >= 1):
                st.tokens = []
                self._bind_slot(arena, ses.slot, st)
                self._m_admitted.inc()
                return True
        return False

    def _encode_group(self, arena: _Arena, rows) -> None:
        """One admission wave: prefix-encode up to ``_MAX_PREFILL_ROWS``
        same-width streams in ONE vmapped prefill dispatch, then scatter
        all of them into their claimed slots in ONE install program. Pad
        rows (bucket rounding) replay the last real row — idempotent."""
        g = len(rows)
        if g == 0:
            return
        width = arena.width
        k_n = 1
        while k_n < g:
            k_n *= 2
        ids = np.zeros((k_n, width), np.int32)
        pad = np.zeros((k_n, width), bool)
        lengths = np.zeros((k_n,), np.int32)
        slots_arr = np.zeros((k_n,), np.int32)
        for j, (st, cur, slot) in enumerate(rows):
            p = len(cur)
            ids[j, :p] = cur
            pad[j, p:] = True
            lengths[j] = p
            slots_arr[j] = slot
        for j in range(g, k_n):
            ids[j] = ids[g - 1]
            pad[j] = pad[g - 1]
            lengths[j] = lengths[g - 1]
            slots_arr[j] = slots_arr[g - 1]
        try:
            faults.inject("generation.prefill")
            t0 = time.monotonic()
            prefill = self._program("prefill", width, k_n,
                                    (self.params, ids, pad, lengths))
            blogits, bcache = prefill(self.params, ids, pad, lengths)
            install = self._program(
                f"install_rows{k_n}", width, arena.n_slots,
                (arena.buf, bcache, blogits, slots_arr))
            arena.buf = install(arena.buf, bcache, blogits, slots_arr)
            self._m_prefill_s.observe(time.monotonic() - t0)
        except BaseException as e:
            # the wave is the blast radius: free its claimed slots, error
            # its streams; the batch (other slots) sails on
            with self._cv:
                for _, _, slot in rows:
                    arena.slots[slot].state = _FREE
                    arena.slots[slot].epoch += 1
            killed = 0
            for st, _, _ in rows:
                killed += len(st.tokens)
                st.q.put(("error", e))
            if killed:
                self._m_tokens["wasted_killed"].inc(killed)
            return
        t_install = time.monotonic()
        with self._cv:
            for st, _, slot in rows:
                self._bind_slot(arena, slot, st)
                st.t_install = t_install
        self._m_prefills.inc(g)
        self._m_admitted.inc(g)

    def _finish(self, st: _Stream, resident_ok: bool) -> None:
        """Complete a stream: mint its session handle (a resident slot
        claim when the rings can still serve a follow-up) and signal the
        caller."""
        ses = None
        if st.placed:
            # a slot whose rings are exhausted (remaining 0) can't serve a
            # follow-up — freeing it beats hoarding a useless resident
            resident = resident_ok and st.width - st.cur_len() >= 1
            with self._cv:
                arena = self._arenas.get(st.width)
                s = arena.slots[st.slot]
                self._retire_slot(arena, st.slot, resident=resident)
                if resident:
                    ses = ArenaSession(st.prefix + st.tokens, st.width,
                                       st.sampling.seed, len(st.tokens),
                                       st.slot, s.epoch)
        st.session_out = ses
        if st.placed:
            self._m_retired.inc()
        self._m_tokens["delivered"].inc(len(st.tokens))
        st.q.put(("done", list(st.tokens)))

    def _dispatch_round(self) -> None:
        """One chunk boundary: per arena with active slots, LAUNCH one
        batched dispatch (jax dispatch is async — every arena's program is
        in flight before the first result is fetched, so multi-width rounds
        overlap on device), then distribute tokens, retire finished
        streams, and re-queue episode-boundary streams for re-placement."""
        with self._cv:
            widths = [w for w, a in self._arenas.items()
                      if any(s.state == _ACTIVE for s in a.slots)]
        launched = [self._launch_arena(w) for w in widths]
        for rec in launched:
            if rec is not None:
                self._complete_arena(*rec)

    def _launch_arena(self, width: int):
        with self._cv:
            arena = self._arenas[width]
            n = arena.n_slots
            steps_left = np.zeros((n,), np.int32)
            by_slot: Dict[int, _Stream] = {}
            for i, s in enumerate(arena.slots):
                if s.state != _ACTIVE:
                    continue
                st = s.stream
                if st.cancelled:
                    self._retire_slot(arena, i, resident=False)
                    self._m_tokens["wasted_cancelled"].inc(len(st.tokens))
                    st.q.put(("error", RuntimeError("stream cancelled")))
                    continue
                budget = st.max_new - len(st.tokens)
                ring = width - st.cur_len()
                steps_left[i] = max(0, min(self.chunk, budget, ring))
                by_slot[i] = st
            temp = arena.temp.copy()
            top_k = arena.top_k.copy()
            seeds = arena.seeds.copy()
        if not by_slot:
            return None
        total_steps = int(steps_left.sum())
        if total_steps == 0:
            # every bound stream is at an episode/absolute boundary:
            # pure bookkeeping, no device dispatch
            return (arena, by_slot, steps_left, None, 0.0, 0, 0)
        faults.inject("generation.batch_dispatch")
        active_n = int((steps_left > 0).sum())
        t0 = time.monotonic()
        compiled = self._program(
            "decode", width, n,
            (self.params, arena.buf, temp, top_k, seeds, steps_left))
        arena.buf, out = compiled(self.params, arena.buf, temp, top_k,
                                  seeds, steps_left)
        return (arena, by_slot, steps_left, out, t0, active_n, total_steps)

    def _complete_arena(self, arena, by_slot, steps_left, out, t0,
                        active_n, total_steps) -> None:
        n = arena.n_slots
        if out is None:
            out_np = np.full((n, self.chunk), -1, np.int32)
            wall = 0.0
        else:
            out_np = np.asarray(out)  # blocks until this arena's round lands
            wall = time.monotonic() - t0
            self._m_chunk_s.observe(wall)
            self._m_steps.inc(total_steps)
            self._m_tokens["generated"].inc(total_steps)
            self._m_steps_per_dispatch.observe(total_steps)
            self._m_occupancy.set(active_n)
            with self._cv:
                self._stats["dispatches"] += 1
                self._stats["steps"] += total_steps
                self._stats["fill_sum"] += active_n / max(n, 1)
        wall_ms = round(wall * 1e3, 3)
        now = time.monotonic()
        events: List[Tuple[_Stream, List[int], Dict[str, Any]]] = []
        requeue: List[_Stream] = []
        spans: List[Tuple[_Stream, int]] = []
        with self._cv:
            width = arena.width
            for i, st in by_slot.items():
                n_i = int(steps_left[i])
                toks = [int(t) for t in out_np[i, :n_i]]
                st.tokens.extend(toks)
                if toks:
                    # token-production stamps, taken HERE (dispatcher side)
                    # so wants_chunks=False streams measure identically —
                    # one queue-hop ahead of the caller's on_chunk clock,
                    # which is what the 5% reconciliation pin allows for
                    if st.t_first is None:
                        st.t_first = now
                        self._m_ttft_s.observe(
                            now - st.t_start,
                            exemplar=(st.ctx.trace_id
                                      if st.ctx is not None else None))
                    else:
                        self._m_itl_s.observe((now - st.t_prev) / len(toks))
                    st.t_prev = now
                    if st.ctx is not None:
                        spans.append((st, n_i))
                if toks and st.wants_chunks:
                    events.append((st, toks, {
                        "pos": st.cur_len(), "steps": n_i,
                        "chunk_ms": wall_ms, "batched": active_n,
                    }))
                done = (len(st.tokens) >= st.max_new
                        or st.cur_len() >= self.max_seq_len)
                boundary = st.cur_len() >= width
                if done:
                    pass  # finished below (needs the slot retire under cv)
                elif boundary:
                    # episode exhausted: free the slot, re-place at the
                    # next grid width (re-prefill from the extended prefix)
                    self._retire_slot(arena, i, resident=False)
                    st.placed = False
                    st.t_queued = now  # the next queue wait starts here
                    requeue.append(st)
            self._pending.extend(requeue)
            self._m_queue.set(len(self._pending))
        for st, n_i in spans:
            obs.record_span("decode_chunk", st.ctx.child(), t0, wall,
                            engine=self.name, steps=n_i,
                            pos=st.cur_len(), batched=active_n)
        for st, toks, info in events:
            st.q.put(("tokens", (toks, info)))
        finished = [st for st in by_slot.values()
                    if (len(st.tokens) >= st.max_new
                        or st.cur_len() >= self.max_seq_len)]
        for st in finished:
            resident_ok = st.cur_len() < self.max_seq_len
            self._finish(st, resident_ok=resident_ok)
