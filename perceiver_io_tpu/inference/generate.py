"""Incremental Perceiver-AR generation: prefix encode once, then step a
donated on-device latent/KV cache — the autoregressive serving engine.

The model half lives in ``models/perceiver.py`` (:class:`PerceiverARLM`):
``prefill`` runs ONE dense causal forward over the (width-bucketed) prefix
and harvests every tensor the dense path attends over into fixed-capacity
cache rings; ``step`` recomputes only the new token's latent row against
those rings. This module is the engine around that pair:

- **program discipline**: one compiled prefill program per (batch, width)
  bucket and one decode program per (batch, chunk, sampling-shape) — decode
  steps are chained ON DEVICE by ``lax.fori_loop`` inside a single dispatch
  with the cache donated between chunks, so the tunnel's per-dispatch
  latency amortizes over the chunk exactly like the training loop's
  ``steps_per_dispatch`` (PERF.md timing discipline: never per-step
  round-trips).
- **seeded, position-folded sampling**: the PRNG key for the token at
  absolute position p is ``fold_in(key(seed), p)`` — a continuation that
  re-encodes from its prefix on ANOTHER replica (affinity spill, episode
  re-prefill) reproduces the identical stream, which is what lets the
  mid-stream chaos drill assert ``lost_accepted=0`` by content.
- **episodes**: one prefill serves at most ``capacity − 1`` decode steps
  (the latent window must still cover the last prefix token). Longer
  continuations re-prefill from the extended prefix — the same re-encode
  path a dead session pin takes, so it is exercised constantly, not only
  under chaos.
- **parity oracle**: the dense full-prefix forward
  (``PerceiverARLM.__call__`` over the same padded width and
  latent-window anchor) is the oracle the incremental path must match at
  2e-5 on the f32 path (the tier-1 correctness spine,
  ``tests/test_generate.py``).

``GenerateSessionStore`` is the replica-side resident-state half: bounded
session table (FIFO eviction), sessions keyed like the latent-cache
affinity sessions so the router pins them identically.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.resilience import faults

Array = Any

#: every way a generated token leaves the engine — the ``outcome`` label on
#: ``decode_tokens_total``. ``delivered`` = handed to the caller at a
#: successful stream completion; ``generated`` = sampled by a decode
#: dispatch (the denominator: goodput = delivered / generated); the
#: ``wasted_*`` outcomes attribute the gap — tokens a cancelled/killed
#: stream produced but never completed, plus resident decode state an
#: eviction discarded (an overlapping dimension: evicted tokens WERE
#: delivered, what is wasted is the cache work behind a follow-up).
DECODE_TOKEN_OUTCOMES = ("generated", "delivered", "wasted_cancelled",
                         "wasted_killed", "wasted_evicted")


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    """How tokens are drawn from the step logits.

    ``temperature == 0`` is greedy argmax (the parity-friendly mode);
    otherwise logits/temperature with optional top-``k`` truncation feed a
    categorical draw. ``seed`` roots the position-folded key stream."""

    temperature: float = 0.0
    top_k: int = 0
    seed: int = 0

    def normalized(self) -> "SamplingConfig":
        t = float(self.temperature)
        k = int(self.top_k)
        if t < 0:
            raise ValueError(f"temperature must be >= 0, got {t}")
        if k < 0:
            raise ValueError(f"top_k must be >= 0, got {k}")
        return dataclasses.replace(self, temperature=t, top_k=k,
                                   seed=int(self.seed))


def sample_logits(logits, key, temperature, top_k: int, greedy: bool):
    """Draw one token per row from (B, V) logits. ``top_k``/``greedy`` are
    static (they shape the program); ``temperature`` is a traced operand so
    one compiled program serves every temperature."""
    import jax
    import jax.numpy as jnp

    logits = logits.astype(jnp.float32)
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k > 0 and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, jnp.finfo(jnp.float32).min, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


class GenSession:
    """Host-side handle for one generation stream: the device cache rings,
    the pending next-token logits, and the accepted token sequence (prompt +
    continuation) the cache state corresponds to."""

    __slots__ = ("cache", "next_logits", "seq", "width", "seed", "steps")

    def __init__(self, cache, next_logits, seq: List[int], width: int,
                 seed: int):
        self.cache = cache
        self.next_logits = next_logits
        self.seq = seq          # full accepted sequence the cache encodes
        self.width = width      # the cross-ring capacity (bucketed)
        self.seed = seed
        self.steps = 0          # decode steps taken over this session

    def remaining(self) -> int:
        """Decode steps this episode's rings can still absorb."""
        return self.width - len(self.seq)


class ARGenerator:
    """The incremental decode engine over one :class:`PerceiverARLM`.

    Prefill widths live on the GLOBAL EPISODE GRID ``capacity, capacity +
    (capacity−1), capacity + 2(capacity−1), …`` (capped at max_seq_len):
    grid spacing ``capacity − 1`` makes every grid point a legal window end
    for every prefix length inside its span, and a FIXED grid — never a
    function of the request — means a session re-encoded from its prefix at
    ANY point (affinity spill, episode boundary, follow-up call) anchors its
    latent window exactly where the uninterrupted stream would have,
    keeping the position-folded token stream bit-identical. It also bounds
    the prefill program family to ~max_seq_len/capacity widths (flagship:
    three), so serving compiles are a warmable closed set.

    ``chunk`` is the fori_loop trip count per decode dispatch (and the
    streaming granularity a serving caller observes).
    """

    def __init__(
        self,
        model,
        params,
        max_seq_len: int,
        chunk: int = 8,
        compute_dtype: Optional[str] = None,
        quantize: Optional[str] = None,
        group_size: Optional[int] = None,
        name: str = "generate",
        registry: Optional[obs.MetricsRegistry] = None,
    ):
        import jax

        from perceiver_io_tpu.inference.engine import (
            prepare_param_tree,
            resolve_params_mode,
        )
        from perceiver_io_tpu.quant import apply_operands, is_quantized

        if chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {chunk}")
        self.model = model
        self.max_seq_len = max_seq_len
        self.capacity = int(model.num_latents)
        if self.capacity < 2:
            raise ValueError("generation needs num_latents >= 2")
        self.chunk = int(chunk)
        self.name = name
        widths, w = [], self.capacity
        while w < max_seq_len:
            widths.append(w)
            w += self.capacity - 1
        widths.append(max_seq_len)
        self.widths = widths
        # same mode surface as ServingEngine: quantize='int8'/'int4' (or the
        # compute_dtype='int8w'/'int4w' shorthands) store the projection
        # kernels as int bytes, and the batched step's GEMMs stream them
        # through the fused dequant-matmul at the linear_apply sites
        compute_dtype, quantize = resolve_params_mode(compute_dtype, quantize)
        prepared = prepare_param_tree(params, compute_dtype, quantize,
                                      group_size)
        if is_quantized(prepared):
            # read the mode off the PREPARED tree: covers pre-quantized
            # input and int4's default grouping in one place, so the AOT
            # fingerprint always names the effective layout
            quantize, group_size = prepared.mode, prepared.group_size
        self.quantize = quantize
        self.group_size = group_size
        self.params = jax.device_put(prepared)

        def prefill_fn(p, ids, pad, length):
            import jax.numpy as jnp

            p = apply_operands(p)  # quantized tree -> QKernel operands
            logits, cache = model.apply(
                {"params": p}, ids, pad, length=length, method="prefill")
            n_cap = logits.shape[1]
            w = ids.shape[1]
            # the next-token logits: window row of the LAST real token
            row = length - 1 - (w - n_cap)
            nxt = jax.lax.dynamic_index_in_dim(
                logits, row, axis=1, keepdims=False)
            return nxt.astype(jnp.float32), cache

        def decode_fn(p, cache, logits_in, temperature, key,
                      n_steps: int, top_k: int, greedy: bool):
            import jax.numpy as jnp

            b = logits_in.shape[0]

            p = apply_operands(p)  # quantized tree -> QKernel operands

            def body(i, carry):
                cache, logits, out = carry
                pos = cache["len"]  # the position being sampled
                k = jax.random.fold_in(key, pos)
                tok = sample_logits(logits, k, temperature, top_k, greedy)
                out = jax.lax.dynamic_update_slice(
                    out, tok[:, None], (jnp.zeros((), jnp.int32), i))
                logits, cache = model.apply(
                    {"params": p}, cache, tok[:, None], method="step")
                return cache, logits.astype(jnp.float32), out

            out0 = jnp.zeros((b, n_steps), jnp.int32)
            cache, logits, out = jax.lax.fori_loop(
                0, n_steps, body, (cache, logits_in, out0))
            return out, logits, cache

        self._prefill = jax.jit(prefill_fn)
        # the cache is DONATED: each chunk's rings feed the next dispatch's
        # buffers (ping-pong on device, nothing round-trips to host).
        # TPU/GPU only — CPU XLA ignores donation with a warning per program
        # (the ServingEngine rule).
        donate = (1,) if jax.default_backend() == "tpu" else ()
        self._decode = jax.jit(
            decode_fn,
            static_argnames=("n_steps", "top_k", "greedy"),
            donate_argnums=donate,
        )
        reg = registry if registry is not None else obs.get_registry()
        labels = {"engine": name, "task": "generate"}
        self._m_sessions = reg.counter(
            "generate_sessions_total",
            "generation sessions started (one prefix encode each)", labels)
        self._m_prefills = reg.counter(
            "generate_prefills_total",
            "prefix encodes (session starts + episode/spill re-encodes)",
            labels)
        self._m_steps = reg.counter(
            "generate_steps_total", "decode steps taken", labels)
        self._m_prefill_s = reg.histogram(
            "generate_prefill_seconds", "wall time of one prefix encode",
            labels)
        self._m_chunk_s = reg.histogram(
            "generate_chunk_seconds",
            "wall time of one chunked decode dispatch", labels)
        # -- per-stream token-level instruments (r21): the TTFT/ITL/goodput
        # surface of production LLM serving, shared by the continuous
        # batcher (same registration, dispatcher-side stamps there)
        self._m_ttft_s = reg.histogram(
            "decode_ttft_seconds",
            "time to first token: stream enqueue -> first token produced "
            "(exemplar-linked to the stream's trace id)", labels)
        self._m_itl_s = reg.histogram(
            "decode_itl_seconds",
            "inter-token latency: gap between consecutive chunks divided "
            "by the tokens the later chunk carries", labels)
        self._m_queue_wait_s = reg.histogram(
            "decode_queue_wait_seconds",
            "stream enqueue -> arena slot bind (admission queue wait; ~0 "
            "on the per-session engine, which never queues)", labels)
        self._m_tokens = {
            o: reg.counter(
                "decode_tokens_total",
                "tokens by lifecycle outcome (goodput = delivered / "
                "generated; wasted_* attributes the gap — see "
                "DECODE_TOKEN_OUTCOMES)", {**labels, "outcome": o})
            for o in DECODE_TOKEN_OUTCOMES}

    def token_stats(self) -> Dict[str, Any]:
        """Goodput accounting snapshot: cumulative ``decode_tokens_total``
        by outcome plus ``goodput = delivered / generated`` (None before
        any token was generated). Shared by both engines — the continuous
        batcher inherits it, and ``stats()`` embeds the same counters."""
        tokens = {o: int(c.value) for o, c in self._m_tokens.items()}
        gen = tokens["generated"]
        return {"tokens": tokens,
                "goodput": (round(tokens["delivered"] / gen, 4)
                            if gen else None)}

    # -- width / episode planning -------------------------------------------

    def plan_width(self, prefix_len: int) -> int:
        """The prefill width (= ring capacity = latent-window END) for a
        ``prefix_len`` prefix: the smallest episode-grid point past the
        prefix.

        A pure function of the prefix length over a FIXED global grid —
        load-bearing for determinism: the latent-window anchor
        ``o = W − capacity`` shapes every downstream logit, so a session
        re-encoded from its prefix at ANY point (affinity spill, episode
        boundary, follow-up call) must anchor exactly where the
        uninterrupted stream did, or the continuation diverges — the
        mid-stream chaos drill pins this by content. Grid spacing
        ``capacity − 1`` keeps every choice inside the window constraint
        ``W <= prefix_len − 1 + capacity`` (see ``PerceiverARLM``)."""
        if prefix_len >= self.max_seq_len:
            raise ValueError(
                f"prefix {prefix_len} leaves no room under max_seq_len "
                f"{self.max_seq_len}")
        for w in self.widths:
            if w > prefix_len:
                return w
        raise AssertionError("unreachable: grid ends at max_seq_len")

    # -- programs ------------------------------------------------------------

    def warmup(self, widths: Optional[Sequence[int]] = None,
               sampling: SamplingConfig = SamplingConfig()) -> int:
        """Compile the prefill family plus the decode programs for the
        given sampling shape — EVERY chunk size 1..chunk (the tail of a
        request and an episode boundary dispatch partial chunks, which are
        their own programs; an unwarmed one is a mid-STREAM compile stall).
        Returns the number of programs readied. Call once per sampling
        shape served (greedy and top-k are distinct programs)."""
        import jax

        sampling = sampling.normalized()
        count = 0
        for w in widths if widths is not None else self.widths:
            ids = np.zeros((1, w), np.int32)
            pad = np.zeros((1, w), bool)
            logits, cache = self._prefill(
                self.params, ids, pad, np.int32(max(1, w - self.capacity + 1)))
            jax.block_until_ready(logits)
            count += 1
            # decode programs are keyed by the CACHE SHAPES too — every
            # width owns its own chunk family, so each must warm per width
            # or the first stream crossing an episode boundary pays a
            # mid-stream compile stall
            for n in range(1, self.chunk + 1):
                out, logits, cache = self._run_decode(
                    cache, logits, sampling, n_steps=n)
                jax.block_until_ready(out)
                count += 1
        obs.event("generate_warmup", engine=self.name, programs=count)
        return count

    def _run_decode(self, cache, logits, sampling: SamplingConfig,
                    n_steps: Optional[int] = None):
        import jax

        greedy = sampling.temperature == 0.0
        key = jax.random.key(sampling.seed)
        return self._decode(
            self.params, cache, logits,
            np.float32(sampling.temperature), key,
            n_steps=self.chunk if n_steps is None else n_steps,
            top_k=sampling.top_k, greedy=greedy,
        )

    # -- the serving surface ---------------------------------------------------

    def start(self, prefix: Sequence[int], seed: int = 0) -> GenSession:
        """Prefix-encode a session (width = :meth:`plan_width`)."""
        prefix = [int(t) for t in prefix]
        p = len(prefix)
        if p < 1:
            raise ValueError("generation needs a non-empty prefix")
        faults.inject("generation.prefill")
        w = self.plan_width(p)
        ids = np.zeros((1, w), np.int32)
        ids[0, :p] = prefix
        pad = np.zeros((1, w), bool)
        pad[0, p:] = True
        t0 = time.monotonic()
        logits, cache = self._prefill(self.params, ids, pad, np.int32(p))
        self._m_prefill_s.observe(time.monotonic() - t0)
        self._m_prefills.inc()
        return GenSession(cache, logits, prefix, w, seed)

    def decode_chunk(self, session: GenSession,
                     sampling: SamplingConfig,
                     n_steps: Optional[int] = None) -> List[int]:
        """Advance one chunked decode dispatch; returns the new tokens (and
        extends ``session.seq`` — the session cache now encodes them)."""
        faults.inject("generation.step")
        n = self.chunk if n_steps is None else n_steps
        if n > session.remaining():
            raise ValueError(
                f"chunk {n} exceeds session ring capacity "
                f"(remaining {session.remaining()})")
        t0 = time.monotonic()
        out, logits, cache = self._run_decode(
            session.cache, session.next_logits,
            dataclasses.replace(sampling, seed=session.seed), n_steps=n)
        tokens = [int(t) for t in np.asarray(out)[0]]
        self._m_chunk_s.observe(time.monotonic() - t0)
        self._m_steps.inc(n)
        self._m_tokens["generated"].inc(n)
        session.cache = cache
        session.next_logits = logits
        session.seq = session.seq + tokens
        session.steps += n
        return tokens

    def generate(
        self,
        prefix: Sequence[int],
        max_new: int,
        sampling: Optional[SamplingConfig] = None,
        on_chunk: Optional[Callable[[List[int], Dict[str, Any]], None]] = None,
        session: Optional[GenSession] = None,
        trace: Optional[obs.TraceContext] = None,
    ) -> Tuple[List[int], GenSession]:
        """Generate up to ``max_new`` tokens after ``prefix``, streaming
        each chunk through ``on_chunk(tokens, info)``. Episodes re-prefill
        from the extended prefix when the latent window fills — the same
        re-encode a spilled session performs, with the position-folded key
        stream keeping the tokens identical either way. ``trace`` (the
        caller's propagated context) attaches one ``decode_stream`` span
        covering the stream's whole life plus a ``decode_chunk`` child per
        dispatch. Returns ``(new_tokens, session)``; pass the session back
        in (with the extended prefix) to continue without a fresh encode."""
        sampling = (sampling or SamplingConfig()).normalized()
        prefix = [int(t) for t in prefix]
        produced: List[int] = []
        if session is not None and (session.seq != prefix
                                    or session.seed != sampling.seed):
            session = None  # resident state diverged: re-encode
        if session is None:
            self._m_sessions.inc()
        t_enter = time.monotonic()
        ctx = trace.child() if trace is not None else None
        exemplar = ctx.trace_id if ctx is not None else None
        t_first: Optional[float] = None
        t_prev = t_enter
        ok = False
        try:
            while len(produced) < max_new:
                cur = prefix + produced
                if len(cur) >= self.max_seq_len:
                    break  # absolute position budget exhausted
                if session is None or session.remaining() < 1:
                    session = self.start(cur, seed=sampling.seed)
                n = min(self.chunk, max_new - len(produced),
                        session.remaining())
                t0 = time.monotonic()
                tokens = self.decode_chunk(session, sampling, n_steps=n)
                now = time.monotonic()
                produced.extend(tokens)
                if tokens:
                    if t_first is None:
                        t_first = now
                        # no admission queue on the per-session engine: the
                        # wait is entry -> first dispatch start (~0), kept
                        # so both engines export the same instrument set
                        self._m_queue_wait_s.observe(t0 - t_enter,
                                                     exemplar=exemplar)
                        self._m_ttft_s.observe(now - t_enter,
                                               exemplar=exemplar)
                    else:
                        self._m_itl_s.observe((now - t_prev) / len(tokens))
                    t_prev = now
                    if ctx is not None:
                        obs.record_span(
                            "decode_chunk", ctx.child(), t0, now - t0,
                            engine=self.name, steps=n,
                            pos=len(session.seq))
                if on_chunk is not None:
                    on_chunk(tokens, {
                        "pos": len(session.seq),
                        "steps": n,
                        "chunk_ms": round((now - t0) * 1e3, 3),
                    })
            ok = True
            self._m_tokens["delivered"].inc(len(produced))
            return produced, session
        finally:
            if not ok:
                # the stream died (engine error or a raising on_chunk
                # consumer): its tokens never reached a completed stream
                self._m_tokens["wasted_killed"].inc(len(produced))
            if ctx is not None:
                obs.record_span(
                    "decode_stream", ctx, t_enter,
                    time.monotonic() - t_enter, engine=self.name,
                    tokens=len(produced), ok=ok,
                    ttft_s=(None if t_first is None
                            else round(t_first - t_enter, 6)))


def load_ar_checkpoint(
    checkpoint_dir: str,
    tokenizer,
    step: Optional[int] = None,
    dtype: Optional[str] = None,
):
    """Rebuild a ``PerceiverARLM`` from the hparams embedded in a
    ``cli/train_ar.py`` checkpoint and restore its best/chosen step.
    Returns ``(model, params, max_seq_len)`` — the shared loading path of
    the serve CLI and the replica process (mirrors
    ``inference.mlm.load_mlm_checkpoint``)."""
    import jax
    from types import SimpleNamespace

    from perceiver_io_tpu.cli import common
    from perceiver_io_tpu.training.checkpoint import (
        load_hparams,
        restore_params,
    )

    hparams = load_hparams(checkpoint_dir)
    defaults = {
        "dtype": "float32", "attn_impl": "auto", "dropout": 0.0,
    }
    args = SimpleNamespace(**{**defaults, **hparams})
    if dtype is not None:
        args.dtype = dtype
    vocab_size = tokenizer.get_vocab_size()
    max_seq_len = hparams["max_seq_len"]
    model = common.build_ar(args, vocab_size, max_seq_len)

    ids = np.zeros((1, max_seq_len), np.int32)
    pad = np.zeros((1, max_seq_len), bool)
    like = jax.eval_shape(
        lambda: model.init({"params": jax.random.key(0)}, ids, pad)
    )["params"]
    params = restore_params(checkpoint_dir, like, step=step)
    return model, params, max_seq_len


class GenerateSessionStore:
    """Replica-resident generation sessions: bounded, FIFO-evicted, keyed
    like the latent-cache affinity sessions so the router pins them the
    same way. ``match(session, seq)`` returns the resident
    :class:`GenSession` only when its accepted sequence is EXACTLY the
    caller's prefix — anything else (evicted, diverged, restarted replica)
    re-encodes from the prefix, which is the whole spill-on-death story."""

    # pitlint PIT-LOCK: the table is shared between RPC handler threads
    _guarded_by = {"_sessions": "_lock"}

    #: every way a resident session leaves the store — the ``reason`` label
    #: the chaos drills assert on (metrics, not log-scraping)
    RETIRE_REASONS = ("finished", "evicted", "killed")

    def __init__(self, max_sessions: int = 256,
                 registry: Optional[obs.MetricsRegistry] = None,
                 name: str = "replica",
                 on_evict: Optional[Callable[[Any, str], None]] = None):
        self._lock = threading.Lock()
        self._sessions: "OrderedDict[str, GenSession]" = OrderedDict()
        self.max_sessions = max_sessions
        self._on_evict = on_evict
        reg = registry if registry is not None else obs.get_registry()
        self._m_resident = reg.gauge(
            "generate_sessions_resident",
            "generation sessions resident on this replica",
            {"replica": name, "task": "generate"})
        self._m_retired = {
            r: reg.counter(
                "generate_sessions_retired_total",
                "resident generation sessions leaving the store, by reason "
                "(finished = absolute budget exhausted, evicted = FIFO/"
                "overwrite pressure, killed = replica death wiped the table)",
                {"replica": name, "task": "generate", "reason": r})
            for r in self.RETIRE_REASONS}

    def _dropped(self, dropped: List[Any], reason: str) -> None:
        """Account (and fan out) sessions that left the table — called
        OUTSIDE the lock: the eviction callback may take the generation
        engine's own lock (the arena frees the slot behind the session)."""
        for ses in dropped:
            self._m_retired[reason].inc()
            if self._on_evict is not None:
                try:
                    self._on_evict(ses, reason)
                except Exception:
                    pass  # a resource-release hook must never break serving

    def match(self, session_id: Optional[str],
              seq: Sequence[int]) -> Optional[GenSession]:
        if session_id is None:
            return None
        with self._lock:
            ses = self._sessions.get(session_id)
        if ses is None or ses.seq != [int(t) for t in seq]:
            return None
        return ses

    def put(self, session_id: Optional[str],
            session: Optional[GenSession]) -> None:
        if session_id is None or session is None:
            return  # anonymous stream, or a zero-step call that never ran
        dropped = []
        with self._lock:
            old = self._sessions.get(session_id)
            if old is not None and old is not session:
                dropped.append(old)  # overwritten: release its resources
            self._sessions[session_id] = session
            while len(self._sessions) > self.max_sessions:
                dropped.append(self._sessions.popitem(last=False)[1])
            self._m_resident.set(len(self._sessions))
        self._dropped(dropped, "evicted")

    def remove(self, session_id: Optional[str],
               reason: str = "finished") -> bool:
        """Retire one resident session (its continuation hit the absolute
        budget, or the caller is done with it); returns whether it was
        resident."""
        if session_id is None:
            return False
        with self._lock:
            ses = self._sessions.pop(session_id, None)
            self._m_resident.set(len(self._sessions))
        if ses is None:
            return False
        self._dropped([ses], reason)
        return True

    def clear(self, reason: str = "killed") -> None:
        with self._lock:
            dropped = list(self._sessions.values())
            self._sessions.clear()
            self._m_resident.set(0)
        self._dropped(dropped, reason)

    def __len__(self) -> int:
        with self._lock:
            return len(self._sessions)
