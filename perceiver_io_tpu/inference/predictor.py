"""Batched inference wrapper with compile-stable batch bucketing.

The reference has no inference API beyond an inline predict helper
(``train/train_mlm.py:14-35``; SURVEY.md §3.4: "no serve()/export path").
On TPU the naive approach — jit the forward and call it on whatever batch
arrives — recompiles on every new batch size (XLA programs have static
shapes). ``Predictor`` makes serving shapes compile-stable: requests are
padded up to the next power-of-two bucket (one compilation per bucket,
log₂(max_batch) programs total) and oversized requests are chunked at
``max_batch``, so steady-state serving never recompiles.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import numpy as np

Array = jax.Array


def bucket_size(n: int, max_batch: int) -> int:
    """Smallest power of two ≥ n, capped at ``max_batch``."""
    if n <= 0:
        raise ValueError(f"batch size must be positive, got {n}")
    b = 1
    while b < n:
        b *= 2
    return min(b, max_batch)


class Predictor:
    """Wrap a pure ``(params, *batched_arrays) → pytree`` forward for serving.

    - pads every input's leading axis to a power-of-two bucket (padding rows
      repeat row 0, and are sliced off every output leaf), so each bucket
      compiles exactly once;
    - chunks requests larger than ``max_batch`` and concatenates the results;
    - ``donate_params=False`` always: params live on device across calls.

    ``apply_fn`` must treat examples independently along the leading axis
    (true of every model in this framework — no cross-batch interaction).
    """

    def __init__(
        self,
        apply_fn: Callable[..., Any],
        params,
        max_batch: int = 64,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.params = params
        self.max_batch = max_batch
        self._jitted = jax.jit(apply_fn)

    @classmethod
    def for_model(cls, model, params, max_batch: int = 64, **apply_kwargs):
        """Predictor over ``model.apply`` with dropout off (inference mode)."""

        def apply_fn(p, *inputs):
            return model.apply(
                {"params": p}, *inputs, deterministic=True, **apply_kwargs
            )

        return cls(apply_fn, params, max_batch=max_batch)

    def _dispatch_padded(self, inputs: Sequence[np.ndarray], n: int):
        """Pad to the bucket and dispatch; returns the on-device output
        (not fetched — JAX dispatch is async, so a second chunk can be queued
        before the first host transfer)."""
        bucket = bucket_size(n, self.max_batch)
        padded = []
        for x in inputs:
            if x.shape[0] != n:
                raise ValueError(
                    f"all inputs must share the leading batch axis: {x.shape[0]} != {n}"
                )
            if bucket > n:
                x = np.concatenate(
                    [x, np.broadcast_to(x[:1], (bucket - n, *x.shape[1:]))], axis=0
                )
            padded.append(x)
        return self._jitted(self.params, *padded)

    def _empty_result(self, inputs: Sequence[np.ndarray]):
        """Outputs for an n=0 request without touching the device: eval_shape
        over a one-row input gives the pytree structure/dtypes for free."""
        ones = [np.zeros((1, *x.shape[1:]), x.dtype) for x in inputs]
        shapes = jax.eval_shape(self._jitted, self.params, *ones)
        return jax.tree.map(
            lambda s: np.zeros((0, *s.shape[1:]), s.dtype), shapes
        )

    def __call__(self, *inputs):
        host_inputs = [np.asarray(x) for x in inputs]
        n = host_inputs[0].shape[0]
        if any(x.shape[0] != n for x in host_inputs):
            raise ValueError("all inputs must share the leading batch axis")
        if n == 0:
            return self._empty_result(host_inputs)
        if n <= self.max_batch:
            out = self._dispatch_padded(host_inputs, n)
            return jax.tree.map(lambda leaf: np.asarray(jax.device_get(leaf))[:n], out)
        # oversized request: fixed-size chunks (+ one padded tail bucket).
        # Keep exactly two dispatches in flight — chunk i's host transfer
        # overlaps chunk i+1's device compute, while device-resident outputs
        # stay O(max_batch), not O(n) (output-heavy models would otherwise
        # queue gigabytes).
        chunks = []
        pending = None  # (device_out, rows)
        for start in range(0, n, self.max_batch):
            sl = [x[start : start + self.max_batch] for x in host_inputs]
            current = (self._dispatch_padded(sl, sl[0].shape[0]), sl[0].shape[0])
            if pending is not None:
                out, m = pending
                chunks.append(
                    jax.tree.map(lambda leaf: np.asarray(jax.device_get(leaf))[:m], out)
                )
            pending = current
        out, m = pending
        chunks.append(
            jax.tree.map(lambda leaf: np.asarray(jax.device_get(leaf))[:m], out)
        )
        return jax.tree.map(lambda *leaves: np.concatenate(leaves, axis=0), *chunks)
