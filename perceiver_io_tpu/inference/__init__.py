from perceiver_io_tpu.inference.predictor import Predictor, bucket_size
from perceiver_io_tpu.inference.export import (
    export_fn,
    export_forward,
    load_exported,
)
from perceiver_io_tpu.inference.mlm import (
    MLMPredictor,
    encode_masked_texts,
    load_mlm_checkpoint,
)
from perceiver_io_tpu.inference.engine import (
    CachedLatents,
    EngineClosed,
    MLMServer,
    ServingEngine,
    WarmupHandle,
)
from perceiver_io_tpu.inference.generate import (
    ARGenerator,
    GenerateSessionStore,
    GenSession,
    SamplingConfig,
)
from perceiver_io_tpu.inference.batching import (
    ArenaSession,
    ContinuousBatcher,
    sample_logits_rows,
)
from perceiver_io_tpu.resilience import (
    BreakerOpen,
    DeadlineExceeded,
    RejectedError,
)

__all__ = [
    "ARGenerator",
    "ArenaSession",
    "ContinuousBatcher",
    "GenSession",
    "GenerateSessionStore",
    "sample_logits_rows",
    "Predictor",
    "SamplingConfig",
    "bucket_size",
    "export_fn",
    "export_forward",
    "load_exported",
    "MLMPredictor",
    "encode_masked_texts",
    "load_mlm_checkpoint",
    "BreakerOpen",
    "CachedLatents",
    "DeadlineExceeded",
    "EngineClosed",
    "MLMServer",
    "RejectedError",
    "ServingEngine",
    "WarmupHandle",
]
