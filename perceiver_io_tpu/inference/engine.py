"""High-throughput serving engine: continuous micro-batching over the
bucketed XLA programs, plus the latent-cache (encode-once / decode-many) path.

``Predictor`` (``inference/predictor.py``) made single requests
compile-stable; this module makes a *stream* of requests fast. The three
ideas, all reusing machinery the training stack already proved out:

1. **Continuous micro-batching** (``ServingEngine``): callers ``submit()``
   requests into a queue; a worker thread coalesces whatever is pending into
   one micro-batch, pads it to the next power-of-two bucket (the
   ``Predictor`` shapes — one XLA program per bucket, ``warmup()`` compiles
   them all ahead of time so steady state never compiles), and dispatches.
   Up to ``max_inflight`` dispatches stay in flight, so host work — queue
   drain, padding, result slicing — overlaps device compute exactly the way
   ``steps_per_dispatch`` overlaps the training loop. While the device chews
   on batch *i*, arrivals accumulate and become batch *i+1*: under load the
   engine serves large batches at device throughput; idle, a lone request
   dispatches immediately (``max_delay_ms`` optionally holds the first
   request back to let a batch form).

2. **Latent-cache decode** (``MLMServer.encode`` / ``decode``): Perceiver
   IO's fixed latent array is the model's entire summary of the input — the
   architecture's analogue of a KV cache. The split ``encode()``/``decode()``
   methods on the model core (``models/perceiver.py``) let multi-query
   workloads (fill-mask at several positions, multi-task decode heads) pay
   the O(L) encoder cross-attention once and decode arbitrarily many query
   sets against the cached latents.

3. **Width bucketing for variable-length text** (``MLMServer``): requests
   tokenize to their natural length and pad to the smallest serving width
   bucket (``resolve_bucket_width`` — the same rule as the training
   collator's ``bucket_widths``), so short requests never pay max_seq_len
   compute. Same-width requests batch together; each (width, batch-bucket,
   query-bucket) triple is one compiled program, all warmable ahead of time.

bf16 serving: pass ``compute_dtype='bfloat16'`` to an engine built over a
bf16-``dtype`` model — floating params/inputs are cast ONCE at engine
construction / dispatch (halving param HBM traffic per batch). Never set it
on the f32 golden-parity path: bf16 rounds. On TPU the padded input buffers
are donated to XLA (``donate_argnums``) — each dispatch's staging buffer is
handed to the device while the host fills the next one (ping-pong staging);
off-TPU donation is skipped (unimplemented there, and XLA would warn).

int8w serving: ``quantize='int8'`` (or the ``compute_dtype='int8w'``
shorthand — bf16 compute over int8-stored weights) quantizes the matmul
kernels ONCE at engine construction (``perceiver_io_tpu.quant``: per-channel
symmetric int8, f32 scales, key paths identical to the f32 tree) and
dequantizes inside the jitted dispatch, so each micro-batch streams int8
weight bytes from HBM — the measured roofline's binding term. Same bucket
programs, same AOT ``warmup()``; checkpoints stay f32 on disk.
``update_params()`` hot-swaps (re-quantizing under the same mode) without
recompiling: preparation runs on the caller thread and the worker installs
the finished tree atomically between micro-batches, so requests that arrive
mid-(re)quantization queue against the old params rather than racing a
half-built tree.

Self-healing (``perceiver_io_tpu.resilience``): the engine assumes the
device can misbehave the way the tunneled backend actually does —

- **request deadlines** (``request_deadline_s`` / ``submit(deadline_s=)``):
  enforced at admission (an already-expired deadline is refused) and again
  at batch assembly, where expired parts are shed with
  :class:`~perceiver_io_tpu.resilience.DeadlineExceeded` instead of burning
  a dispatch on work whose caller's ``result(timeout=)`` already gave up;
- **bounded queue** (``queue_limit``): admission fast-fails with
  :class:`~perceiver_io_tpu.resilience.RejectedError` once that many parts
  are backlogged — explicit load shedding instead of unbounded queue growth;
- **transient re-dispatch** (``dispatch_retries``): a dispatch or completion
  failure the taxonomy classifies transient re-queues the micro-batch with
  exponential backoff instead of failing every rider's future;
- **circuit breaker** (``breaker_failures`` > 0): consecutive dispatch
  failures — or a heartbeat stall, via the monitor's ``on_stall`` hook —
  open it; submissions then fast-fail
  (:class:`~perceiver_io_tpu.resilience.BreakerOpen`) until a cooldown
  half-open probe succeeds. State rides the obs registry and ``/healthz``.

Shed/retry/breaker counts export as ``serving_shed_total{reason=...}`` /
``serving_dispatch_retries_total`` / ``breaker_*``.

SLO observability (``perceiver_io_tpu.obs.slo``, ``tools/load_bench.py``):
every request part carries phase timestamps through its whole lifecycle —
submit → queue → batch assembly → dispatch → device compute → completion —
exported per phase as ``serving_phase_seconds{phase=...}`` histograms, as
JSONL spans when an event log is configured (untraced traffic:
``request_phases`` per part, sampled by ``span_every``; traced requests —
``submit(trace=)`` or an engine-minted root under ``trace_sample`` — ride
the compact spooled ``request_phases_batch`` record, assembled into
distributed trace trees by ``obs.reqtrace``/``tools/trace_assemble.py``),
and on the caller's future (``fut.phases``). The phases are consecutive
timestamp diffs, so their sum reconciles with the end-to-end
``serving_latency_seconds`` by construction (``serving_phase_sum_ratio`` is
the live self-check; the test suite pins the p50 reconciliation within 5%,
cross-process since r15). Tail latency therefore ATTRIBUTES: "p99 is high"
becomes "p99 is high because admission wait, not device time". Passing ``slo=obs.SLO(...)``
additionally classifies every completion/shed against a declarative
objective — error-budget burn-rate gauges ride ``/statz`` and ``healthz()``,
and ``tools/load_bench.py`` fits the measured capacity model
(requests/s/chip at the SLO) from an open-loop offered-load sweep.

Zero-recompile cold start (``perceiver_io_tpu.aot``): ``compile_cache=DIR``
persists every compiled bucket program to disk
(``jax.experimental.serialize_executable``), keyed by a content fingerprint
(apply-fn source/model identity, jax+PJRT platform/topology, abstract
shapes/dtypes, donation/quantize/dtype config). A warm restart deserializes
each program instead of tracing+lowering+compiling it — ``warmup()`` then
performs ZERO XLA compiles (pinned by test via ``jax_compilations_total``).
Corrupt entries and fingerprint drift fall back to a normal compile; a cache
problem never refuses traffic. ``warmup(background=True)`` turns the
blocking compile-everything call into a cache-first, priority-ordered
(smallest bucket first) BACKGROUND warmup: the engine serves traffic as soon
as the first needed bucket is ready — a request for a not-yet-warm program
either rides the warmup thread's in-flight build (cache mode dedups via a
per-program claim) or compiles on demand — and the remaining family keeps
warming off the hot path. Warmth is observable: per-engine ``engine_ready``
gauge (0 = warming, 1 = last requested family fully warm, surfaced on
``/statz``) and ``serving_warmup_seconds``.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.aot import (
    callable_sources,
    environment_fingerprint,
    fingerprint as aot_fingerprint,
    resolve_cache,
)
from perceiver_io_tpu.inference.predictor import bucket_size
from perceiver_io_tpu.resilience import (
    BreakerOpen,
    CircuitBreaker,
    DeadlineExceeded,
    RejectedError,
    RetryPolicy,
    faults,
    is_transient,
)

_IDLE_POLL_S = 0.05  # worker wake-up cadence while idle (checks shutdown)
_TRACE_SPOOL_ROWS = 64  # traced span rows per flushed JSONL record (the
# spool also flushes at the first idle moment and on worker exit, so span
# visibility lags only while the engine is saturated — when offline
# assembly is the consumer anyway)

# per-request lifecycle phases, in order; consecutive timestamp diffs, so the
# sum reconciles with the end-to-end latency by construction (the self-check
# rides serving_phase_sum_ratio and the test suite):
#   admission — submit() entry → part enqueued (validation, chunking, bounds)
#   queue     — enqueued → sealed into a micro-batch by the worker
#   assembly  — sealed → padded/cast columns built (host batch formation)
#   dispatch  — columns → the program call returned (host dispatch; a cold
#               program pays its compile/deserialize here)
#   device    — dispatch returned → outputs fetched to host (device compute
#               plus any wait behind earlier in-flight dispatches)
#   complete  — fetched → this part's future delivered (slicing, fan-out)
PHASES = ("admission", "queue", "assembly", "dispatch", "device", "complete")


def resolve_params_mode(
    compute_dtype: Optional[str], quantize: Optional[str]
) -> Tuple[Optional[str], Optional[str]]:
    """Normalize the (compute_dtype, quantize) pair — ONE definition of the
    ``'int8w'``/``'int4w'`` shorthands (bf16 compute over int-stored
    weights) and the mode validation, shared by ``ServingEngine``,
    ``MLMServer``, and the decode engines so they can never drift."""
    # validate BEFORE the shorthand rewrite: compute_dtype='int8w' must not
    # silently swallow a typo'd quantize= argument
    if quantize not in (None, "int8", "int4"):
        raise ValueError(
            f"unknown quantize mode {quantize!r}; expected None, 'int8', "
            "or 'int4'"
        )
    if compute_dtype == "int8w":
        compute_dtype, quantize = "bfloat16", "int8"
    elif compute_dtype == "int4w":
        compute_dtype, quantize = "bfloat16", "int4"
    return compute_dtype, quantize


def prepare_param_tree(params, compute_dtype, quantize: Optional[str],
                       group_size: Optional[int] = None):
    """Load-time param preparation under a serving mode (no device_put):
    cast floating leaves to ``compute_dtype`` (bf16 path), or quantize the
    matmul kernels to int8/int4 with the remaining floats cast (int8w/int4w
    paths — scales computed from the caller's tree, so hand in f32 for full
    scale precision; int4 defaults to grouped scales, ``group_size``
    overrides). A tree that is already ``QuantizedParams`` is trusted as
    prepared."""
    import jax
    import jax.numpy as jnp

    from perceiver_io_tpu.quant import is_quantized, quantize_tree

    if is_quantized(params):
        return params  # prepared upstream (e.g. once for MLMServer's 3 engines)
    if quantize in ("int8", "int4"):
        return quantize_tree(
            params,
            compute_dtype=str(jnp.dtype(compute_dtype or jnp.float32)),
            bits=8 if quantize == "int8" else 4,
            group_size=group_size,
        )
    if compute_dtype is not None:
        dt = jnp.dtype(compute_dtype)
        cast = lambda x: (
            x.astype(dt) if jnp.issubdtype(x.dtype, jnp.floating) else x
        )
        return jax.tree.map(cast, params)
    return params


class EngineClosed(RuntimeError):
    """submit() after close()."""


class WarmupHandle:
    """Tracks one (possibly background) warmup run.

    ``wait()`` blocks until the warmup finishes and returns its result (the
    warmed bucket list for an engine, the warmed program count for an
    ``MLMServer``), re-raising any warmup error. ``cancel()`` asks the
    warming thread(s) to stop at the next bucket boundary (an in-flight
    compile cannot be interrupted); ``close()`` cancels automatically.
    """

    def __init__(self):
        self._done_event = threading.Event()
        self._cancel_event = threading.Event()
        self._error: Optional[BaseException] = None
        self._threads: List[threading.Thread] = []
        self.result: Any = None

    def done(self) -> bool:
        return self._done_event.is_set()

    def cancelled(self) -> bool:
        return self._cancel_event.is_set()

    def cancel(self) -> None:
        self._cancel_event.set()

    def join(self, timeout: Optional[float] = None) -> None:
        """Wait for the warming thread(s) to actually exit (bounded).

        ``cancel()`` only asks; a thread mid-compile finishes that build
        first. Owners call this from ``close()`` so no warmup thread keeps
        driving the jax runtime concurrently with whatever the process does
        next — a leftover compile racing later work is a real crash, not a
        hygiene nit. A wedged build past ``timeout`` is abandoned (daemon)."""
        for t in self._threads:
            t.join(timeout)

    def wait(self, timeout: Optional[float] = None):
        if not self._done_event.wait(timeout):
            raise TimeoutError("warmup not finished within timeout")
        if self._error is not None:
            raise self._error
        return self.result

    def _finish(self, result) -> None:
        self.result = result
        self._done_event.set()

    def _fail(self, error: BaseException) -> None:
        self._error = error
        self._done_event.set()


class _Future:
    """Result handle for one submitted request.

    Oversized requests are split into ``num_parts`` sub-dispatches; the
    future assembles them (axis-0 concat per leaf) when the last completes.
    ``transform`` (optional) maps the assembled result in the caller's
    ``result()`` — post-processing (top-k decode, detokenization) stays off
    the engine worker thread.
    """

    def __init__(self, num_parts: int = 1,
                 transform: Optional[Callable[[Any], Any]] = None,
                 trace: Optional[obs.TraceContext] = None):
        self._event = threading.Event()
        self._parts: List[Any] = [None] * num_parts
        self._remaining = num_parts
        self._lock = threading.Lock()
        self._error: Optional[BaseException] = None
        self._transform = transform
        self._assembled = None
        self._has_result = False
        self._phases: List[Dict[str, float]] = []
        self.trace = trace  # distributed-trace context (None = untraced)

    def _note_phases(self, phases: Dict[str, float]) -> None:
        with self._lock:
            self._phases.append(phases)

    @property
    def phases(self) -> List[Dict[str, float]]:
        """Per-part phase timings (seconds, :data:`PHASES` keys) recorded at
        completion — one dict per dispatched part, the caller-side view the
        load harness consumes without scraping the registry."""
        with self._lock:
            return [dict(p) for p in self._phases]

    def _deliver(self, index: int, result) -> None:
        with self._lock:
            self._parts[index] = result
            self._remaining -= 1
            if self._remaining == 0:
                self._event.set()

    def _fail(self, error: BaseException) -> None:
        with self._lock:
            if self._error is None:
                self._error = error
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("request not served within timeout")
        if self._error is not None:
            raise self._error
        with self._lock:
            if not self._has_result:
                if len(self._parts) == 1:
                    out = self._parts[0]
                else:
                    import jax

                    out = jax.tree.map(
                        lambda *xs: np.concatenate(xs, axis=0), *self._parts
                    )
                if self._transform is not None:
                    out = self._transform(out)
                self._assembled, self._has_result = out, True
                self._parts = []  # free the per-part copies
        return self._assembled


class _Part:
    """One queue unit: ≤ max_batch rows of one request.

    ``deadline`` (monotonic, or None) is checked at batch assembly — expired
    parts are shed, never dispatched. ``retries`` counts transient
    re-dispatch cycles this part has ridden (worker-thread-only writes).

    Phase timestamps (monotonic): ``t_entry`` (submit() entry),
    ``t_submit`` (enqueued), then worker-written ``t_sealed`` / ``t_built`` /
    ``t_sent`` — a retried part overwrites them on its final dispatch, so the
    queue phase absorbs the retry wait and the sum still partitions
    [t_entry, delivery].
    """

    __slots__ = ("inputs", "n", "key", "future", "index", "t_submit",
                 "deadline", "retries", "t_entry", "t_sealed", "t_built",
                 "t_sent")

    def __init__(self, inputs: List[np.ndarray], key, future: _Future,
                 index: int, deadline: Optional[float] = None,
                 t_entry: Optional[float] = None):
        self.inputs = inputs
        self.n = inputs[0].shape[0]
        self.key = key
        self.future = future
        self.index = index
        self.t_submit = time.monotonic()
        self.t_entry = self.t_submit if t_entry is None else t_entry
        self.deadline = deadline
        self.retries = 0
        self.t_sealed = self.t_built = self.t_sent = self.t_submit


class ServingEngine:
    """Continuous micro-batching over ``apply_fn(params, *inputs)``.

    - requests with identical non-leading shapes/dtypes (the program *key* —
      e.g. one sequence-width bucket) coalesce into micro-batches, padded to
      the next power-of-two ≤ ``max_batch`` (padding repeats row 0; sliced
      off per request), oldest key first;
    - requests larger than ``max_batch`` are chunked and reassembled;
    - ``max_inflight`` dispatches are kept outstanding — assembling batch
      *i+1* overlaps the device computing batch *i*;
    - ``warmup(*example)`` compiles every batch bucket for an input
      signature ahead of time, so steady-state serving never compiles;
    - ``compute_dtype`` casts floating params (once) and inputs (per batch)
      — the bf16 serving path; leave None on the f32 parity path;
    - on TPU, input buffers are donated to XLA (ping-pong staging).

    Telemetry: every engine publishes ``serving_*`` instruments (labeled
    ``engine=<name>``) to the metrics registry — request/row/batch/padding
    counters, queue-depth and in-flight gauges, admission→dispatch wait and
    per-bucket latency histograms, compile events. ``heartbeat_deadline_s``
    arms a dispatch heartbeat: if no dispatch completes within the deadline
    while work is in flight (the wedged-tunnel signature), ``/healthz`` flips
    unhealthy and a diagnostic snapshot (thread stacks + queue state) is
    dumped instead of the loop hanging silently. ``selfprofile_every`` > 0
    turns on the in-loop device-trace watchdog every that-many micro-batches.
    ``stats()`` remains as a locked, deep-copied per-instance snapshot (the
    registry is the cross-engine aggregate).

    ``apply_fn`` must treat examples independently along the leading axis
    (true of every model here) and be deterministic (dropout off).
    """

    # pitlint PIT-LOCK (analysis/rules_locks.py): these attributes are shared
    # between the submit/caller threads and the worker — every touch outside
    # __init__ must sit inside `with self.<lock>` (lock-free fast paths carry
    # an inline pragma with their reasoning)
    _guarded_by = {
        "_stats": "_stats_lock",
        "_dispatch_seq": "_stats_lock",
        "_backlog": "_stats_lock",
        "_assembling": "_stats_lock",
        "_pending_params": "_params_lock",
        "_params_version": "_params_lock",
        "_params_staged": "_params_lock",
        "_aot_programs": "_aot_lock",
        "_aot_claims": "_aot_lock",
    }

    def __init__(
        self,
        apply_fn: Callable[..., Any],
        params,
        max_batch: int = 64,
        max_delay_ms: float = 0.0,
        max_inflight: int = 2,
        compute_dtype: Optional[str] = None,
        quantize: Optional[str] = None,
        group_size: Optional[int] = None,
        donate_inputs: Optional[bool] = None,
        name: str = "serve",
        registry: Optional[obs.MetricsRegistry] = None,
        heartbeat_deadline_s: Optional[float] = None,
        selfprofile_every: int = 0,
        request_deadline_s: Optional[float] = None,
        queue_limit: Optional[int] = None,
        dispatch_retries: int = 2,
        retry_policy: Optional[RetryPolicy] = None,
        breaker_failures: int = 0,
        breaker_cooldown_s: float = 5.0,
        compile_cache=None,
        cache_salt: str = "",
        slo: Optional[obs.SLO] = None,
        slo_window: int = 4096,
        span_every: int = 1,
        trace_sample: float = 1.0,
    ):
        import jax
        import jax.numpy as jnp

        from perceiver_io_tpu.quant import is_quantized, kernel_operands

        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_inflight < 1:
            raise ValueError(f"max_inflight must be >= 1, got {max_inflight}")
        if request_deadline_s is not None and request_deadline_s <= 0:
            raise ValueError(
                f"request_deadline_s must be positive, got {request_deadline_s}"
            )
        if queue_limit is not None and queue_limit < 1:
            raise ValueError(f"queue_limit must be >= 1, got {queue_limit}")
        self.max_batch = max_batch
        self.max_delay = max_delay_ms / 1e3
        self.max_inflight = max_inflight
        self.name = name
        self.request_deadline_s = request_deadline_s
        self.queue_limit = queue_limit
        self._retry_policy = (
            retry_policy if retry_policy is not None
            else RetryPolicy(max_retries=max(0, int(dispatch_retries)))
        )
        compute_dtype, quantize = resolve_params_mode(compute_dtype, quantize)
        if is_quantized(params):
            # a pre-quantized tree (MLMServer shares ONE across its engines)
            # implies the mode; its baked compute dtype is validated in
            # _prepare_params — which also guards update_params, so a later
            # hot-swap cannot slip in a mismatched tree either
            quantize = params.mode
            group_size = params.group_size
        if quantize == "int4" and group_size is None:
            # pin the effective group size at construction so the mode
            # guard in _prepare_params can demand exact equality — a
            # hot-swap with a different grouping changes the treedef and
            # would recompile every warmed bucket program
            from perceiver_io_tpu.quant import DEFAULT_GROUP_SIZE

            group_size = DEFAULT_GROUP_SIZE
        self.quantize = quantize
        self.group_size = group_size
        self._compute_dtype = (
            None if compute_dtype is None else jnp.dtype(compute_dtype)
        )
        if donate_inputs is None:
            # donation is a TPU/GPU runtime feature; on CPU XLA ignores it
            # with a warning per program
            donate_inputs = jax.default_backend() == "tpu"
        self.donate_inputs = donate_inputs

        self._params_lock = threading.Lock()
        self._pending_params = None
        # update_params ordering (both under the lock): _params_version hands
        # out call-order tickets, _params_staged records the newest ticket
        # whose PREPARED tree actually staged — a failing preparation never
        # consumes its ticket, so it cannot cancel a concurrent valid update
        self._params_version = 0
        self._params_staged = 0
        self.params = self._prepare_params(params)

        self._apply_fn = apply_fn

        def call(p, inputs):
            if is_quantized(p):
                # traced inside the jit: quantized kernels travel as QKernel
                # operands to the linear_apply sites, where the fused
                # dequant-matmul (TPU) or the XLA-fused dequant (elsewhere)
                # streams the int8/int4 bytes (ops/pallas_matmul.py)
                p = kernel_operands(p)
            return apply_fn(p, *inputs)

        self._call = call
        self._jitted = jax.jit(
            call, donate_argnums=(1,) if donate_inputs else ()
        )

        self._queue: "queue.Queue[_Part]" = queue.Queue()
        # program-key → deque of pending parts; dict order = arrival order of
        # the oldest pending part per key (FIFO across keys)
        self._pending: Dict[Any, deque] = {}
        self._programs: set = set()  # (key, bucket) pairs ever dispatched

        # per-instance stats live behind ONE lock (they are written from the
        # submit/caller threads AND the worker); stats() deep-copies under it
        self._stats_lock = threading.Lock()
        self._stats: Dict[str, Any] = {
            "requests": 0, "rows": 0, "batches": 0, "padded_rows": 0,
            "latency_s_by_bucket": {},
            # per-phase latency windows, written at completion under this
            # same lock so stats() snapshots latency AND its attribution in
            # one consistent read (external pollers never see torn phases)
            "phase_s": {},
        }
        self._dispatch_seq = 0  # StepTraceAnnotation ids (under _stats_lock)
        self._inflight_count = 0  # worker-written, racily read by diagnostics

        self.registry = registry if registry is not None else obs.get_registry()
        labels = {"engine": name}
        reg = self.registry
        self._m_requests = reg.counter(
            "serving_requests_total", "requests submitted", labels)
        self._m_rows = reg.counter(
            "serving_rows_total", "request rows served", labels)
        self._m_batches = reg.counter(
            "serving_batches_total", "micro-batches dispatched", labels)
        self._m_padded = reg.counter(
            "serving_padded_rows_total",
            "padded filler rows (bucket waste)", labels)
        self._m_compiles = reg.counter(
            "serving_compile_events_total",
            "new (signature, batch-bucket) programs entered (one XLA compile "
            "unless warmed — or a zero-compile disk deserialize when the AOT "
            "cache hits; aot_cache_hits_total tells the two apart)", labels)
        self._m_queue = reg.gauge(
            "serving_queue_depth", "parts awaiting batch formation", labels)
        self._m_inflight = reg.gauge(
            "serving_inflight_dispatches", "dispatches in flight", labels)
        self._m_programs = reg.gauge(
            "serving_programs", "distinct compiled programs", labels)
        self._m_occupancy = reg.histogram(
            "serving_batch_occupancy",
            "real rows / bucket rows per micro-batch (1.0 = no padding)",
            labels)
        self._m_wait = reg.histogram(
            "serving_admission_wait_seconds",
            "submit → dispatch wait per request part", labels)
        self._latency_hists: Dict[int, obs.Histogram] = {}
        # per-request phase attribution: "p99 is high" becomes "p99 is high
        # because admission wait, not device time" — one histogram per
        # lifecycle phase, observed at completion from the part's timestamps
        self._m_phase = {
            phase: reg.histogram(
                "serving_phase_seconds",
                "per-request-part time in each lifecycle phase "
                "(admission|queue|assembly|dispatch|device|complete; the "
                "phase sum reconciles with serving_latency_seconds)",
                {**labels, "phase": phase})
            for phase in PHASES
        }
        self._m_phase_ratio = reg.gauge(
            "serving_phase_sum_ratio",
            "phase-sum / end-to-end latency of the last completed part "
            "(the tracing self-check: ~1.0 when the phases partition the "
            "request lifetime)", labels)
        shed_help = "requests/parts shed instead of served, by reason"
        self._m_shed = {
            reason: reg.counter("serving_shed_total", shed_help,
                                {**labels, "reason": reason})
            for reason in ("queue_full", "breaker_open", "deadline", "draining")
        }
        self._m_retries = reg.counter(
            "serving_dispatch_retries_total",
            "transient micro-batch re-dispatch cycles", labels)
        self._backlog = 0  # parts admitted but not yet dispatched/shed
                           # (written under _stats_lock)
        self._assembling = 0  # parts the worker has popped from the backlog
                              # but not yet dispatched/shed/failed — closes
                              # the drain() window between the backlog
                              # decrement and the in-flight increment
                              # (written under _stats_lock)

        # zero-recompile cold start (perceiver_io_tpu.aot): when a cache is
        # attached, every bucket program dispatches through an AOT-compiled
        # executable — loaded from disk on a fingerprint hit, compiled (and
        # persisted) otherwise. _aot_claims dedups concurrent builds of the
        # same program (background warmup racing the worker's on-demand path).
        self._cache = resolve_cache(compile_cache, registry=reg)
        self._cache_salt = cache_salt
        self._aot_lock = threading.Lock()
        self._aot_programs: Dict[Any, Any] = {}
        self._aot_claims: Dict[Any, threading.Event] = {}
        self._fp_base = None  # lazy: needs the backend up
        # every live warmup's handle (one per warmup() call — e.g. one per
        # signature): close() must cancel+join ALL of them, not just the
        # newest, or an earlier signature's thread outlives the engine
        self._warmup_handles: List[WarmupHandle] = []
        self._m_ready = reg.gauge(
            "engine_ready",
            "1 once the last requested warmup family is fully "
            "compiled/loaded; 0 while cold or warming", labels)
        self._m_warmup_s = reg.gauge(
            "serving_warmup_seconds",
            "wall seconds the last warmup took (cache hits make this "
            "near-zero)", labels)

        self.breaker: Optional[CircuitBreaker] = None
        if breaker_failures > 0:
            self.breaker = CircuitBreaker(
                name=name, failure_threshold=breaker_failures,
                cooldown_s=breaker_cooldown_s, registry=reg,
            )

        # declarative objective: every completion/shed classifies against it,
        # burn-rate gauges ride the registry and healthz() (obs/slo.py)
        self.slo_tracker: Optional[obs.SLOTracker] = None
        if slo is not None:
            # slo_window bounds the classification window (burn rate =
            # recent behavior): a smaller window makes the burn gauge — and
            # any alert rule over it — track episode boundaries faster
            self.slo_tracker = obs.SLOTracker(slo, registry=reg,
                                              labels=labels,
                                              window=slo_window)

        # untraced JSONL request_phases spans sample every Nth part (the
        # registry histograms keep the full-rate view regardless); TRACED
        # parts instead spool compact rows that flush as ONE record per
        # _TRACE_SPOOL_ROWS completions (or at the first idle moment /
        # worker exit), so full tracing amortizes its serialization the
        # way the dispatch amortizes everything else
        self._span_every = max(1, int(span_every))
        self._trace_spool: List[list] = []  # worker-thread-only
        self._span_seq = 0  # worker-thread-only
        # distributed tracing: requests arriving WITHOUT a propagated
        # context (single-process serving) mint their own root at this
        # head-sampling rate once an event log is configured; propagated
        # contexts (the replica shim) carry the router's decision instead
        self._trace_sample = float(trace_sample)

        self.heartbeat = obs.Heartbeat(
            f"{name}-dispatch", deadline_s=heartbeat_deadline_s,
            diagnostics=self._diagnostics,
            # a wedged dispatch never FAILS — only the stall monitor can see
            # it; tripping the breaker makes submission fast-fail while the
            # worker is stuck inside the hung device call
            on_stall=(
                (lambda: self.breaker.trip("heartbeat stall (wedged dispatch)"))
                if self.breaker is not None else None
            ),
        )
        self._profiler: Optional[obs.SelfProfiler] = None
        if selfprofile_every > 0:
            self._profiler = obs.SelfProfiler(
                every_n=selfprofile_every, prefix=name, registry=reg
            )

        self._crash: Optional[BaseException] = None
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"{name}-engine", daemon=True
        )
        self._thread.start()

    # -- params preparation / hot swap ---------------------------------------

    def _prepare_params(self, params):
        """:func:`prepare_param_tree` under this engine's mode + device_put.

        Guards BOTH construction and ``update_params``: a pre-quantized tree
        must match this engine's mode exactly — a mismatched baked compute
        dtype (or a quantized tree handed to a non-quantized engine) would
        silently serve a different precision than advertised AND change the
        params treedef, recompiling every warmed bucket program mid-serving.
        """
        import jax
        import jax.numpy as jnp

        from perceiver_io_tpu.quant import is_quantized

        if is_quantized(params):
            want = str(jnp.dtype(self._compute_dtype or jnp.float32))
            if (self.quantize != params.mode
                    or params.compute_dtype != want
                    or self.group_size != params.group_size):
                raise ValueError(
                    f"pre-quantized params (mode={params.mode!r}, "
                    f"compute_dtype={params.compute_dtype!r}, group_size="
                    f"{params.group_size}) do not match this engine's mode "
                    f"(quantize={self.quantize!r}, compute_dtype={want!r}, "
                    f"group_size={self.group_size}) — re-quantize under the "
                    "engine's mode or pass the raw f32 tree"
                )
        return jax.device_put(
            prepare_param_tree(params, self._compute_dtype, self.quantize,
                               self.group_size)
        )

    def update_params(self, params) -> None:
        """Hot-swap the served parameters without recompiling.

        Preparation (the same cast/quantize as construction — hand in the
        raw f32 tree, not a pre-cast copy) runs on the CALLER thread; the
        finished tree is installed atomically by the worker between
        micro-batches. Requests arriving while a (re)quantization is in
        progress therefore queue normally and are served with whichever
        complete tree is installed at their dispatch — never a torn one.
        In-flight dispatches finish on the old params. As long as the new
        tree matches the old structure/shapes/dtypes (same checkpoint
        family), the warmed bucket programs are reused without recompiling.

        Concurrent calls resolve in CALL order, not prepare-completion
        order: each call takes a version ticket up front and only stages its
        tree if no NEWER call has already staged — a slow (re)quantization
        of an older tree can never overwrite a newer one, and a call whose
        preparation RAISES (e.g. a mismatched pre-quantized tree) never
        consumes its ticket, so it cannot cancel a concurrent valid update.
        """
        if self._stop.is_set():
            raise self._closed_error("update_params()")
        with self._params_lock:
            self._params_version += 1
            version = self._params_version
        prepared = self._prepare_params(params)  # may raise: nothing consumed
        with self._params_lock:
            if version < self._params_staged:
                return  # a newer update_params call already staged its tree
            self._params_staged = version
            self._pending_params = prepared
        obs.event("engine_params_update_staged", engine=self.name)

    def _install_pending_params(self) -> None:
        """Worker-only: adopt a staged param tree between micro-batches."""
        # lock-free fast path on the per-batch hot loop: a stale None read
        # just defers the install one micro-batch; the adopt re-reads locked
        if self._pending_params is None:  # pitlint: ignore[PIT-LOCK] racy-None fast path, install re-reads under the lock
            return
        with self._params_lock:
            pending, self._pending_params = self._pending_params, None
        self.params = pending
        obs.event("engine_params_update", engine=self.name)

    # -- submission ----------------------------------------------------------

    def _closed_error(self, verb: str = "submit()") -> EngineClosed:
        """EngineClosed naming WHY the engine is closed; a worker crash is
        chained as ``__cause__`` so post-crash callers see the root error,
        not just 'closed'."""
        if self._crash is not None:
            err = EngineClosed(
                f"{verb} on a crashed engine (worker died: "
                f"{type(self._crash).__name__}: {self._crash})"
            )
            err.__cause__ = self._crash
            return err
        return EngineClosed(f"{verb} on a closed engine")

    def _slo_bad(self, n: int = 1) -> None:
        """Shed/failed work counts against the SLO's error budget. The unit
        is the PART (what completions record); admission-time refusals that
        happen before the request is chunked (breaker open, pre-expired
        deadline) record one sample — their part count does not exist yet."""
        if self.slo_tracker is not None:
            for _ in range(n):
                self.slo_tracker.record(ok=False)

    def submit(self, *inputs, transform: Optional[Callable] = None,
               deadline_s: Optional[float] = None,
               trace: Optional[obs.TraceContext] = None) -> _Future:
        """Enqueue one request (arrays sharing a leading batch axis); returns
        a future whose ``result()`` is the output pytree sliced to this
        request's rows (numpy, on host).

        ``deadline_s`` (default: the engine's ``request_deadline_s``) bounds
        how long the request may wait for a dispatch: an expired request is
        shed with :class:`DeadlineExceeded` at admission or batch assembly
        instead of occupying the queue as dead work. Admission can also
        fast-fail with :class:`RejectedError` (queue full) or
        :class:`BreakerOpen` (device presumed down).

        ``trace`` joins this request to a distributed trace (the replica
        shim propagates the router's context here); with none given and an
        event log configured, a fresh root is minted (head sampling via the
        engine's ``trace_sample``) — single-process serving traces too.
        Traced parts always emit their engine span, riding the compact
        per-micro-batch ``request_phases_batch`` record (``span_every``
        sampling applies only to untraced traffic: a tail-sampled trace
        with a missing engine hop would assemble as a hole).
        """
        t_entry = time.monotonic()
        if trace is None:
            trace = obs.maybe_trace(self._trace_sample)
        if self._stop.is_set():
            raise self._closed_error()
        if self._draining.is_set():
            # graceful drain: already-admitted work keeps flowing, NEW work
            # is refused with the shed-fast semantics of a full queue. The
            # refusal is deliberately NOT an SLO breach: the tier above (the
            # serving router, a supervisor restart) re-routes it — the
            # request is displaced, not lost.
            self._m_shed["draining"].inc()
            raise RejectedError(
                f"engine {self.name!r} is draining — not admitting new work"
            )
        if self.breaker is not None and not self.breaker.allow():
            self._m_shed["breaker_open"].inc()
            self._slo_bad()
            raise BreakerOpen(
                f"engine {self.name!r}: circuit breaker open "
                f"(device presumed down; cooldown {self.breaker.cooldown_s:g}s)"
            )
        if deadline_s is None:
            deadline_s = self.request_deadline_s
        if deadline_s is not None and deadline_s <= 0:
            self._m_shed["deadline"].inc()
            self._slo_bad()
            raise DeadlineExceeded(
                f"request deadline {deadline_s}s already expired at admission"
            )
        arrays = [np.asarray(x) for x in inputs]
        if not arrays:
            raise ValueError("submit() needs at least one input array")
        n = arrays[0].shape[0]
        if any(a.shape[0] != n for a in arrays):
            raise ValueError("all inputs must share the leading batch axis")
        if n == 0:
            fut = _Future(1, transform, trace=trace)
            fut._deliver(0, self._empty_result(arrays))
            return fut
        starts = list(range(0, n, self.max_batch))
        # backlog is tracked unconditionally (diagnostics read it); the
        # bound is only ENFORCED when queue_limit is set
        with self._stats_lock:
            if (self.queue_limit is not None
                    and self._backlog + len(starts) > self.queue_limit):
                backlog = self._backlog
                admitted = False
            else:
                self._backlog += len(starts)
                admitted = True
        if not admitted:
            self._m_shed["queue_full"].inc()
            # per PART, the same unit completions record at — a shed 4-part
            # request must weigh as much in the burn rate as a served one
            self._slo_bad(len(starts))
            raise RejectedError(
                f"engine {self.name!r}: queue full ({backlog} parts "
                f"backlogged, limit {self.queue_limit}) — request shed"
            )
        fut = _Future(len(starts), transform, trace=trace)
        deadline = (
            None if deadline_s is None else time.monotonic() + deadline_s
        )
        with self._stats_lock:
            self._stats["requests"] += 1
        self._m_requests.inc()
        for index, start in enumerate(starts):
            chunk = [a[start: start + self.max_batch] for a in arrays]
            self._queue.put(
                _Part(chunk, self._key(chunk), fut, index, deadline=deadline,
                      t_entry=t_entry)
            )
        self._m_queue.set(self._queue.qsize())
        if self._stop.is_set() and not self._thread.is_alive():
            # raced a shutdown/worker-crash: the drain already ran, so these
            # parts would sit unread forever — fail the future ourselves
            fut._fail(self._closed_error("request queued"))
        return fut

    def predict(self, *inputs, timeout: Optional[float] = None):
        """Synchronous submit + result."""
        return self.submit(*inputs).result(timeout=timeout)

    def _key(self, arrays: Sequence[np.ndarray]):
        return tuple((a.shape[1:], str(a.dtype)) for a in arrays)

    def _empty_result(self, arrays: Sequence[np.ndarray]):
        """n=0 request: pytree of empty arrays via eval_shape (no device)."""
        import jax

        ones = tuple(
            self._cast(np.zeros((1, *a.shape[1:]), a.dtype)) for a in arrays
        )
        shapes = jax.eval_shape(self._call, self.params, ones)
        return jax.tree.map(
            lambda s: np.zeros((0, *s.shape[1:]), s.dtype), shapes
        )

    # -- warmup --------------------------------------------------------------

    def warmup(self, *example_inputs,
               buckets: Optional[Sequence[int]] = None,
               background: bool = False):
        """Ready every batch bucket for this input signature (row 0 of
        ``example_inputs``, tiled) ahead of traffic — from the AOT cache when
        one is attached (deserialize, zero compiles), compiling otherwise.
        One call per distinct signature — e.g. per serving width bucket —
        and steady state never compiles.

        Blocking (default): returns the warmed bucket list, raising on
        error — the historical contract. ``background=True`` returns a
        :class:`WarmupHandle` immediately and warms on a daemon thread in
        PRIORITY order (smallest bucket first, so a lone request is
        servable as soon as bucket 1 lands); traffic may be submitted right
        away — a request whose program is mid-build rides the warmup
        thread's build (cache mode) or compiles on demand.
        """
        arrays = [np.asarray(x) for x in example_inputs]
        if any(a.shape[0] < 1 for a in arrays):
            raise ValueError("warmup needs at least one example row")
        if buckets is None:
            buckets, b = [], 1
            while b < self.max_batch:
                buckets.append(b)
                b *= 2
            buckets.append(self.max_batch)
        # ascending = priority order: the small buckets unblock first traffic
        buckets = sorted({bucket_size(int(b), self.max_batch) for b in buckets})
        handle = WarmupHandle()
        # prune finished handles so a long-lived engine's list stays flat
        self._warmup_handles = [
            h for h in self._warmup_handles if not h.done()
        ] + [handle]
        self._m_ready.set(0.0)
        if background:
            thread = threading.Thread(
                target=self._warm_buckets, args=(arrays, buckets, handle),
                name=f"{self.name}-warmup", daemon=True,
            )
            handle._threads.append(thread)
            thread.start()
            return handle
        self._warm_buckets(arrays, buckets, handle)
        return handle.wait()

    def _warm_buckets(self, arrays: List[np.ndarray], buckets: List[int],
                      handle: WarmupHandle) -> None:
        """Warm ``buckets`` for one signature, smallest first; finishes (or
        fails) ``handle`` and publishes readiness + duration gauges."""
        import jax

        t0 = time.monotonic()
        key = self._key([a[:1] for a in arrays])
        warmed: List[int] = []
        try:
            for b in buckets:
                if self._crash is not None:
                    # a crashed engine must FAIL the warmup, not report a
                    # truncated bucket list as success (blocking callers
                    # treat the return as 'warm')
                    raise self._closed_error("warmup()")
                if handle.cancelled():
                    break
                cols = tuple(
                    self._cast(np.ascontiguousarray(
                        np.broadcast_to(a[:1], (b, *a.shape[1:]))
                    ))
                    for a in arrays
                )
                out = self._execute(cols, b, key)
                jax.block_until_ready(out)
                warmed.append(b)
        except BaseException as e:
            self._m_warmup_s.set(time.monotonic() - t0)
            obs.event("serving_warmup_failed", engine=self.name,
                      error=type(e).__name__, warmed=warmed)
            handle._fail(e)
            return
        elapsed = time.monotonic() - t0
        self._m_warmup_s.set(elapsed)
        if warmed == buckets:
            self._m_ready.set(1.0)
        obs.event("serving_warmup", engine=self.name, buckets=warmed,
                  seconds=round(elapsed, 3),
                  cached=self._cache is not None)
        handle._finish(warmed)

    # -- worker --------------------------------------------------------------

    def _run(self) -> None:
        inflight: deque = deque()  # ((device_out, bucket), parts)

        def _sync_inflight() -> None:
            # watchdog window close: the trace must not stop while dispatches
            # are still executing — truncated trailing step windows would
            # bias the lower-quartile device number low
            import jax

            for (out, _bucket), _parts in list(inflight):
                jax.block_until_ready(out)

        def _note_inflight() -> None:
            self._inflight_count = len(inflight)
            self._m_inflight.set(len(inflight))
            if inflight:
                self.heartbeat.arm()
            else:
                self.heartbeat.disarm()

        try:
            while True:
                self._install_pending_params()
                parts = None
                if len(inflight) < self.max_inflight:
                    # while dispatches are in flight this poll is
                    # non-blocking: the device working IS the micro-batching
                    # window
                    parts = self._next_batch(0.0 if inflight else _IDLE_POLL_S)
                if parts is not None:
                    with self._stats_lock:
                        self._backlog -= len(parts)
                        self._assembling += len(parts)
                    try:
                        # assembly-side deadline enforcement: a part whose
                        # caller already gave up must not burn a dispatch
                        live = self._shed_expired(parts)
                        if live:
                            # armed BEFORE the dispatch call: a wedged tunnel
                            # can hang the dispatch itself, not just the
                            # completion
                            self.heartbeat.arm()
                            try:
                                inflight.append((self._dispatch(live), live))
                            except BaseException as e:  # bad batch
                                self._batch_failed(live, e, where="dispatch")
                            _note_inflight()
                    finally:
                        # only AFTER the parts are accounted elsewhere
                        # (in-flight, shed, failed, or re-queued) — a
                        # concurrent drain() poll never sees a false-empty
                        # window mid-assembly
                        with self._stats_lock:
                            self._assembling -= len(parts)
                    if live and self._profiler is not None:
                        self._profiler.tick(sync=_sync_inflight)
                    continue
                if inflight:
                    self._complete(*inflight.popleft())
                    self.heartbeat.beat()
                    _note_inflight()
                    continue
                # idle (nothing in flight, nothing sealed): any spooled
                # traced span rows land now rather than waiting out the
                # next saturated stretch — and before worker exit below
                self._flush_trace_spool()
                if (self._stop.is_set() and self._queue.empty()
                        and not self._pending):
                    return
        except BaseException as e:
            # the worker must never die with futures outstanding — a caller
            # blocked in result() with no timeout would hang forever. Fail
            # everything queued/pending/in flight, record the cause (so
            # submit() raises EngineClosed chained from it), stop accepting.
            self._crash = e
            self._stop.set()
            self.heartbeat.disarm()
            try:
                # completed work's spans are valid telemetry even when the
                # worker dies — land them (best effort) before failing out
                self._flush_trace_spool()
            except Exception:
                pass
            obs.event("engine_worker_crash", engine=self.name,
                      error=type(e).__name__)
            for _, parts in inflight:
                for p in parts:
                    p.future._fail(e)
            for dq in self._pending.values():
                for p in dq:
                    p.future._fail(e)
            self._pending.clear()
            while True:
                try:
                    self._queue.get_nowait().future._fail(e)
                except queue.Empty:
                    break
            with self._stats_lock:
                self._backlog = 0
                self._assembling = 0
            raise

    def _flush_trace_spool(self) -> None:
        """Worker-only: land the spooled traced span rows as one
        ``request_phases_batch`` record — ``parts`` is the ";"-joined
        packed rows (the assembler expands each back into an engine span
        + six phase children)."""
        if self._trace_spool:
            rows, self._trace_spool = self._trace_spool, []
            obs.event("request_phases_batch", engine=self.name,
                      parts=";".join(rows))

    def _shed_expired(self, parts: List[_Part]) -> List[_Part]:
        """Worker-only: drop parts whose deadline passed; their futures fail
        with :class:`DeadlineExceeded` (a terminal result — the caller's
        ``result(timeout=)`` has almost certainly given up already, and the
        part must not occupy a dispatch)."""
        now = time.monotonic()
        alive = []
        for p in parts:
            if p.deadline is not None and now >= p.deadline:
                self._m_shed["deadline"].inc()
                self._slo_bad()
                obs.event("engine_request_shed", engine=self.name,
                          reason="deadline",
                          waited_s=round(now - p.t_submit, 4))
                p.future._fail(DeadlineExceeded(
                    f"request deadline expired before dispatch "
                    f"(waited {now - p.t_submit:.3f}s in engine "
                    f"{self.name!r})"
                ))
            else:
                alive.append(p)
        return alive

    def _batch_failed(self, parts: List[_Part], error: BaseException,
                      where: str) -> None:
        """Worker-only: a micro-batch dispatch (or its completion fetch)
        raised. Transient errors re-queue the parts — with backoff, at the
        front of their key's line — up to the retry budget, so one flaky
        dispatch no longer fails every rider's future; fatal errors (and
        exhausted budgets) fail the futures and feed the breaker."""
        if self.breaker is not None:
            self.breaker.record_failure(error)
        policy = self._retry_policy
        retries = parts[0].retries
        if (retries < policy.max_retries and is_transient(error)
                and not self._stop.is_set()):
            for p in parts:
                p.retries += 1
            self._m_retries.inc()
            with self._stats_lock:
                self._backlog += len(parts)  # back into the admission count
            pause = policy.backoff_s(retries + 1)
            obs.event("engine_dispatch_retry", engine=self.name, where=where,
                      error=type(error).__name__, retry=retries + 1,
                      backoff_s=round(pause, 4))
            if pause > 0:
                self._stop.wait(pause)
            # front of the key's deque: retried work keeps its place in line
            self._pending.setdefault(parts[0].key, deque()).extendleft(
                reversed(parts)
            )
            return
        obs.event("engine_batch_failed", engine=self.name, where=where,
                  error=type(error).__name__, retries=retries)
        self._slo_bad(len(parts))
        for p in parts:
            p.future._fail(error)

    def _absorb(self, part: _Part) -> None:
        self._pending.setdefault(part.key, deque()).append(part)

    def _rows_pending(self, key) -> int:
        return sum(p.n for p in self._pending.get(key, ()))

    def _next_batch(self, timeout: float) -> Optional[List[_Part]]:
        """Collect the next micro-batch: drain the queue into per-key pending
        lists, wait up to ``max_delay`` for the oldest key to fill (skipped
        when 0 — pure continuous batching), then seal whole parts of the
        oldest key up to ``max_batch`` rows."""
        if not self._pending:
            try:
                self._absorb(self._queue.get(timeout=timeout))
            except queue.Empty:
                return None
        deadline = time.monotonic() + self.max_delay
        while True:
            try:
                while True:  # non-blocking drain of everything queued now
                    self._absorb(self._queue.get_nowait())
            except queue.Empty:
                pass
            key = next(iter(self._pending))
            if self._rows_pending(key) >= self.max_batch:
                break
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                self._absorb(self._queue.get(timeout=remaining))
            except queue.Empty:
                break
        key = next(iter(self._pending))
        dq = self._pending[key]
        parts, total = [], 0
        while dq and total + dq[0].n <= self.max_batch:
            part = dq.popleft()
            parts.append(part)
            total += part.n
        if not dq:
            del self._pending[key]
        else:
            # round-robin across keys: a key with work left over goes to the
            # BACK of the dict order, so sustained load on one program key
            # cannot starve requests queued under another
            self._pending[key] = self._pending.pop(key)
        return parts

    def _cast(self, a: np.ndarray):
        if self._compute_dtype is not None and np.issubdtype(
            a.dtype, np.floating
        ):
            return a.astype(self._compute_dtype)
        return a

    def _execute(self, cols: Tuple[np.ndarray, ...], bucket: int, key):
        import jax

        program = (key, bucket)
        with self._stats_lock:  # warmup (caller thread) races the worker
            is_new = program not in self._programs
            if is_new:
                self._programs.add(program)
            self._dispatch_seq += 1
            step_num = self._dispatch_seq
        if is_new:
            self._m_compiles.inc()
            self._m_programs.set(len(self._programs))
            obs.event("serving_compile", engine=self.name, bucket=bucket,
                      programs=len(self._programs))
        fn = (
            self._jitted if self._cache is None
            else self._aot_program(program, cols)
        )
        with jax.profiler.StepTraceAnnotation(
            self.name, step_num=step_num
        ):
            try:
                return fn(self.params, cols)
            except ValueError as e:
                # an update_params() that changed the param PLACEMENT (not
                # the avals — those are validated) invalidates an AOT
                # executable lowered for the old shardings: rebuild against
                # the current placement (new fingerprint → correct entry)
                if (self._cache is None
                        or "Compiled object called with input" not in str(e)):
                    raise
                with self._aot_lock:
                    self._aot_programs.pop(program, None)
                return self._aot_program(program, cols)(self.params, cols)

    # -- AOT program cache (perceiver_io_tpu.aot) ----------------------------

    def _aot_program(self, program, cols: Tuple[np.ndarray, ...]):
        """The compiled executable for ``program`` — from memory, the disk
        cache, or a fresh compile (which is then persisted). Concurrent
        requests for the same program (background warmup vs the worker's
        on-demand path, or two warmup threads) build it ONCE: the first
        caller claims the build, the rest wait on its event."""
        while True:
            with self._aot_lock:
                compiled = self._aot_programs.get(program)
                if compiled is not None:
                    return compiled
                claim = self._aot_claims.get(program)
                if claim is None:
                    claim = threading.Event()
                    self._aot_claims[program] = claim
                    break  # this thread owns the build
            claim.wait()  # owner finished (or failed) — re-check / re-claim
        try:
            compiled = self._build_aot_program(cols)
            with self._aot_lock:
                self._aot_programs[program] = compiled
            return compiled
        finally:
            # on failure the claim is simply released: the error propagates
            # to this caller, and waiters re-claim (retrying the build)
            with self._aot_lock:
                self._aot_claims.pop(program, None)
            claim.set()

    def _build_aot_program(self, cols: Tuple[np.ndarray, ...]):
        import jax

        def sds(x):
            # committed params (e.g. NamedSharding from a mesh-restored
            # checkpoint) must compile AND fingerprint with their placement:
            # a Compiled object rejects inputs whose sharding differs from
            # what it was lowered for
            return jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None)
            )

        avals = jax.tree.map(sds, (self.params, tuple(cols)))
        fp = aot_fingerprint(self._fingerprint_base(), avals=avals,
                             extra=self._fp_sources)
        compiled = self._cache.load(fp)
        if compiled is None:
            compiled = self._jitted.lower(*avals).compile()
            self._cache.store(fp, compiled)
        return compiled

    def _fingerprint_base(self) -> Dict[str, Any]:
        """Static (per-engine) half of every program fingerprint; computed
        once, after the backend is up."""
        if self._fp_base is None:
            base = dict(environment_fingerprint())
            base.update(
                donate=self.donate_inputs,
                quantize=str(self.quantize),
                group_size=str(self.group_size),
                compute_dtype=str(self._compute_dtype),
                salt=self._cache_salt,
            )
            # apply_fn identity: source text + closure reprs (model
            # hyperparameters ride the flax module repr)
            self._fp_sources = tuple(callable_sources(self._apply_fn))
            self._fp_base = base
        return self._fp_base

    def _dispatch(self, parts: List[_Part]):
        t_sealed = time.monotonic()  # the micro-batch is decided: queue ends
        for p in parts:
            p.t_sealed = t_sealed
        faults.inject("engine.dispatch")  # chaos hook: no-op unless installed
        # per-engine site: multi-replica chaos drills target ONE replica's
        # dispatch path (`engine.dispatch.<name>`) without perturbing the
        # generic site's call counts
        faults.inject(f"engine.dispatch.{self.name}")
        n = sum(p.n for p in parts)
        bucket = bucket_size(n, self.max_batch)
        num_inputs = len(parts[0].inputs)
        cols = []
        for i in range(num_inputs):
            col = (
                parts[0].inputs[i] if len(parts) == 1
                else np.concatenate([p.inputs[i] for p in parts], axis=0)
            )
            if bucket > n:  # padding repeats row 0; sliced off at completion
                col = np.concatenate(
                    [col, np.broadcast_to(col[:1], (bucket - n, *col.shape[1:]))],
                    axis=0,
                )
            cols.append(self._cast(np.ascontiguousarray(col)))
        now = time.monotonic()
        for p in parts:
            self._m_wait.observe(now - p.t_submit)
            p.t_built = now
        out = self._execute(tuple(cols), bucket, parts[0].key)
        t_sent = time.monotonic()
        for p in parts:
            p.t_sent = t_sent
        with self._stats_lock:
            self._stats["batches"] += 1
            self._stats["rows"] += n
            self._stats["padded_rows"] += bucket - n
        self._m_batches.inc()
        self._m_rows.inc(n)
        self._m_padded.inc(bucket - n)
        self._m_occupancy.observe(n / bucket)
        self._m_queue.set(self._queue.qsize())
        return out, bucket

    def _latency_hist(self, bucket: int) -> obs.Histogram:
        hist = self._latency_hists.get(bucket)
        if hist is None:
            hist = self.registry.histogram(
                "serving_latency_seconds",
                "submit → result latency by batch bucket",
                {"engine": self.name, "bucket": str(bucket)},
            )
            self._latency_hists[bucket] = hist
        return hist

    def _complete(self, out_bucket, parts: List[_Part]) -> None:
        import jax

        out, bucket = out_bucket
        try:
            faults.inject("engine.complete")  # chaos hook
            faults.inject(f"engine.complete.{self.name}")  # per-engine site
            host = jax.tree.map(np.asarray, jax.device_get(out))
        except BaseException as e:
            self._batch_failed(parts, e, where="complete")
            return
        if self.breaker is not None:
            self.breaker.record_success()
        t_fetched = time.monotonic()  # device phase ends: outputs on host
        hist = self._latency_hist(bucket)
        emit_spans = obs.get_event_log() is not None
        latencies, phase_rows = [], []
        offset = 0
        for p in parts:
            now = time.monotonic()
            # consecutive diffs over the part's timestamps: the phases
            # PARTITION [t_entry, now], so their sum reconciles with the
            # end-to-end latency by construction (self-check below; the sum
            # exceeds e2e by exactly the admission phase, since the latency
            # metric's clock starts at enqueue)
            phases = {
                "admission": p.t_submit - p.t_entry,
                "queue": p.t_sealed - p.t_submit,
                "assembly": p.t_built - p.t_sealed,
                "dispatch": p.t_sent - p.t_built,
                "device": t_fetched - p.t_sent,
                "complete": now - t_fetched,
            }
            e2e = now - p.t_submit
            self._span_seq += 1
            trace = p.future.trace
            traced = trace is not None and trace.sampled
            # an exemplar per 4 observations is plenty of linkage (the
            # ring keeps 8) and keeps the attach off most completions;
            # the SAME trace id lands on the latency histogram and every
            # phase histogram, so a phase-level alert ("p99 queue time is
            # burning") links to the identical assembled trace
            exemplar = (trace.trace_id
                        if traced and self._span_seq & 3 == 0 else None)
            for k, v in phases.items():
                self._m_phase[k].observe(v, exemplar=exemplar)
            if e2e > 0:
                self._m_phase_ratio.set(sum(phases.values()) / e2e)
            # record BEFORE delivering: result() waking the caller is the
            # publication point — a caller reading fut.phases right after
            # result() must find this part's record already there
            p.future._note_phases(phases)
            if self.slo_tracker is not None:
                self.slo_tracker.record(latency_s=e2e, ok=True)
            if emit_spans and traced:
                # each part is one engine span: fresh id under the
                # propagated context, so the assembler hangs the six
                # phases (synthesized children) off the right hop. The
                # row is a PACKED string (comma-separated, integer
                # microseconds, PHASES order — phases is built in that
                # order): the flushed record then carries one long string
                # the writer's json.dumps only escape-scans, instead of
                # ~12 values x 64 rows it would format element-wise. This
                # plus the spool is what keeps full tracing inside the
                # <=2% overhead bar (PERF.md §Tracing)
                ph_a, ph_q, ph_as, ph_d, ph_dev, ph_c = phases.values()
                self._trace_spool.append(
                    f"{trace.trace_id},{obs.new_span_id()},"
                    f"{trace.span_id},{int(p.t_entry * 1e6)},{p.n},"
                    f"{int(ph_a * 1e6)},{int(ph_q * 1e6)},"
                    f"{int(ph_as * 1e6)},{int(ph_d * 1e6)},"
                    f"{int(ph_dev * 1e6)},{int(ph_c * 1e6)},{bucket}"
                )
            elif emit_spans and self._span_seq % self._span_every == 0:
                obs.event("request_phases", engine=self.name, bucket=bucket,
                          rows=p.n, total_s=round(e2e, 6),
                          **{k: round(v, 6) for k, v in phases.items()})
            latencies.append(e2e)
            hist.observe(e2e, exemplar=exemplar)
            phase_rows.append(phases)
            o = offset
            p.future._deliver(
                p.index, jax.tree.map(lambda a: a[o: o + p.n], host)
            )
            offset += p.n
        if len(self._trace_spool) >= _TRACE_SPOOL_ROWS:
            self._flush_trace_spool()
        with self._stats_lock:
            # bounded: an engine serves indefinitely — unbounded per-request
            # float lists would grow without limit; the window is plenty for
            # p50/p95 reporting. Phase rows land under the SAME lock (and in
            # the same order) as the latencies they attribute, so stats()
            # reads a consistent latency+attribution pair.
            lat = self._stats["latency_s_by_bucket"].setdefault(
                bucket, deque(maxlen=4096)
            )
            lat.extend(latencies)
            ph = self._stats["phase_s"]
            for row in phase_rows:
                for k, v in row.items():
                    ph.setdefault(k, deque(maxlen=4096)).append(v)

    # -- replica-facing surface (perceiver_io_tpu.serving) -------------------
    #
    # The router tier consumes exactly this contract from every replica:
    # submit()/predict() for traffic, update_params() for rolling rollout,
    # `ready` for join gating, drain()/resume_admission() for graceful
    # rotation, stats()/the registry gauges for load-aware dispatch.

    @property
    def ready(self) -> bool:
        """True once the last requested warmup family is fully warm (the
        ``engine_ready`` gauge) — what a router's join gate polls before
        admitting a (re)started replica."""
        return self._m_ready.value >= 1.0

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    @property
    def backlog(self) -> int:
        """Parts admitted but not yet dispatched/shed — the queue-depth term
        of a router's least-loaded score."""
        with self._stats_lock:
            return self._backlog

    @property
    def inflight(self) -> int:
        """Micro-batches currently dispatched (racy read, diagnostics-grade)."""
        return self._inflight_count

    @property
    def params_pending(self) -> bool:
        """True while a staged ``update_params`` tree awaits the worker's
        between-batches install (the replica shim's swap RPC answers only
        once this clears, so a rollout's bake window never watches a
        replica that is still serving the OLD tree)."""
        with self._params_lock:
            return self._pending_params is not None

    @property
    def requests_served(self) -> int:
        """Requests admitted over this engine's lifetime (the rollout bake's
        did-traffic-actually-flow check)."""
        with self._stats_lock:
            return self._stats["requests"]

    def drain(self, timeout: Optional[float] = None,
              poll_s: float = 0.01) -> bool:
        """Graceful drain: stop admitting, finish everything already accepted.

        New ``submit()`` calls fail fast with :class:`RejectedError`
        immediately; queued parts and in-flight micro-batches complete
        normally (accepted work is never dropped). Returns True once nothing
        admitted remains un-served, False if ``timeout`` elapsed first (work
        is still in flight — the engine stays draining either way). The
        engine itself stays alive: ``resume_admission()`` re-opens it (the
        rolling-rollout path drains, swaps params, resumes), ``close()``
        detaches it.
        """
        self._draining.set()
        obs.event("engine_drain_begin", engine=self.name)
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._stats_lock:
                backlog = self._backlog + self._assembling
            if (backlog == 0 and self._inflight_count == 0
                    and self._queue.empty()):
                obs.event("engine_drained", engine=self.name)
                return True
            if self._stop.is_set():
                # a closing/crashed engine cannot finish the work; the
                # worker's own shutdown/crash path fails the futures
                return False
            if deadline is not None and time.monotonic() >= deadline:
                obs.event("engine_drain_timeout", engine=self.name,
                          backlog=backlog, inflight=self._inflight_count)
                return False
            time.sleep(poll_s)

    def stop_admission(self) -> None:
        """Close admission without waiting (``drain()`` = this + the wait).
        Multi-engine callers close EVERY door first so a composite request
        can never slip in behind an already-drained sibling — see
        :func:`drain_engines`."""
        self._draining.set()

    def resume_admission(self) -> None:
        """Re-open a drained engine for traffic (the rollout undrain)."""
        self._draining.clear()
        obs.event("engine_drain_end", engine=self.name)

    # -- introspection / lifecycle -------------------------------------------

    @property
    def num_programs(self) -> int:
        """Distinct (signature, batch-bucket) programs dispatched or warmed."""
        return len(self._programs)

    def stats(self) -> Dict[str, Any]:
        """Locked, deep-copied snapshot of this instance's counters.

        The compatibility surface over the registry instruments (which
        aggregate across engines sharing a name): mutating the returned dict
        or its latency lists never touches live state, and the read is
        consistent (taken under the same lock every writer holds).
        """
        with self._stats_lock:
            snap: Dict[str, Any] = {
                k: v for k, v in self._stats.items()
                if k not in ("latency_s_by_bucket", "phase_s")
            }
            snap["latency_s_by_bucket"] = {
                b: list(d)
                for b, d in self._stats["latency_s_by_bucket"].items()
            }
            # same locked deep-copy as the latencies: external pollers (the
            # future router tier) never read torn phase attribution
            snap["phase_s"] = {
                k: list(d) for k, d in self._stats["phase_s"].items()
            }
        return snap

    def _diagnostics(self) -> Dict[str, Any]:
        """Heartbeat-stall snapshot: queue/in-flight state + last-known
        counters (runs on the monitor thread — reads are racy by design;
        a wedged worker cannot be asked to cooperate)."""
        snap = self.stats()
        snap.pop("latency_s_by_bucket", None)
        snap.pop("phase_s", None)
        with self._stats_lock:
            backlog = self._backlog
        return {
            "queue_parts": self._queue.qsize(),
            "pending_keys": len(self._pending),
            "inflight": self._inflight_count,
            "backlog_parts": backlog,
            "breaker": (self.breaker.state if self.breaker is not None
                        else "absent"),
            "programs": len(self._programs),
            "warming": any(not h.done() for h in self._warmup_handles),
            "stats": snap,
        }

    def close(self, timeout: Optional[float] = None) -> None:
        """Stop accepting requests, drain everything queued, join the worker."""
        # EVERY background warmup stops at its next bucket boundary, and we
        # WAIT for the threads to exit (bounded): a leftover warmup compile
        # racing whatever the process runs next corrupts the jax runtime.
        # A build wedged past the bound is abandoned (daemon thread) rather
        # than hanging close().
        for h in self._warmup_handles:
            h.cancel()
        for h in self._warmup_handles:
            h.join(timeout if timeout is not None else 60.0)
        self._stop.set()
        self._thread.join(timeout)
        self.heartbeat.close()
        if self.breaker is not None:
            self.breaker.close()
        if self.slo_tracker is not None:
            self.slo_tracker.close()
        if self._profiler is not None:
            self._profiler.close()
        # a submit() racing close() can slip a part in after the worker
        # exits — fail it rather than leave its future hanging
        while True:
            try:
                self._queue.get_nowait().future._fail(
                    EngineClosed("engine closed before this request ran")
                )
            except queue.Empty:
                break

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def drain_engines(engines, timeout: Optional[float] = None) -> bool:
    """Drain several engines as ONE unit: close every door first (a
    composite request — e.g. an MLM fill that rides encoder AND decoder —
    can never slip in behind an already-drained sibling), then wait on each
    under one shared deadline. Returns True only when every engine drained
    in time. The callers: :meth:`MLMServer.drain` and the replica shim's
    ``ReplicaApp.drain``."""
    engines = list(engines)
    for eng in engines:
        eng.stop_admission()
    deadline = None if timeout is None else time.monotonic() + timeout
    ok = True
    for eng in engines:
        left = (None if deadline is None
                else max(0.0, deadline - time.monotonic()))
        ok = eng.drain(timeout=left) and ok
    return ok


def mlm_apply_fns(model) -> Dict[str, Callable]:
    """The three serving program families over one ``PerceiverMLM`` — the
    fused single-pass path plus the encode/decode latent-cache split — as
    plain ``apply_fn(params, *arrays)`` callables, keyed by the RPC verb the
    replica shim serves them under (``infer``/``encode``/``decode``).

    ONE definition shared by :class:`MLMServer` (in-process serving) and
    ``perceiver_io_tpu.serving.replica`` (a replica process hosting the same
    engines behind the router tier), so the two surfaces can never drift."""

    def fused_apply(p, token_ids, pad_mask, positions):
        logits, _ = model.apply(
            {"params": p}, token_ids, pad_mask, masking=False,
            deterministic=True, positions=positions,
        )
        return logits

    def encode_apply(p, token_ids, pad_mask):
        return model.apply(
            {"params": p}, token_ids, pad_mask, deterministic=True,
            method="encode",
        )

    def decode_apply(p, latents, positions):
        return model.apply(
            {"params": p}, latents, deterministic=True,
            positions=positions, method="decode",
        )

    return {"infer": fused_apply, "encode": encode_apply,
            "decode": decode_apply}


class CachedLatents:
    """Result of :meth:`MLMServer.encode`: the latent arrays plus the
    request-side bookkeeping needed to decode against them later."""

    __slots__ = ("latents", "token_ids", "mask_positions")

    def __init__(self, latents: np.ndarray, token_ids: List[np.ndarray],
                 mask_positions: List[np.ndarray]):
        self.latents = latents          # (B, N, C) — width-independent
        self.token_ids = token_ids      # per row, at its serving width
        self.mask_positions = mask_positions  # per row, [MASK] indices

    def __len__(self) -> int:
        return self.latents.shape[0]


class MLMServer:
    """Text serving frontend over a ``PerceiverMLM``: tokenize → width-bucket
    → micro-batching engine; fill-mask via the gathered decode, plus the
    encode-once/decode-many latent cache.

    ``bucket_widths``: serving sequence-width buckets (the training
    collator's rule, ``resolve_bucket_width``); None = always ``max_seq_len``.
    Each (width, batch-bucket, K-bucket) is one program — ``warmup()``
    compiles them all so steady state never compiles.

    ``quantize='int8'`` (or ``compute_dtype='int8w'``): weight-only int8
    serving — the checkpoint's f32 params are quantized ONCE here and the
    single ``QuantizedParams`` copy is shared by all three engines, exactly
    like the bf16 path shares its one cast copy.
    """

    def __init__(
        self,
        model,
        params,
        tokenizer,
        max_seq_len: int,
        bucket_widths: Optional[Sequence[int]] = None,
        max_batch: int = 64,
        max_delay_ms: float = 0.0,
        max_inflight: int = 2,
        compute_dtype: Optional[str] = None,
        quantize: Optional[str] = None,
        group_size: Optional[int] = None,
        registry: Optional[obs.MetricsRegistry] = None,
        heartbeat_deadline_s: Optional[float] = None,
        selfprofile_every: int = 0,
        request_deadline_s: Optional[float] = None,
        queue_limit: Optional[int] = None,
        dispatch_retries: int = 2,
        breaker_failures: int = 0,
        breaker_cooldown_s: float = 5.0,
        compile_cache=None,
        slo: Optional[obs.SLO] = None,
        span_every: int = 1,
        trace_sample: float = 1.0,
    ):
        import jax

        from perceiver_io_tpu.data.tokenizer import MASK_TOKEN

        self.model = model
        self.tokenizer = tokenizer
        self.max_seq_len = max_seq_len
        self.mask_id = tokenizer.token_to_id(MASK_TOKEN)
        if bucket_widths:
            widths = sorted({int(w) for w in bucket_widths})
            if widths[0] <= 0 or widths[-1] > max_seq_len:
                raise ValueError(
                    f"bucket_widths must lie in [1, max_seq_len={max_seq_len}],"
                    f" got {widths}"
                )
            if widths[-1] != max_seq_len:
                widths.append(max_seq_len)
            self.widths: List[int] = widths
        else:
            self.widths = [max_seq_len]

        # ONE device-resident (optionally bf16-cast or int8-quantized) param
        # copy shared by all three programs — the engines receive committed
        # arrays and their device_put is a no-op (same mode resolution and
        # preparation as the engines themselves: resolve_params_mode /
        # prepare_param_tree, so server and engine can never drift)
        compute_dtype, quantize = resolve_params_mode(compute_dtype, quantize)
        self._compute_dtype, self._quantize = compute_dtype, quantize
        self._group_size = group_size
        self._update_lock = threading.Lock()
        self._warmup_handles: List[WarmupHandle] = []
        params = jax.device_put(
            prepare_param_tree(params, compute_dtype, quantize, group_size)
        )

        apply_fns = mlm_apply_fns(model)

        common = dict(
            max_batch=max_batch, max_delay_ms=max_delay_ms,
            max_inflight=max_inflight, compute_dtype=compute_dtype,
            registry=registry, heartbeat_deadline_s=heartbeat_deadline_s,
            selfprofile_every=selfprofile_every,
            # resilience knobs: per-engine breakers (labeled by engine name)
            # over the shared device, shared deadline/shed/retry policy
            request_deadline_s=request_deadline_s, queue_limit=queue_limit,
            dispatch_retries=dispatch_retries,
            breaker_failures=breaker_failures,
            breaker_cooldown_s=breaker_cooldown_s,
            # one SLO spec, one tracker per engine (labeled by engine name):
            # the fused path's burn rate and the latent-cache halves' stay
            # separately attributable on /statz and healthz()
            slo=slo,
            span_every=span_every,
            trace_sample=trace_sample,
            # ONE ExecutableCache (resolved here so a fail-soft warning
            # prints once, not three times) shared by all three program
            # families; their fingerprints differ by apply-fn source/avals
            compile_cache=resolve_cache(compile_cache, registry=registry),
        )
        # fused single-pass path (one-shot requests) + the split pair
        # (latent-cache workloads); each engine owns one program family
        self.engine = ServingEngine(
            apply_fns["infer"], params, name="mlm", **common
        )
        self.encoder = ServingEngine(
            apply_fns["encode"], params, name="mlm_enc", **common
        )
        self.decoder = ServingEngine(
            apply_fns["decode"], params, name="mlm_dec", **common
        )

        # latent-cache accounting: a "hit" is a fill-mask answered from
        # cached latents (no encoder work), a "miss" is the fused path
        reg = registry if registry is not None else obs.get_registry()
        self._m_fused = reg.counter(
            "mlm_fill_mask_requests_total", "fill-mask requests by path",
            {"path": "fused"})
        self._m_cached = reg.counter(
            "mlm_fill_mask_requests_total", "fill-mask requests by path",
            {"path": "cached"})
        self._m_encoded = reg.counter(
            "mlm_cache_encodes_total", "texts encoded into the latent cache")
        self._m_hit_rate = reg.gauge(
            "mlm_latent_cache_hit_rate",
            "cached fill-masks / all fill-masks (encode-once pay-off)")

    def _note_fill(self, cached: bool) -> None:
        (self._m_cached if cached else self._m_fused).inc()
        total = self._m_cached.value + self._m_fused.value
        if total:
            self._m_hit_rate.set(self._m_cached.value / total)

    # -- request preparation -------------------------------------------------

    def _prepare(self, text: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Tokenize one text (ONCE, at natural length) and pad to its serving
        width bucket; returns ``(token_ids (1, W), pad_mask (1, W),
        mask_positions)``."""
        from perceiver_io_tpu.data.pipeline import resolve_bucket_width
        from perceiver_io_tpu.inference.mlm import (
            masked_token_ids,
            pad_token_rows,
        )

        row = masked_token_ids(self.tokenizer, text)
        width = resolve_bucket_width(len(row), self.widths)
        ids, pad = pad_token_rows([row], width, self._pad_id())
        return ids, pad, np.nonzero(ids[0] == self.mask_id)[0]

    def _pad_id(self) -> int:
        from perceiver_io_tpu.data.tokenizer import PAD_TOKEN

        return self.tokenizer.token_to_id(PAD_TOKEN)

    def _positions_row(self, mask_pos: np.ndarray, width: int) -> np.ndarray:
        """(1, K-bucket) positions row; filler slots repeat position 0 (their
        logits are never read). K buckets are powers of two so same-K
        requests share a program."""
        kb = bucket_size(max(len(mask_pos), 1), width)
        row = np.zeros((1, kb), np.int32)
        row[0, : len(mask_pos)] = mask_pos
        return row

    def _topk_transform(self, n_masks: int, k: int):
        def transform(logits: np.ndarray) -> List[List[str]]:
            out = []
            for slot in range(n_masks):
                top = np.argsort(-np.asarray(logits[0, slot], np.float32))[:k]
                out.append([self.tokenizer.id_to_token(int(t)) for t in top])
            return out

        return transform

    # -- one-shot fill-mask (fused path) -------------------------------------

    def submit(self, text: str, k: int = 5) -> _Future:
        """Enqueue one fill-mask request; ``result()`` is the per-``[MASK]``
        top-k token lists (``MLMPredictor.fill_masks`` row semantics)."""
        self._note_fill(cached=False)
        ids, pad, mask_pos = self._prepare(text)
        if len(mask_pos) == 0:  # nothing to decode: complete without device
            fut = _Future(1, None)
            fut._deliver(0, [])
            return fut
        positions = self._positions_row(mask_pos, ids.shape[1])
        return self.engine.submit(
            ids, pad, positions,
            transform=self._topk_transform(len(mask_pos), k),
        )

    def fill_masks(self, texts: Sequence[str], k: int = 5) -> List[List[List[str]]]:
        """Batch-synchronous fill-mask: submit everything, then collect —
        the engine micro-batches the whole set."""
        futures = [self.submit(t, k) for t in texts]
        return [f.result() for f in futures]

    # -- latent cache: encode once, decode many ------------------------------

    def encode(self, texts: Sequence[str]) -> CachedLatents:
        """Run the encoder half once per text (width-bucketed, micro-batched)
        and cache the latents; the O(L) work never repeats across decodes."""
        prepared = [self._prepare(t) for t in texts]
        self._m_encoded.inc(len(prepared))
        futures = [
            self.encoder.submit(ids, pad) for ids, pad, _ in prepared
        ]
        latents = np.concatenate([f.result() for f in futures], axis=0)
        return CachedLatents(
            latents,
            [ids[0] for ids, _, _ in prepared],
            [pos for _, _, pos in prepared],
        )

    def decode(self, cached: CachedLatents, positions: np.ndarray) -> np.ndarray:
        """Decode explicit (B, K) query ``positions`` against cached latents:
        (B, K, vocab) logits. B must match ``len(cached)``."""
        positions = np.asarray(positions, np.int32)
        if positions.shape[0] != len(cached):
            raise ValueError(
                f"positions rows {positions.shape[0]} != cached batch "
                f"{len(cached)}"
            )
        return self.decoder.predict(cached.latents, positions)

    def fill_masks_cached(self, cached: CachedLatents,
                          k: int = 5) -> List[List[List[str]]]:
        """Fill-mask from cached latents only — the decode-many half of the
        cache: each row decodes its own ``[MASK]`` positions (K-bucketed), no
        encoder work at all."""
        futures = []
        for row in range(len(cached)):
            self._note_fill(cached=True)
            mask_pos = cached.mask_positions[row]
            if len(mask_pos) == 0:
                fut = _Future(1, None)
                fut._deliver(0, [])
                futures.append(fut)
                continue
            positions = self._positions_row(mask_pos, self.max_seq_len)
            futures.append(self.decoder.submit(
                cached.latents[row: row + 1], positions,
                transform=self._topk_transform(len(mask_pos), k),
            ))
        return [f.result() for f in futures]

    # -- lifecycle -----------------------------------------------------------

    def update_params(self, params) -> None:
        """Hot-swap the served model across ALL THREE engines from ONE
        prepared tree (cast/quantized once under the server's mode — not
        three times), staged on each engine in the same call so the
        cross-engine mismatch window is one worker-loop iteration, not three
        independent re-preparations.

        Caveat for latent-cache users: ``CachedLatents`` obtained before the
        swap were encoded by the OLD weights — decoding them against the new
        decoder mixes models. Re-``encode()`` after an update.

        Concurrent server-level updates are serialized (one lock around
        prepare + the three stagings): without it, two racing calls could
        interleave their per-engine stagings and permanently install
        DIFFERENT versions on the fused/encoder/decoder paths.
        """
        import jax

        with self._update_lock:
            prepared = jax.device_put(
                prepare_param_tree(params, self._compute_dtype,
                                   self._quantize, self._group_size)
            )
            for eng in (self.engine, self.encoder, self.decoder):
                eng.update_params(prepared)

    def warmup(self, batch_buckets: Optional[Sequence[int]] = None,
               query_buckets: Sequence[int] = (1, 2, 4),
               background: bool = False):
        """Ready the serving programs ahead of traffic: every width bucket ×
        batch bucket (× K bucket for the decode paths), cache-first when a
        ``compile_cache`` is attached. The three program families (fused /
        encoder / decoder) warm CONCURRENTLY on their own threads, each in
        priority order (smallest width and bucket first).

        Blocking (default): returns the number of programs warmed — after
        this, steady-state serving never compiles (the compile-count test
        pins it). ``background=True`` returns a :class:`WarmupHandle`
        immediately; requests may be submitted right away and are answered
        as soon as their program is ready (not-yet-warm programs build on
        demand, deduped against the warmup threads in cache mode).
        """
        handle = WarmupHandle()
        self._warmup_handles = [
            h for h in self._warmup_handles if not h.done()
        ] + [handle]
        counts = [0, 0, 0]
        errors: List[BaseException] = []

        def example(width: int):
            # pad NOTHING in the warmup example: a fully-padded row would
            # feed the cross-attention an all-masked KV stream (NaN softmax)
            return (np.zeros((1, width), np.int32),
                    np.zeros((1, width), bool))

        def warm_fused():
            for width in self.widths:
                ids, pad = example(width)
                for kb in sorted({bucket_size(int(q), width)
                                  for q in query_buckets}):
                    if handle.cancelled():
                        return
                    positions = np.zeros((1, kb), np.int32)
                    counts[0] += len(self.engine.warmup(
                        ids, pad, positions, buckets=batch_buckets
                    ))

        def warm_encoder():
            for width in self.widths:
                if handle.cancelled():
                    return
                counts[1] += len(self.encoder.warmup(
                    *example(width), buckets=batch_buckets
                ))

        def warm_decoder():
            # needs one latent row; the encoder dispatch dedups against
            # warm_encoder's in-flight build of the same program
            latent_row = self.encoder.predict(*example(self.widths[0]))
            for kb in sorted({bucket_size(int(q), self.max_seq_len)
                              for q in query_buckets}):
                if handle.cancelled():
                    return
                positions = np.zeros((1, kb), np.int32)
                counts[2] += len(self.decoder.warmup(
                    latent_row, positions, buckets=batch_buckets
                ))

        def guarded(fn):
            def run():
                try:
                    fn()
                except BaseException as e:
                    errors.append(e)
                    # fail FAST: stop the sibling families at their next
                    # boundary instead of paying their full compile wall
                    # before the caller sees the first error
                    handle.cancel()
            return run

        def supervise():
            t0 = time.monotonic()
            threads = [
                threading.Thread(target=guarded(fn), name=f"mlm-warm-{i}",
                                 daemon=True)
                for i, fn in enumerate(
                    (warm_fused, warm_encoder, warm_decoder))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            obs.event("mlm_server_warmup", programs=sum(counts),
                      seconds=round(time.monotonic() - t0, 3),
                      cancelled=handle.cancelled(), errors=len(errors))
            if errors:
                handle._fail(errors[0])
            else:
                handle._finish(sum(counts))

        if background:
            supervisor = threading.Thread(
                target=supervise, name="mlm-warmup", daemon=True
            )
            handle._threads.append(supervisor)
            supervisor.start()
            return handle
        supervise()
        return handle.wait()

    @property
    def ready(self) -> bool:
        """All three program families fully warm (router join gate)."""
        return all(e.ready for e in (self.engine, self.encoder, self.decoder))

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful drain across all three engines: stop admitting, finish
        everything accepted (see :meth:`ServingEngine.drain` and
        :func:`drain_engines` for the close-every-door-first ordering)."""
        return drain_engines((self.engine, self.encoder, self.decoder),
                             timeout)

    def resume_admission(self) -> None:
        for eng in (self.engine, self.encoder, self.decoder):
            eng.resume_admission()

    def stats(self) -> Dict[str, Any]:
        """Locked, deep-copied snapshot across the three engines (the
        compatibility shim over the registry instruments)."""
        return {
            "fused": self.engine.stats(),
            "encode": self.encoder.stats(),
            "decode": self.decoder.stats(),
            "programs": (self.engine.num_programs
                         + self.encoder.num_programs
                         + self.decoder.num_programs),
        }

    def close(self) -> None:
        # ask every warm run's threads to stop, then WAIT for the
        # supervisors (which join them) — no warmup compile may outlive
        # the server (see ServingEngine.close)
        for h in self._warmup_handles:
            h.cancel()
        for h in self._warmup_handles:
            h.join(60.0)
        self.engine.close()
        self.encoder.close()
        self.decoder.close()

    def __enter__(self) -> "MLMServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
