"""Ahead-of-time model export via ``jax.export`` (StableHLO serialization).

The reference has no export path (SURVEY.md §3.4); the TPU-native story is
XLA's own portable artifact: lower the jitted forward once, serialize the
StableHLO + calling convention to bytes, and reload it anywhere a JAX runtime
exists — no Python model code, flax, or this framework needed at load time.
``platforms`` allows cross-lowering (e.g. export for TPU from a CPU host).

Params are baked into the artifact as constants, making it self-contained —
the serving analogue of a frozen graph. For weight-hot-swap serving keep
params as an argument instead: ``export_fn(fn, (params, *inputs), ...)``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
from jax import export as jax_export


def export_fn(
    fn,
    example_args: Tuple,
    path: Optional[str] = None,
    platforms: Optional[Sequence[str]] = None,
):
    """Lower ``fn(*example_args)`` and serialize. Returns the ``Exported``;
    writes the serialized bytes to ``path`` when given."""
    exported = jax_export.export(
        jax.jit(fn), platforms=list(platforms) if platforms else None
    )(*example_args)
    if path is not None:
        with open(path, "wb") as f:
            f.write(exported.serialize())
    return exported


def export_forward(
    model,
    params,
    example_inputs: Tuple,
    path: Optional[str] = None,
    platforms: Optional[Sequence[str]] = None,
    **apply_kwargs,
):
    """Export ``model.apply`` in inference mode with ``params`` baked in as
    constants (self-contained artifact).

    ``example_inputs`` are splatted POSITIONALLY into ``model.apply`` — for
    models whose later positional parameters are mode flags (e.g.
    ``PerceiverMLM(token_ids, pad_mask, masking=...)``), pass only the
    leading array arguments here and wrap extras like ``positions`` in an
    explicit fn via :func:`export_fn` instead (a third positional would
    collide with ``masking``; tools/inference_bench.py shows the pattern)."""

    def fn(*inputs):
        return model.apply(
            {"params": params}, *inputs, deterministic=True, **apply_kwargs
        )

    return export_fn(fn, example_inputs, path=path, platforms=platforms)


def load_exported(path: str):
    """Deserialize an exported artifact; returns a callable running it under
    jit on the current backend."""
    with open(path, "rb") as f:
        exported = jax_export.deserialize(f.read())
    return exported.call
