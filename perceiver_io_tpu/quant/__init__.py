"""Weight-only quantization for serving (int8/int4 storage, f32/bf16 compute).

See :mod:`perceiver_io_tpu.quant.int8` for the scheme (per-channel int8,
grouped int4), the policy, the tree contract (quantized key paths == f32
key paths — sharding rules and torch-parity names untouched), and the
:class:`QKernel` operand transport feeding the fused dequant-matmul kernel
(:mod:`perceiver_io_tpu.ops.pallas_matmul`).
"""

from perceiver_io_tpu.quant.int8 import (
    DEFAULT_GROUP_SIZE,
    DEFAULT_QUANT_RULES,
    QKernel,
    QuantizedParams,
    apply_operands,
    bytes_summary,
    dequantize_array,
    dequantize_tree,
    is_quantized,
    kernel_operands,
    quantize_array,
    quantize_tree,
    tree_bytes,
)

__all__ = [
    "DEFAULT_GROUP_SIZE",
    "DEFAULT_QUANT_RULES",
    "QKernel",
    "QuantizedParams",
    "apply_operands",
    "bytes_summary",
    "dequantize_array",
    "dequantize_tree",
    "is_quantized",
    "kernel_operands",
    "quantize_array",
    "quantize_tree",
    "tree_bytes",
]
