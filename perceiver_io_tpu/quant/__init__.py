"""Weight-only quantization for serving (int8 storage, f32/bf16 compute).

See :mod:`perceiver_io_tpu.quant.int8` for the scheme, the policy, and the
tree contract (quantized key paths == f32 key paths — sharding rules and
torch-parity names untouched).
"""

from perceiver_io_tpu.quant.int8 import (
    DEFAULT_QUANT_RULES,
    QuantizedParams,
    bytes_summary,
    dequantize_array,
    dequantize_tree,
    is_quantized,
    quantize_array,
    quantize_tree,
    tree_bytes,
)

__all__ = [
    "DEFAULT_QUANT_RULES",
    "QuantizedParams",
    "bytes_summary",
    "dequantize_array",
    "dequantize_tree",
    "is_quantized",
    "quantize_array",
    "quantize_tree",
    "tree_bytes",
]
