"""Weight-only int8 quantization for the HBM-bound serving path.

The measured inference roofline (PERF.md, `tools/hbm_roofline.py`) puts the
binding resource of this workload on HBM param/elementwise streams, not MXU
FLOPs — every serving micro-batch re-streams the full weight set from HBM.
Weight-only quantization (LLM.int8(), AWQ) attacks exactly that term: store
the matmul weights as int8 with per-channel f32 scales (~4x fewer weight
bytes than f32, ~2x fewer than bf16) and dequantize at apply time, INSIDE
the jitted program, so XLA fuses the ``convert * scale`` into the matmul
operand read and the f32/bf16 copy never round-trips HBM. Compute stays in
the model's compute dtype — this is storage quantization, not int8 matmuls.

Scheme: **per-channel symmetric int8.** For a kernel ``(in, out)`` each
OUTPUT channel ``j`` gets ``scale[j] = max|w[:, j]| / 127`` (f32) and
``q[:, j] = round(w[:, j] / scale[j])`` clipped to ±127; dequantization is
``q * scale`` — elementwise error is bounded by ``scale/2``. Symmetric (no
zero point) keeps dequant a single fused multiply; per-channel (rather than
per-tensor) scales keep the quantization grid matched to each output
column's dynamic range, which is what holds the end-to-end parity error to
the documented bound (see PERF.md §Quantization).

Policy: quantize the **streamed** weights — 2-D leaves whose path ends in
``kernel`` (every q/k/v/out_proj, MLP dense_1/dense_2, and the vocab-sized
head ``linear/kernel``, the single biggest param tensor). GATHERED tables
(``text_embedding/embedding``, the learned latent/output query arrays,
``pos_encoding``) stay in compute dtype: a gather touches only the rows it
reads, while a tree-level dequant would rebuild the full table every
dispatch — quantizing them would ADD traffic on the HBM-bound path, not
remove it. Biases and LayerNorm params are 1-D noise.

Tree contract (the invariant everything else leans on): the quantized
``values`` tree has EXACTLY the key paths of the source f32 tree — int8
leaves replace f32 kernels in place, scales ride in a separate flat
``{path: (out,) f32}`` map. Checkpoints stay f32 on disk (quantize at
load); ``parallel/sharding.py`` path-regex rules resolve against
``QuantizedParams.values`` unchanged (same paths, same shapes), and the
torch-parity param names are untouched. The apply-time dequant feeds the
existing ``_LinearParams`` fusion sites in ``ops/attention.py`` and the
adapter projections in ``models/`` exactly the tensors they would have read
from an f32 tree — the model code never sees an int8 array.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# The canonical key-path rendering — the SAME one parallel/sharding.py
# matches PARAM_RULES against; the scale map is keyed by it.
from perceiver_io_tpu.utils.treepath import simple_keystr as _simple_keystr

# Path regexes selecting the leaves to quantize (first match wins, like
# parallel/sharding.PARAM_RULES — and deliberately a SUBSET of the paths
# those rules shard: every quantized leaf keeps its sharding rule, because
# the int8 tree re-uses the f32 tree's paths and shapes verbatim).
DEFAULT_QUANT_RULES: Sequence[str] = (r"kernel$",)

_QMAX = 127.0  # symmetric int8: [-127, 127]; -128 unused (no zero point)


def quantize_array(w) -> Tuple[np.ndarray, np.ndarray]:
    """Per-channel symmetric int8 over the LAST axis: ``(q int8, scale f32)``
    with ``scale`` shaped like the last dimension. Runs on host numpy — this
    is one-time load work, not step work."""
    w = np.asarray(w, np.float32)
    if w.ndim < 1:
        raise ValueError("quantize_array needs at least one axis")
    amax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)))
    # an all-zero channel quantizes to zeros under any scale; 1.0 avoids /0
    scale = np.where(amax > 0, amax / _QMAX, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale), -_QMAX, _QMAX).astype(np.int8)
    return q, scale


def dequantize_array(q, scale, dtype) -> jax.Array:
    """``q * scale`` in f32, cast to the compute dtype. Traced inside the
    serving jit: XLA fuses the convert+multiply into the consuming matmul's
    operand read, so HBM streams the int8 bytes, not a materialized copy."""
    return (q.astype(jnp.float32) * scale).astype(dtype)


@jax.tree_util.register_pytree_with_keys_class
class QuantizedParams:
    """A params-shaped pytree of int8 weights + their per-channel scales.

    ``values`` mirrors the source tree's key paths exactly (int8 leaves at
    quantized paths, compute-dtype leaves elsewhere); ``scales`` is a flat
    ``{path: (out,) f32}`` dict keyed by the same ``/``-joined path strings
    the sharding rules match. ``compute_dtype`` (static aux data) names the
    dtype :func:`dequantize_tree` reconstructs.
    """

    __slots__ = ("values", "scales", "compute_dtype")

    def __init__(self, values: Any, scales: Dict[str, Any], compute_dtype: str):
        self.values = values
        self.scales = scales
        self.compute_dtype = compute_dtype

    def tree_flatten_with_keys(self):
        return (
            (
                (jax.tree_util.GetAttrKey("values"), self.values),
                (jax.tree_util.GetAttrKey("scales"), self.scales),
            ),
            self.compute_dtype,
        )

    @classmethod
    def tree_unflatten(cls, aux_data, children):
        return cls(children[0], children[1], aux_data)

    def __repr__(self) -> str:
        return (
            f"QuantizedParams({len(self.scales)} int8 leaves, "
            f"compute_dtype={self.compute_dtype!r})"
        )


def is_quantized(tree: Any) -> bool:
    """True for a tree already prepared by :func:`quantize_tree` (the
    engine's skip-requantization check when one quantized copy is shared by
    several engines, e.g. ``MLMServer``'s three program families)."""
    return isinstance(tree, QuantizedParams)


def quantize_tree(
    params: Any,
    compute_dtype: str = "float32",
    rules: Sequence[str] = DEFAULT_QUANT_RULES,
) -> QuantizedParams:
    """Quantize a params tree for int8w serving.

    Leaves matching ``rules`` (2-D floating ``kernel`` tensors by default)
    become int8 with per-output-channel f32 scales computed FROM THE f32
    SOURCE (never from an already-rounded bf16 copy); every other floating
    leaf is cast to ``compute_dtype`` (the same cast the bf16 serving path
    applies). Key paths, shapes, and tree structure are preserved exactly.
    """
    compute_dtype = str(jnp.dtype(compute_dtype))
    compiled = [re.compile(p) for p in rules]
    scales: Dict[str, Any] = {}

    def convert(path, leaf):
        name = _simple_keystr(path)
        # dtype inspection must not touch the device (jnp.asarray would
        # transfer every leaf just to read .dtype)
        if not hasattr(leaf, "dtype"):
            leaf = np.asarray(leaf)
        is_float = jnp.issubdtype(leaf.dtype, jnp.floating)
        if (
            is_float
            and getattr(leaf, "ndim", 0) == 2
            and any(p.search(name) for p in compiled)
        ):
            q, scale = quantize_array(leaf)
            scales[name] = jnp.asarray(scale)
            return jnp.asarray(q)
        if is_float:
            return leaf.astype(compute_dtype)
        return leaf

    values = jax.tree_util.tree_map_with_path(convert, params)
    if not scales:
        raise ValueError(
            "quantize_tree found no quantizable leaves — expected at least "
            f"one 2-D floating leaf matching {list(rules)}"
        )
    return QuantizedParams(values, scales, compute_dtype)


def dequantize_tree(qparams: QuantizedParams) -> Any:
    """Reconstruct a compute-dtype params tree from a quantized one.

    Call this INSIDE the jitted serving forward (``jax.jit(lambda qp, *x:
    apply(dequantize_tree(qp), *x))``): dequantized kernels are then
    fusion-local intermediates feeding the ``_LinearParams`` sites, and the
    program's weight HBM traffic is the int8 bytes. Calling it eagerly
    outside jit materializes full-size copies and forfeits the win.
    """
    if not is_quantized(qparams):
        raise TypeError(f"expected QuantizedParams, got {type(qparams).__name__}")
    dtype = jnp.dtype(qparams.compute_dtype)

    def deq(path, leaf):
        scale = qparams.scales.get(_simple_keystr(path))
        if scale is None:
            return leaf
        return dequantize_array(leaf, scale, dtype)

    return jax.tree_util.tree_map_with_path(deq, qparams.values)


def tree_bytes(tree: Any) -> int:
    """Total parameter bytes of a pytree (``QuantizedParams`` included —
    its scales count; they are streamed with the weights)."""
    return sum(
        int(np.prod(leaf.shape)) * jnp.dtype(leaf.dtype).itemsize
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype")
    )


def bytes_summary(params: Any, qparams: Optional[QuantizedParams] = None,
                  compute_dtype: str = "bfloat16") -> Dict[str, Any]:
    """Predicted per-dispatch weight-stream accounting for the quant bench.

    Every serving dispatch streams the full weight set once, so the
    predicted bytes-per-dispatch ratio IS the byte ratio of the trees:
    ``int8w_bytes / cast_bytes`` (the bf16-vs-int8w A/B's roofline
    prediction, checked against the device trace on TPU).
    """
    if qparams is None:
        qparams = quantize_tree(params, compute_dtype=compute_dtype)
    itemsize = jnp.dtype(compute_dtype).itemsize

    def leaf_cast_bytes(leaf):
        if not hasattr(leaf, "dtype"):  # python scalars — host-only inspect
            leaf = np.asarray(leaf)
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            return int(np.prod(leaf.shape)) * itemsize
        return int(np.prod(leaf.shape)) * jnp.dtype(leaf.dtype).itemsize

    cast_bytes = sum(
        leaf_cast_bytes(leaf) for leaf in jax.tree_util.tree_leaves(params)
    )
    f32_bytes = tree_bytes(params)
    int8w_bytes = tree_bytes(qparams)
    return {
        "param_bytes_f32": f32_bytes,
        f"param_bytes_{jnp.dtype(compute_dtype)}": cast_bytes,
        "param_bytes_int8w": int8w_bytes,
        "quantized_leaves": len(qparams.scales),
        "predicted_weight_stream_ratio": round(int8w_bytes / cast_bytes, 4),
    }
