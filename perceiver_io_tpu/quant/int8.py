"""Weight-only int8 quantization for the HBM-bound serving path.

The measured inference roofline (PERF.md, `tools/hbm_roofline.py`) puts the
binding resource of this workload on HBM param/elementwise streams, not MXU
FLOPs — every serving micro-batch re-streams the full weight set from HBM.
Weight-only quantization (LLM.int8(), AWQ) attacks exactly that term: store
the matmul weights as int8 with per-channel f32 scales (~4x fewer weight
bytes than f32, ~2x fewer than bf16) and dequantize at apply time, INSIDE
the jitted program, so XLA fuses the ``convert * scale`` into the matmul
operand read and the f32/bf16 copy never round-trips HBM. Compute stays in
the model's compute dtype — this is storage quantization, not int8 matmuls.

Scheme: **per-channel symmetric int8.** For a kernel ``(in, out)`` each
OUTPUT channel ``j`` gets ``scale[j] = max|w[:, j]| / 127`` (f32) and
``q[:, j] = round(w[:, j] / scale[j])`` clipped to ±127; dequantization is
``q * scale`` — elementwise error is bounded by ``scale/2``. Symmetric (no
zero point) keeps dequant a single fused multiply; per-channel (rather than
per-tensor) scales keep the quantization grid matched to each output
column's dynamic range, which is what holds the end-to-end parity error to
the documented bound (see PERF.md §Quantization).

Policy: quantize the **streamed** weights — 2-D leaves whose path ends in
``kernel`` (every q/k/v/out_proj, MLP dense_1/dense_2, and the vocab-sized
head ``linear/kernel``, the single biggest param tensor). GATHERED tables
(``text_embedding/embedding``, the learned latent/output query arrays,
``pos_encoding``) stay in compute dtype: a gather touches only the rows it
reads, while a tree-level dequant would rebuild the full table every
dispatch — quantizing them would ADD traffic on the HBM-bound path, not
remove it. Biases and LayerNorm params are 1-D noise.

Below int8: **grouped int4** (``bits=4``). Per-channel int4 loses too much
grid resolution on kernels with wide per-column dynamic range, so int4
scales are per ``(group_size x column)`` block — ``scale[g, j]`` covers rows
``[g*group_size, (g+1)*group_size)`` of column ``j`` (AWQ-style grouping;
group_size=128 default). Kernels whose fan-in is not a multiple of
``group_size`` fall back to per-channel scales for that leaf (documented,
deterministic — the parity bound covers both). Storage is ``jnp.int4``
(packed 2/byte on TPU; predicted bytes account it at 0.5 B/elem).

Kernel-path transport: :class:`QKernel` is a registered pytree node that
carries ``(q, scale)`` *through* the model's param tree in place of a
kernel leaf, so the fused dequant-matmul kernel (``ops/pallas_matmul.py``)
can stream the int8/int4 bytes instead of a pre-dequantized tensor.
:func:`kernel_operands` builds that operand tree INSIDE the serving jit;
``linear_apply`` at the ``_LinearParams`` sites dispatches on it. Flax param
retrieval only reads ``.shape`` off the leaf, which QKernel provides.

Tree contract (the invariant everything else leans on): the quantized
``values`` tree has EXACTLY the key paths of the source f32 tree — int8
leaves replace f32 kernels in place, scales ride in a separate flat
``{path: (out,) f32}`` map. Checkpoints stay f32 on disk (quantize at
load); ``parallel/sharding.py`` path-regex rules resolve against
``QuantizedParams.values`` unchanged (same paths, same shapes), and the
torch-parity param names are untouched. The apply-time dequant feeds the
existing ``_LinearParams`` fusion sites in ``ops/attention.py`` and the
adapter projections in ``models/`` exactly the tensors they would have read
from an f32 tree — the model code never sees an int8 array.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# The canonical key-path rendering — the SAME one parallel/sharding.py
# matches PARAM_RULES against; the scale map is keyed by it.
from perceiver_io_tpu.utils.treepath import simple_keystr as _simple_keystr

# Path regexes selecting the leaves to quantize (first match wins, like
# parallel/sharding.PARAM_RULES — and deliberately a SUBSET of the paths
# those rules shard: every quantized leaf keeps its sharding rule, because
# the int8 tree re-uses the f32 tree's paths and shapes verbatim).
DEFAULT_QUANT_RULES: Sequence[str] = (r"kernel$",)

_QMAX = 127.0  # symmetric int8: [-127, 127]; -128 unused (no zero point)
_QMAX4 = 7.0   # symmetric int4: [-7, 7]; -8 unused (no zero point)
DEFAULT_GROUP_SIZE = 128  # int4 default: one scale per 128-row column block


def quantize_array(
    w, bits: int = 8, group_size: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric quantization over the LAST axis: ``(q, scale f32)``.

    ``bits=8`` (default): per-channel, ``scale`` shaped like the last
    dimension; ``q`` is int8. ``bits=4``: values live in [-7, 7] (returned
    as int8 on host — callers cast to ``jnp.int4`` for storage). With
    ``group_size`` on a 2-D ``(in, out)`` kernel whose fan-in divides
    evenly, ``scale`` is ``(in // group_size, out)`` — one scale per
    column-block; otherwise per-channel. Runs on host numpy — this is
    one-time load work, not step work."""
    w = np.asarray(w, np.float32)
    if w.ndim < 1:
        raise ValueError("quantize_array needs at least one axis")
    if bits not in (8, 4):
        raise ValueError(f"unsupported bits={bits}; expected 8 or 4")
    qmax = _QMAX if bits == 8 else _QMAX4
    if group_size and w.ndim == 2 and w.shape[0] % group_size == 0:
        g = w.shape[0] // group_size
        wg = w.reshape(g, group_size, w.shape[1])
        amax = np.max(np.abs(wg), axis=1)  # (g, out)
        scale = np.where(amax > 0, amax / qmax, 1.0).astype(np.float32)
        q = np.clip(np.rint(wg / scale[:, None, :]), -qmax, qmax)
        return q.reshape(w.shape).astype(np.int8), scale
    amax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)))
    # an all-zero channel quantizes to zeros under any scale; 1.0 avoids /0
    scale = np.where(amax > 0, amax / qmax, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale), -qmax, qmax).astype(np.int8)
    return q, scale


def dequantize_array(q, scale, dtype) -> jax.Array:
    """``q * scale`` in f32, cast to the compute dtype. Traced inside the
    serving jit: XLA fuses the convert+multiply into the consuming matmul's
    operand read, so HBM streams the int8 bytes, not a materialized copy.
    A 2-D ``scale`` on a 2-D ``q`` means grouped scales: row block ``g`` of
    column ``j`` dequantizes by ``scale[g, j]``."""
    if getattr(scale, "ndim", 0) == 2 and q.ndim == 2:
        g = scale.shape[0]
        gs = q.shape[0] // g
        wf = q.astype(jnp.float32).reshape(g, gs, q.shape[1])
        return (wf * scale[:, None, :]).reshape(q.shape).astype(dtype)
    return (q.astype(jnp.float32) * scale).astype(dtype)


@jax.tree_util.register_pytree_with_keys_class
class QuantizedParams:
    """A params-shaped pytree of int8 weights + their per-channel scales.

    ``values`` mirrors the source tree's key paths exactly (int8 leaves at
    quantized paths, compute-dtype leaves elsewhere); ``scales`` is a flat
    ``{path: (out,) f32}`` dict keyed by the same ``/``-joined path strings
    the sharding rules match. ``compute_dtype`` (static aux data) names the
    dtype :func:`dequantize_tree` reconstructs.
    """

    __slots__ = ("values", "scales", "compute_dtype", "bits", "group_size")

    def __init__(self, values: Any, scales: Dict[str, Any], compute_dtype: str,
                 bits: int = 8, group_size: Optional[int] = None):
        self.values = values
        self.scales = scales
        self.compute_dtype = compute_dtype
        self.bits = bits
        self.group_size = group_size

    def tree_flatten_with_keys(self):
        return (
            (
                (jax.tree_util.GetAttrKey("values"), self.values),
                (jax.tree_util.GetAttrKey("scales"), self.scales),
            ),
            (self.compute_dtype, self.bits, self.group_size),
        )

    @classmethod
    def tree_unflatten(cls, aux_data, children):
        # pre-r24 aux was the bare compute_dtype string — accept both so
        # trees pickled/flattened under the old layout still unflatten
        if isinstance(aux_data, tuple):
            compute_dtype, bits, group_size = aux_data
        else:
            compute_dtype, bits, group_size = aux_data, 8, None
        return cls(children[0], children[1], compute_dtype, bits, group_size)

    @property
    def mode(self) -> str:
        """The engine-facing quantize mode string: ``'int8'`` or ``'int4'``."""
        return "int8" if self.bits == 8 else "int4"

    def __repr__(self) -> str:
        return (
            f"QuantizedParams({len(self.scales)} {self.mode} leaves, "
            f"compute_dtype={self.compute_dtype!r}, "
            f"group_size={self.group_size})"
        )


@jax.tree_util.register_pytree_with_keys_class
class QKernel:
    """A quantized kernel leaf travelling through a params-shaped tree.

    Carries ``(q, scale)`` to a ``linear_apply`` site so the fused
    dequant-matmul kernel can stream the int8/int4 bytes itself instead of
    receiving a pre-dequantized tensor. Registered as a pytree node (jit
    boundaries flatten it into its arrays); exposes ``.shape/.ndim/.dtype``
    mirroring the dequantized kernel so flax's param retrieval — which only
    inspects the leaf's shape — passes it through untouched. ``x @ qkernel``
    dispatches into the fused kernel via ``__rmatmul__`` (so generic
    apply_fns handed to ``ServingEngine`` keep working on a quantized
    tree); any OTHER array op receiving one fails loudly on the first use —
    deliberate containment, not a supported path.
    """

    __slots__ = ("q", "scale", "compute_dtype")

    def __init__(self, q: Any, scale: Any, compute_dtype: str):
        self.q = q
        self.scale = scale
        self.compute_dtype = compute_dtype

    def tree_flatten_with_keys(self):
        return (
            (
                (jax.tree_util.GetAttrKey("q"), self.q),
                (jax.tree_util.GetAttrKey("scale"), self.scale),
            ),
            self.compute_dtype,
        )

    @classmethod
    def tree_unflatten(cls, aux_data, children):
        return cls(children[0], children[1], aux_data)

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim

    @property
    def dtype(self):
        return jnp.dtype(self.compute_dtype)

    @property
    def group_size(self) -> Optional[int]:
        """Rows per scale group, or None for per-channel scales — derived
        from the array shapes so it stays correct under tracing."""
        if getattr(self.scale, "ndim", 1) == 2 and self.q.ndim == 2:
            return self.q.shape[0] // self.scale.shape[0]
        return None

    def dequantize(self) -> jax.Array:
        return dequantize_array(self.q, self.scale, jnp.dtype(self.compute_dtype))

    def __rmatmul__(self, x):
        # `x @ qkernel` IS a linear-apply site in the x·W convention — route
        # it through the fused dequant-matmul dispatch (local import: the
        # kernel module imports QKernel at top level)
        from perceiver_io_tpu.ops.pallas_matmul import quantized_matmul

        return quantized_matmul(x, self)

    def __repr__(self) -> str:
        return (
            f"QKernel({getattr(self.q, 'shape', '?')}, "
            f"{getattr(self.q, 'dtype', '?')}, gs={self.group_size})"
        )


def is_quantized(tree: Any) -> bool:
    """True for a tree already prepared by :func:`quantize_tree` (the
    engine's skip-requantization check when one quantized copy is shared by
    several engines, e.g. ``MLMServer``'s three program families)."""
    return isinstance(tree, QuantizedParams)


def quantize_tree(
    params: Any,
    compute_dtype: str = "float32",
    rules: Sequence[str] = DEFAULT_QUANT_RULES,
    bits: int = 8,
    group_size: Optional[int] = None,
) -> QuantizedParams:
    """Quantize a params tree for int8w/int4w serving.

    Leaves matching ``rules`` (2-D floating ``kernel`` tensors by default)
    become int8 (or int4 with ``bits=4``) with f32 scales computed FROM THE
    f32 SOURCE (never from an already-rounded bf16 copy); every other
    floating leaf is cast to ``compute_dtype`` (the same cast the bf16
    serving path applies). Key paths, shapes, and tree structure are
    preserved exactly. ``bits=4`` defaults to grouped scales
    (``group_size=128``); kernels whose fan-in is indivisible fall back to
    per-channel for that leaf.
    """
    compute_dtype = str(jnp.dtype(compute_dtype))
    if bits == 4 and group_size is None:
        group_size = DEFAULT_GROUP_SIZE
    compiled = [re.compile(p) for p in rules]
    scales: Dict[str, Any] = {}
    store_dtype = jnp.int8 if bits == 8 else jnp.int4

    def convert(path, leaf):
        name = _simple_keystr(path)
        # dtype inspection must not touch the device (jnp.asarray would
        # transfer every leaf just to read .dtype)
        if not hasattr(leaf, "dtype"):
            leaf = np.asarray(leaf)
        is_float = jnp.issubdtype(leaf.dtype, jnp.floating)
        if (
            is_float
            and getattr(leaf, "ndim", 0) == 2
            and any(p.search(name) for p in compiled)
        ):
            q, scale = quantize_array(leaf, bits=bits, group_size=group_size)
            scales[name] = jnp.asarray(scale)
            return jnp.asarray(q, dtype=store_dtype)
        if is_float:
            return leaf.astype(compute_dtype)
        return leaf

    values = jax.tree_util.tree_map_with_path(convert, params)
    if not scales:
        raise ValueError(
            "quantize_tree found no quantizable leaves — expected at least "
            f"one 2-D floating leaf matching {list(rules)}"
        )
    return QuantizedParams(values, scales, compute_dtype, bits, group_size)


def dequantize_tree(qparams: QuantizedParams) -> Any:
    """Reconstruct a compute-dtype params tree from a quantized one.

    Call this INSIDE the jitted serving forward (``jax.jit(lambda qp, *x:
    apply(dequantize_tree(qp), *x))``): dequantized kernels are then
    fusion-local intermediates feeding the ``_LinearParams`` sites, and the
    program's weight HBM traffic is the int8 bytes. Calling it eagerly
    outside jit materializes full-size copies and forfeits the win.
    """
    if not is_quantized(qparams):
        raise TypeError(f"expected QuantizedParams, got {type(qparams).__name__}")
    dtype = jnp.dtype(qparams.compute_dtype)

    def deq(path, leaf):
        scale = qparams.scales.get(_simple_keystr(path))
        if scale is None:
            return leaf
        return dequantize_array(leaf, scale, dtype)

    return jax.tree_util.tree_map_with_path(deq, qparams.values)


def kernel_operands(qparams: QuantizedParams) -> Any:
    """Build the kernel-path operand tree: quantized leaves become
    :class:`QKernel` nodes (int bytes + scale travelling together), every
    other leaf passes through. Call this INSIDE the serving jit in place of
    :func:`dequantize_tree` — ``linear_apply`` at the ``_LinearParams``
    sites then dispatches each QKernel to the fused dequant-matmul, and the
    program's weight HBM traffic is the int8/int4 bytes with the
    convert×scale applied in VMEM per tile."""
    if not is_quantized(qparams):
        raise TypeError(f"expected QuantizedParams, got {type(qparams).__name__}")
    dtype = str(jnp.dtype(qparams.compute_dtype))

    def conv(path, leaf):
        scale = qparams.scales.get(_simple_keystr(path))
        if scale is None:
            return leaf
        return QKernel(leaf, scale, dtype)

    return jax.tree_util.tree_map_with_path(conv, qparams.values)


def apply_operands(params: Any) -> Any:
    """The engines' one-line unwrap: quantized trees become QKernel operand
    trees (kernel path), anything else passes through unchanged. Safe to
    call at the top of every jitted forward."""
    return kernel_operands(params) if is_quantized(params) else params


def _leaf_bytes(leaf) -> int:
    n = int(np.prod(leaf.shape))
    if jnp.dtype(leaf.dtype) == jnp.dtype(jnp.int4):
        # ml_dtypes int4 reports itemsize 1 on host; TPU HBM packs 2/byte —
        # predicted-bytes accounting uses the packed figure (validated
        # against the device trace when the tunnel is live, PERF.md §r10)
        return (n + 1) // 2
    return n * jnp.dtype(leaf.dtype).itemsize


def tree_bytes(tree: Any) -> int:
    """Total parameter bytes of a pytree (``QuantizedParams`` included —
    its scales count; they are streamed with the weights). int4 leaves
    count at the packed 0.5 B/element."""
    return sum(
        _leaf_bytes(leaf)
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype")
    )


def bytes_summary(params: Any, qparams: Optional[QuantizedParams] = None,
                  compute_dtype: str = "bfloat16") -> Dict[str, Any]:
    """Predicted per-dispatch weight-stream accounting for the quant bench.

    Every serving dispatch streams the full weight set once, so the
    predicted bytes-per-dispatch ratio IS the byte ratio of the trees:
    ``int8w_bytes / cast_bytes`` (the bf16-vs-int8w A/B's roofline
    prediction, checked against the device trace on TPU).
    """
    if qparams is None:
        qparams = quantize_tree(params, compute_dtype=compute_dtype)
    itemsize = jnp.dtype(compute_dtype).itemsize

    def leaf_cast_bytes(leaf):
        if not hasattr(leaf, "dtype"):  # python scalars — host-only inspect
            leaf = np.asarray(leaf)
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            return int(np.prod(leaf.shape)) * itemsize
        return int(np.prod(leaf.shape)) * jnp.dtype(leaf.dtype).itemsize

    cast_bytes = sum(
        leaf_cast_bytes(leaf) for leaf in jax.tree_util.tree_leaves(params)
    )
    f32_bytes = tree_bytes(params)
    q_bytes = tree_bytes(qparams)
    return {
        "param_bytes_f32": f32_bytes,
        f"param_bytes_{jnp.dtype(compute_dtype)}": cast_bytes,
        f"param_bytes_{qparams.mode}w": q_bytes,
        "quantized_leaves": len(qparams.scales),
        "predicted_weight_stream_ratio": round(q_bytes / cast_bytes, 4),
    }
