// Native WordPiece encoder: byte-trie greedy longest-match-first.
//
// The framework's replacement for the third-party Rust tokenizer backend the
// reference depends on (HF `tokenizers`, reference perceiver/tokenizer.py:10-36):
// the tokenize hot loop — matching each pre-tokenized word against the vocab —
// runs here in C++; normalization/pre-tokenization (unicode-heavy, cacheable)
// stay on the Python side. Bound via ctypes (see native/wordpiece.py).
//
// Two tries over raw UTF-8 bytes: one for word-initial pieces, one for
// continuation pieces (the "##"-prefixed vocab entries, stored stripped).
// Greedy matching walks the trie recording the deepest node that terminates a
// vocab token; no match from the current offset -> whole word becomes UNK.

#include <cstdint>
#include <cstring>
#include <memory>
#include <vector>

namespace {

struct TrieNode {
  int32_t token_id = -1;  // -1: not a token end
  std::unique_ptr<TrieNode> children[256];
};

struct WordPiece {
  TrieNode initial;
  TrieNode continuation;
  int32_t unk_id;
};

void trie_insert(TrieNode* root, const char* s, size_t len, int32_t id) {
  TrieNode* node = root;
  for (size_t i = 0; i < len; ++i) {
    uint8_t b = static_cast<uint8_t>(s[i]);
    if (!node->children[b]) node->children[b] = std::make_unique<TrieNode>();
    node = node->children[b].get();
  }
  node->token_id = id;
}

// Longest match for word[start..): returns matched byte length (0 if none),
// stores the token id.
size_t trie_longest(const TrieNode* root, const char* word, size_t len,
                    size_t start, int32_t* id_out) {
  const TrieNode* node = root;
  size_t best_len = 0;
  int32_t best_id = -1;
  for (size_t i = start; i < len; ++i) {
    node = node->children[static_cast<uint8_t>(word[i])].get();
    if (!node) break;
    if (node->token_id >= 0) {
      best_len = i - start + 1;
      best_id = node->token_id;
    }
  }
  *id_out = best_id;
  return best_len;
}

}  // namespace

extern "C" {

// tokens: n UTF-8 strings; ids: their vocab ids.
//
// Parity contract with the Python encoder (a single dict): a word-INITIAL
// piece is looked up by its raw string — including tokens that literally
// start with "##" (a '#'-heavy corpus can mint those) — so EVERY token goes
// into the initial trie raw; a CONTINUATION piece is looked up as
// "##" + substring, so "##"-prefixed tokens additionally enter the
// continuation trie with the prefix stripped.
void* wp_create(const char** tokens, const int32_t* ids, int32_t n,
                int32_t unk_id) {
  auto* wp = new WordPiece();
  wp->unk_id = unk_id;
  for (int32_t i = 0; i < n; ++i) {
    const char* t = tokens[i];
    size_t len = std::strlen(t);
    if (len > 0) trie_insert(&wp->initial, t, len, ids[i]);
    if (len > 2 && t[0] == '#' && t[1] == '#') {
      trie_insert(&wp->continuation, t + 2, len - 2, ids[i]);
    }
  }
  return wp;
}

void wp_destroy(void* handle) { delete static_cast<WordPiece*>(handle); }

// Encode one pre-tokenized, normalized word (UTF-8, word_len bytes) into
// out[0..max_out). Returns the number of ids written; on no-match returns 1
// with out[0] = unk_id; returns -1 if out would overflow.
int32_t wp_encode_word(void* handle, const char* word, int32_t word_len,
                       int32_t* out, int32_t max_out) {
  auto* wp = static_cast<WordPiece*>(handle);
  size_t len = static_cast<size_t>(word_len);
  size_t start = 0;
  int32_t count = 0;
  while (start < len) {
    const TrieNode* root = (start == 0) ? &wp->initial : &wp->continuation;
    int32_t id;
    size_t matched = trie_longest(root, word, len, start, &id);
    if (matched == 0) {
      if (max_out < 1) return -1;
      out[0] = wp->unk_id;
      return 1;
    }
    if (count >= max_out) return -1;
    out[count++] = id;
    start += matched;
  }
  return count;
}

}  // extern "C"
