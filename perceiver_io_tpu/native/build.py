"""Build-on-demand for the native components.

Compiles ``<name>.cpp`` in this directory to ``lib<name>.so`` with g++ the
first time it is needed (results cached next to the source; stale artifacts —
older than the source — are rebuilt). Raises on failure; callers treat any
exception as "use the Python fallback".
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_LOCK = threading.Lock()


def load_library(name: str) -> ctypes.CDLL:
    src = os.path.join(_NATIVE_DIR, f"{name}.cpp")
    lib = os.path.join(_NATIVE_DIR, f"lib{name}.so")
    with _BUILD_LOCK:
        if not os.path.exists(lib) or os.path.getmtime(lib) < os.path.getmtime(src):
            subprocess.run(
                ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", lib, src],
                check=True,
                capture_output=True,
            )
    return ctypes.CDLL(lib)
