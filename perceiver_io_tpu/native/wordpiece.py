"""ctypes binding for the C++ WordPiece encoder (see wordpiece.cpp)."""

from __future__ import annotations

import ctypes
import threading
from typing import Dict, List

from perceiver_io_tpu.native.build import load_library

_MAX_PIECES = 512


class NativeWordPiece:
    def __init__(self, vocab: Dict[str, int], unk_id: int):
        self._lib = load_library("wordpiece")
        self._lib.wp_create.restype = ctypes.c_void_p
        self._lib.wp_create.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.c_int32,
        ]
        self._lib.wp_encode_word.restype = ctypes.c_int32
        self._lib.wp_encode_word.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
        ]
        self._lib.wp_destroy.argtypes = [ctypes.c_void_p]

        items = list(vocab.items())
        tokens = (ctypes.c_char_p * len(items))(
            *[t.encode("utf-8") for t, _ in items]
        )
        ids = (ctypes.c_int32 * len(items))(*[i for _, i in items])
        self._handle = self._lib.wp_create(tokens, ids, len(items), unk_id)
        self._unk_id = unk_id
        self._out = (ctypes.c_int32 * _MAX_PIECES)()
        # the ctypes call releases the GIL; concurrent prefetch threads
        # (train + val loaders sharing one tokenizer) must not share _out
        self._lock = threading.Lock()

    def encode_word(self, word: str) -> List[int]:
        raw = word.encode("utf-8")
        with self._lock:
            n = self._lib.wp_encode_word(
                self._handle, raw, len(raw), self._out, _MAX_PIECES
            )
            if n < 0:  # overflow — absurdly long word; match the Python fallback
                return [self._unk_id]
            return list(self._out[:n])

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            try:
                self._lib.wp_destroy(handle)
            except Exception:
                pass
