"""Native (C++) runtime components, bound via ctypes.

Built on demand with g++ into this directory; every native component has a
pure-Python fallback so the framework works without a toolchain.
"""
