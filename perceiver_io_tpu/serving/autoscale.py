"""Elastic autoscaling: the actuation half of the serving control loop.

Everything before this watched: r11 fit the capacity model
(``obs.slo.fit_capacity``), r15 attributed the tail, r16 stored the windowed
burn/queue signals in the router's fleet series store. This module ACTS on
them — an :class:`Autoscaler` drives replica spawn/retire from the live
series so the fleet tracks offered load instead of being sized for the peak:

- **signals** come from the router's :class:`~perceiver_io_tpu.obs.
  timeseries.SeriesStore` (the scrape loop's per-replica history): demand as
  the windowed counter rate of ``fleet_replica_requests_total`` summed over
  replicas, pressure as the windowed max of ``fleet_replica_slo_burn`` and
  the per-replica mean of ``fleet_replica_queue_depth`` — a HISTORY, never a
  point read (the r16 bake lesson: a spike between polls still counts).
- **the policy** (:class:`AutoscalePolicy`) is seeded by the capacity fit:
  ``rps_per_replica`` is exactly what :func:`fit_capacity` measured one
  replica sustaining at the SLO (``AutoscalePolicy.from_capacity``). Demand
  over ``rps_per_replica × target_utilization`` sets the desired count;
  burn/queue pressure forces an up-step even when the demand estimate lags.
- **hold-down + hysteresis** in the r16 ``AlertRule`` style: an up (down)
  condition must hold continuously for ``hold_up_s`` (``hold_down_s``)
  before acting, scale-down engages only below ``scale_down_utilization`` —
  strictly under the scale-UP target, so the two thresholds open a dead band
  a bursty minute oscillates inside without flapping the fleet — and each
  action starts a cooldown. Scale-up holds short and cools briefly (capacity
  missing is an SLO burn); scale-down holds long and cools long (capacity
  idling is only money).
- **scale-down is drain-then-retire only**: the victim leaves the router's
  placement (``drain_replica(detach=True)`` — finishes every accepted
  request, then its gauges and series leave the fleet store), and only then
  does the pool reap the process. ``lost_accepted`` stays 0 across every
  scale event, which is the acceptance bar.
- **failed spawns back off, capped-exponentially** (``resilience.
  RetryPolicy``): a spawn that raises (the ``autoscale.scale`` fault site,
  or a real fork failure) defers the next attempt instead of hammering — and
  the fleet NEVER flaps in response, because backoff gates only the
  actuation, not the desired-count estimate.

Actuation targets a tiny pool surface (``spawn() -> client`` /
``retire(name)``): :class:`SupervisorPool` adapts the r12
:class:`~perceiver_io_tpu.serving.supervisor.ReplicaSupervisor` (real
processes; a spawned replica JOINs through the router's readiness gate and
takes traffic only once warm), :class:`CallbackPool` adapts in-process
fleets (tests, ``tools/load_bench.py --autoscale``).

Every decision lands in the event log (``autoscale_decision``), trace-linked
through the router's latency-histogram exemplars — "why did the fleet grow
at 14:07" resolves to the assembled traces that were burning the SLO.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.resilience import RetryPolicy, faults

__all__ = [
    "Autoscaler",
    "AutoscalePolicy",
    "CallbackPool",
    "SupervisorPool",
]

FAULT_SITE = "autoscale.scale"


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """The declarative control policy.

    ``rps_per_replica`` is the measured requests/s ONE replica sustains at
    the SLO — seed it from the capacity fit (:meth:`from_capacity`), never
    a guess. Desired count = demand / (``rps_per_replica`` ×
    ``target_utilization``), clamped to [``min_replicas``,
    ``max_replicas``]. ``up_burn`` / ``queue_high`` are the pressure
    overrides (scale up even when the demand estimate lags reality);
    ``scale_down_utilization`` < ``target_utilization`` and ``down_burn``
    < ``up_burn`` are the hysteresis gaps, and the hold/cooldown pairs are
    the flap dampers (AlertRule ``for_s`` semantics: the condition must
    hold CONTINUOUSLY, a one-tick spike re-arms the timer).
    """

    rps_per_replica: float
    min_replicas: int = 1
    max_replicas: int = 8
    target_utilization: float = 0.7
    scale_down_utilization: float = 0.45
    up_burn: float = 1.0
    down_burn: float = 0.5
    up_stream_burn: float = 1.0
    down_stream_burn: float = 0.5
    queue_high: float = 8.0
    window_s: float = 5.0
    hold_up_s: float = 1.0
    hold_down_s: float = 5.0
    cooldown_up_s: float = 2.0
    cooldown_down_s: float = 10.0
    max_step: int = 2
    drain_timeout_s: float = 30.0

    def __post_init__(self):
        if self.rps_per_replica <= 0:
            raise ValueError(
                f"rps_per_replica must be positive, got "
                f"{self.rps_per_replica} — seed it from fit_capacity()")
        if self.min_replicas < 1 or self.max_replicas < self.min_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"[{self.min_replicas}, {self.max_replicas}]")
        if not 0.0 < self.target_utilization <= 1.0:
            raise ValueError(
                f"target_utilization must lie in (0, 1], got "
                f"{self.target_utilization}")
        if not 0.0 < self.scale_down_utilization < self.target_utilization:
            # the hysteresis dead band: scale-down must engage strictly
            # below the scale-up target or the fleet flaps on the boundary
            raise ValueError(
                f"scale_down_utilization ({self.scale_down_utilization}) "
                f"must sit strictly below target_utilization "
                f"({self.target_utilization}) — the gap is the anti-flap "
                f"dead band")
        if self.down_burn > self.up_burn:
            raise ValueError(
                f"down_burn ({self.down_burn}) must not exceed up_burn "
                f"({self.up_burn}) — hysteresis opens against the firing "
                f"direction")
        if self.down_stream_burn > self.up_stream_burn:
            raise ValueError(
                f"down_stream_burn ({self.down_stream_burn}) must not "
                f"exceed up_stream_burn ({self.up_stream_burn}) — "
                f"hysteresis opens against the firing direction")
        if (self.hold_up_s < 0 or self.hold_down_s < 0
                or self.cooldown_up_s < 0 or self.cooldown_down_s < 0):
            raise ValueError("hold/cooldown durations must be >= 0")
        if self.window_s <= 0:
            raise ValueError(f"window_s must be positive, got {self.window_s}")
        if self.max_step < 1:
            raise ValueError(f"max_step must be >= 1, got {self.max_step}")

    @staticmethod
    def from_capacity(fit: Dict[str, Any], replicas_measured: int = 1,
                      **overrides) -> "AutoscalePolicy":
        """Seed the policy from a :func:`~perceiver_io_tpu.obs.slo.
        fit_capacity` record: per-replica sustainable rate = the SLO-
        sustainable fit (falling back knee → capacity) over the replica
        count the sweep measured."""
        rps = (fit.get("slo_sustainable_rps") or fit.get("knee_rps")
               or fit.get("capacity_rps") or 0.0)
        return AutoscalePolicy(
            rps_per_replica=float(rps) / max(replicas_measured, 1),
            **overrides)


class SupervisorPool:
    """Actuation over a :class:`~perceiver_io_tpu.serving.supervisor.
    ReplicaSupervisor`: spawn returns the new client IMMEDIATELY (the
    router's JOINING gate keeps traffic off it until the warm pool is
    live), retire reaps an already-router-drained replica."""

    def __init__(self, supervisor, drain_timeout_s: float = 30.0):
        self.supervisor = supervisor
        self.drain_timeout_s = drain_timeout_s

    def spawn(self):
        return self.supervisor.add_replica()

    def retire(self, name: str) -> None:
        self.supervisor.retire(name, drain_timeout_s=self.drain_timeout_s)


class CallbackPool:
    """Actuation over caller-supplied functions (in-process fleets:
    ``spawn_fn() -> client``, ``retire_fn(name)``)."""

    def __init__(self, spawn_fn: Callable[[], Any],
                 retire_fn: Optional[Callable[[str], None]] = None):
        self.spawn_fn = spawn_fn
        self.retire_fn = retire_fn

    def spawn(self):
        return self.spawn_fn()

    def retire(self, name: str) -> None:
        if self.retire_fn is not None:
            self.retire_fn(name)


class Autoscaler:
    """Drives a router's fleet between ``min_replicas`` and
    ``max_replicas`` from the fleet series store. ``tick()`` is the
    deterministic unit (injectable ``now`` for tests); ``start()`` runs it
    on a daemon thread every ``interval_s``."""

    # pitlint PIT-LOCK: decision/accounting state is written by the tick
    # (control thread) and read by stats() callers
    _guarded_by = {
        "_counts": "_lock",
        "_replica_seconds": "_lock",
    }

    def __init__(
        self,
        router,
        pool,
        policy: AutoscalePolicy,
        interval_s: float = 0.5,
        spawn_backoff: Optional[RetryPolicy] = None,
        registry: Optional[obs.MetricsRegistry] = None,
        name: Optional[str] = None,
    ):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be positive, got {interval_s}")
        self.router = router
        self.pool = pool
        self.policy = policy
        self.interval_s = interval_s
        self.name = name if name is not None else router.name
        self._backoff = spawn_backoff or RetryPolicy(
            max_retries=8, base_s=0.5, max_s=30.0)
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self._replica_seconds = 0.0
        # hold-down state (AlertRule for_s semantics)
        self._up_since: Optional[float] = None
        self._down_since: Optional[float] = None
        self._cooldown_until = 0.0
        self._spawn_failures = 0
        self._spawn_retry_at = 0.0
        self._last_tick: Optional[float] = None
        reg = registry if registry is not None else obs.get_registry()
        labels = {"router": self.name}
        self._m_target = reg.gauge(
            "fleet_target_replicas",
            "the autoscaler's desired replica count (clamped)", labels)
        self._m_decisions: Dict[str, Any] = {}
        self._reg = reg
        self._m_spawn_failures = reg.counter(
            "autoscale_spawn_failures_total",
            "replica spawns that raised (each defers the next attempt "
            "with capped exponential backoff)", labels)
        self._m_backoff = reg.gauge(
            "autoscale_spawn_backoff_s",
            "seconds until the next spawn attempt is allowed (0 = none "
            "pending)", labels)
        self._m_replica_seconds = reg.counter(
            "autoscale_replica_seconds_total",
            "integral of live replicas over time — the resource the "
            "autoscaler exists to save vs a peak-sized static fleet",
            labels)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- signals -------------------------------------------------------------

    def signals(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The windowed control inputs, read from the router's fleet series
        store (never a point scrape): summed demand rate, max burn, mean
        queue depth per replica."""
        store = self.router.series
        w = self.policy.window_s
        demand = 0.0
        saw_rate = False
        for key in store.match("fleet_replica_requests_total"):
            r = store.rate(key, w, now=now)
            if r is not None:
                demand += max(r, 0.0)
                saw_rate = True
        burn = 0.0
        for key in store.match("fleet_replica_slo_burn"):
            b = store.window_agg(key, w, "max", now=now)
            if b is not None:
                burn = max(burn, b)
        stream_burn = 0.0
        for key in store.match("fleet_replica_stream_burn"):
            b = store.window_agg(key, w, "max", now=now)
            if b is not None:
                stream_burn = max(stream_burn, b)
        queue_sum = 0.0
        n_queues = 0
        for key in store.match("fleet_replica_queue_depth"):
            q = store.window_agg(key, w, "mean", now=now)
            if q is not None:
                queue_sum += q
                n_queues += 1
        replicas = len(self.router.replicas())
        return {
            "demand_rps": demand if saw_rate else None,
            "burn": burn,
            "stream_burn": stream_burn,
            "queue_per_replica": queue_sum / max(n_queues, 1),
            "replicas": replicas,
        }

    # -- the control tick ----------------------------------------------------

    def tick(self, now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """One control evaluation; returns the decision record when the
        tick ACTED (scale_up / scale_down / spawn_failed), None otherwise.
        ``now`` (monotonic) is injectable for tests."""
        p = self.policy
        now = time.monotonic() if now is None else now
        sig = self.signals(now=now)
        n = sig["replicas"]
        if self._last_tick is not None and now > self._last_tick:
            dt = now - self._last_tick
            with self._lock:
                self._replica_seconds += n * dt
            self._m_replica_seconds.inc(n * dt)
        self._last_tick = now
        demand = sig["demand_rps"] or 0.0
        desired = (math.ceil(demand / (p.rps_per_replica
                                       * p.target_utilization))
                   if demand > 0 else p.min_replicas)
        desired = max(p.min_replicas, min(p.max_replicas, desired))
        self._m_target.set(desired)
        self._m_backoff.set(max(0.0, self._spawn_retry_at - now))

        pressure = (sig["burn"] > p.up_burn
                    or sig["stream_burn"] > p.up_stream_burn
                    or sig["queue_per_replica"] > p.queue_high)
        up_cond = n < p.max_replicas and (desired > n or pressure)
        # hysteresis: with one fewer replica, utilization must still sit
        # below the scale-DOWN bound (strictly under the scale-up target)
        # and nothing may be burning
        down_cond = (
            not up_cond
            and n > p.min_replicas
            and sig["burn"] < p.down_burn
            and sig["stream_burn"] < p.down_stream_burn
            and demand / (max(n - 1, 1) * p.rps_per_replica)
            < p.scale_down_utilization
        )

        decision = None
        if up_cond:
            self._down_since = None
            if self._up_since is None:
                self._up_since = now
            if (now - self._up_since >= p.hold_up_s
                    and now >= self._cooldown_until
                    and now >= self._spawn_retry_at):
                decision = self._scale_up(n, desired, sig, now)
                self._up_since = None  # re-arm: the next step holds again
        elif down_cond:
            self._up_since = None
            if self._down_since is None:
                self._down_since = now
            if (now - self._down_since >= p.hold_down_s
                    and now >= self._cooldown_until):
                decision = self._scale_down(n, desired, sig, now)
                self._down_since = None
        else:
            self._up_since = None
            self._down_since = None
        return decision

    def _count(self, action: str) -> None:
        with self._lock:
            self._counts[action] = self._counts.get(action, 0) + 1
        counter = self._m_decisions.get(action)
        if counter is None:
            counter = self._m_decisions[action] = self._reg.counter(
                "autoscale_decisions_total",
                "autoscaler actions taken, by kind",
                {"router": self.name, "action": action})
        counter.inc()

    def _event(self, action: str, sig: Dict[str, Any],
               **fields: Any) -> Dict[str, Any]:
        rec = {
            "action": action,
            "replicas": sig["replicas"],
            "demand_rps": (None if sig["demand_rps"] is None
                           else round(sig["demand_rps"], 3)),
            "burn": round(sig["burn"], 4),
            "stream_burn": round(sig["stream_burn"], 4),
            "queue_per_replica": round(sig["queue_per_replica"], 3),
            **fields,
        }
        exemplars = self.router.latency_exemplars()
        if exemplars:
            # the trace link: WHY the fleet moved resolves to the assembled
            # traces that were burning the tail when the decision fired
            rec["trace_exemplars"] = exemplars
        obs.event("autoscale_decision", autoscaler=self.name, **rec)
        return rec

    def _scale_up(self, n: int, desired: int, sig: Dict[str, Any],
                  now: float) -> Dict[str, Any]:
        p = self.policy
        target = min(max(desired, n + 1), p.max_replicas, n + p.max_step)
        spawned: List[str] = []
        for _ in range(target - n):
            try:
                faults.inject(FAULT_SITE)
                client = self.pool.spawn()
            except Exception as e:
                self._spawn_failures += 1
                self._m_spawn_failures.inc()
                pause = self._backoff.backoff_s(self._spawn_failures)
                self._spawn_retry_at = now + pause
                self._m_backoff.set(pause)
                self._count("spawn_failed")
                rec = self._event(
                    "spawn_failed", sig, target=target,
                    error=f"{type(e).__name__}: {e}",
                    consecutive_failures=self._spawn_failures,
                    backoff_s=round(pause, 3), spawned=spawned)
                if spawned:
                    # a partial step still counts as a scale-up (and cools
                    # down): the fleet moved
                    self._finish_up(sig, target, spawned, now)
                return rec
            self.router.add_replica(client)
            spawned.append(client.name)
        self._spawn_failures = 0
        self._spawn_retry_at = 0.0
        self._m_backoff.set(0.0)
        return self._finish_up(sig, target, spawned, now)

    def _finish_up(self, sig: Dict[str, Any], target: int,
                   spawned: List[str], now: float) -> Dict[str, Any]:
        self._cooldown_until = now + self.policy.cooldown_up_s
        self._count("scale_up")
        return self._event("scale_up", sig, target=target, spawned=spawned)

    def _scale_down(self, n: int, desired: int, sig: Dict[str, Any],
                    now: float) -> Optional[Dict[str, Any]]:
        p = self.policy
        victim = self._pick_victim()
        if victim is None:
            return None
        try:
            faults.inject(FAULT_SITE)
            # drain-then-retire, NEVER kill: the victim finishes every
            # accepted request inside the router (detach removes its gauges
            # and series from the fleet store), then the pool reaps it
            drained = self.router.drain_replica(
                victim, timeout_s=p.drain_timeout_s, detach=True)
            self.pool.retire(victim)
        except Exception as e:
            self._count("retire_failed")
            self._cooldown_until = now + p.cooldown_down_s
            return self._event("retire_failed", sig, victim=victim,
                               error=f"{type(e).__name__}: {e}")
        self._cooldown_until = now + p.cooldown_down_s
        self._count("scale_down")
        return self._event("scale_down", sig, victim=victim,
                           drained=drained, target=max(desired, n - 1))

    def _pick_victim(self) -> Optional[str]:
        """Scale-down victim preference: a JOINING replica first (it takes
        no traffic yet, and the down decision just concluded its capacity
        is not needed — retiring it can never reduce serving capacity),
        then the least-loaded SERVING replica — but NEVER the last serving
        one while non-serving members remain (that trade would be an
        outage: live capacity swapped for a replica still warming)."""
        statuses = self.router.statuses()
        joining = [name for name, s in statuses.items()
                   if s["state"] == "joining"]
        if joining:
            return min(joining)
        serving = [(s["router_inflight"] + (s["queue_depth"] or 0), name)
                   for name, s in statuses.items()
                   if s["state"] == "serving"]
        if serving:
            if len(serving) == 1 and len(statuses) > 1:
                return None  # the only live capacity stays
            return min(serving)[1]
        others = [name for name, s in statuses.items()
                  if s["state"] not in ("draining", "down")]
        return min(others) if others else None

    # -- lifecycle / introspection -------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            counts = dict(self._counts)
            replica_seconds = self._replica_seconds
        return {
            "replicas": len(self.router.replicas()),
            "target": int(self._m_target.value),
            "decisions": counts,
            "scale_ups": counts.get("scale_up", 0),
            "scale_downs": counts.get("scale_down", 0),
            "spawn_failures": int(self._m_spawn_failures.value),
            "replica_seconds": round(replica_seconds, 3),
        }

    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name=f"{self.name}-autoscale",
                daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as e:
                # the control loop must outlive a bad tick (a scrape race,
                # a closing router) — but never silently
                obs.event("autoscale_tick_error", autoscaler=self.name,
                          error=f"{type(e).__name__}: {e}")

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def __enter__(self) -> "Autoscaler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
