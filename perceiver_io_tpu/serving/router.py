"""Health-aware, least-loaded request router over N serving replicas.

The router is the fleet's front door: callers submit exactly as they would to
one ``ServingEngine`` (``submit() -> future``), and the router decides WHICH
replica serves each request from the live per-replica view its scrape loop
maintains (``/statz`` → up / ready / queue depth / breaker / SLO burn):

- **least-loaded dispatch**: among eligible replicas (up, warm-pool ready,
  not draining, breaker closed), pick the one with the lowest
  ``router-inflight + scraped-queue-depth`` score. A replica whose SLO burn
  crosses ``burn_degrade`` is DEGRADED: routed around while any healthy
  replica remains, used as a last resort rather than shedding.
- **failover** (:class:`~perceiver_io_tpu.resilience.FailoverPolicy`): a
  dead replica surfaces as a connection error, an overloaded one as a
  rejection — both displace the request to the next-best replica, up to the
  placement budget. Re-routing happens ONLY for requests with no received
  response (at-most-once delivery); a delivered result is never re-placed.
  Accepted work is therefore lost only when the policy exhausts every
  replica — the zero-lost-accepted drill pins this under ``kill -9``.
- **latent-cache affinity**: ``encode(session=...)`` pins the session to the
  replica now holding its latents; ``decode(session=...)`` MUST go there
  (the state does not exist elsewhere, so there is nothing to fail over to).
  If the pinned replica died, the pin is dropped and the caller sees
  :class:`~perceiver_io_tpu.resilience.AffinityLost` — re-encoding
  establishes a fresh pin on a live replica (spill-on-death re-encode).
- **graceful drain** (``drain_replica``): stop routing to a replica, have it
  finish accepted work (``/admin/drain``), then optionally detach it — the
  rotation primitive rollouts and scale-downs share.
- **rolling rollout** (``rolling_update``): swap replicas one at a time via
  their hot-swap surface (params spec; AOT warm pools carry over, so a swap
  is preparation time, not a compile family), bake each swap against its
  scraped SLO burn / breaker state, and on regression roll the whole fleet
  back to the previous tree.

Health composes fleet-aware (``obs.fleet``): one replica's trouble degrades
that replica's label in ``/statz``/``healthz()`` detail; the router's own
``/healthz`` 503s only when fewer than ``min_serving`` replicas can serve.
"""

from __future__ import annotations

import threading
import time
import weakref
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.obs import fleet as _fleet
from perceiver_io_tpu.resilience import (
    AffinityLost,
    FailoverPolicy,
    RejectedError,
)


class RouterClosed(RuntimeError):
    """submit() after close()."""


class RouterFuture:
    """Result handle for one routed request: ``result(timeout)`` returns the
    replica's output arrays (a single array when there is exactly one).
    ``replica`` / ``attempts`` record where and how it was finally served."""

    def __init__(self, trace: Optional[obs.TraceContext] = None):
        self._event = threading.Event()
        self._result: Any = None
        self._error: Optional[BaseException] = None
        self.replica: Optional[str] = None
        self.attempts = 0
        self.t_done: Optional[float] = None  # monotonic completion stamp
        # (the open-loop load harness computes latency as t_done - t_submit
        # without the collect-loop skew a post-result() clock read has)
        self.trace = trace  # distributed-trace context (None = untraced)
        self.phases: List[dict] = []  # the replica engine's per-part phase
        # attribution, returned through the RPC (engine-future parity: the
        # load harness reads fut.phases on either future kind)

    def done(self) -> bool:
        return self._event.is_set()

    def _deliver(self, result) -> None:
        self._result = result
        self.t_done = time.monotonic()
        self._event.set()

    def _fail(self, error: BaseException) -> None:
        self._error = error
        self.t_done = time.monotonic()
        self._event.set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("request not served within timeout")
        if self._error is not None:
            raise self._error
        return self._result


class _Slot:
    """Router-side state for one replica."""

    def __init__(self, client):
        self.client = client
        self.name = client.name
        self.inflight = 0          # router-side, under Router._lock
        self.draining = False      # router-side admission stop
        self.scrape: Dict[str, Any] = {"up": True, "ready": False}
        self.failures = 0          # consecutive call failures (suspicion)
        # when the current scrape body was OBSERVED (any completed scrape,
        # up or not, is a fresh observation): placement must know whether
        # the gauges it steers by describe the replica now or N intervals
        # ago — a wedged scrape loop otherwise keeps steering least-loaded
        # dispatch by a snapshot of the past
        self.last_scrape_mono = time.monotonic()

    def scrape_age(self) -> float:
        return time.monotonic() - self.last_scrape_mono

    def load(self) -> float:
        return self.inflight + float(self.scrape.get("queue_depth", 0) or 0)


class Router:
    """Least-loaded, health-aware dispatch over replica clients (HTTP
    process replicas and/or in-process :class:`LocalReplica`s — any object
    with the ``call/scrape/drain/resume/update_params`` surface)."""

    # pitlint PIT-LOCK: fleet membership, session pins, and the admission
    # count are shared between submitters, the dispatch pool, and the scrape
    # thread — touched only under _lock
    _guarded_by = {
        "_slots": "_lock",
        "_pins": "_lock",
        "_pending": "_lock",
    }

    def __init__(
        self,
        replicas: Sequence = (),
        policy: Optional[FailoverPolicy] = None,
        name: str = "router",
        registry: Optional[obs.MetricsRegistry] = None,
        scrape_interval_s: float = 0.25,
        max_workers: int = 32,
        queue_limit: Optional[int] = None,
        burn_degrade: Optional[float] = 2.0,
        min_serving: int = 1,
        request_timeout_s: float = 120.0,
        trace_sample: float = 1.0,
        stale_after_intervals: Optional[float] = 8.0,
        series_store: Optional[obs.SeriesStore] = None,
        admission=None,
    ):
        self.name = name
        self.policy = policy if policy is not None else FailoverPolicy()
        self.queue_limit = queue_limit
        # admission control (serving.admission.AdmissionController): when
        # set, every submit passes the class/quota gate and admitted work
        # dispatches in weighted-fair order instead of FIFO — one bursting
        # client sheds in ITS class while other classes' tail stays flat
        self.admission = admission
        self.burn_degrade = burn_degrade
        self.request_timeout_s = request_timeout_s
        # scrape-staleness bound: a slot whose view is older than this many
        # scrape intervals is DEGRADED for placement (routed around while
        # any fresh replica serves, last resort otherwise). None disables.
        self._stale_after_s = (
            None if stale_after_intervals is None
            else max(stale_after_intervals * scrape_interval_s, 0.5))
        # the fleet time-series: every scrape sweep lands per-replica
        # labeled samples here, so rollout bakes and post-mortems judge a
        # HISTORY instead of whatever the latest poll happened to catch
        self.series = (series_store if series_store is not None
                       else obs.SeriesStore(max_samples=512))
        # distributed tracing: submit() mints the root TraceContext at this
        # head-sampling rate (free while no event log is configured); the
        # context crosses the replica RPC as headers, and completed roots
        # land in the trace buffer (exemplar-linked from router_latency)
        self.trace_sample = trace_sample
        self.traces = obs.TraceBuffer()
        self._lock = threading.Lock()
        self._slots: Dict[str, _Slot] = {}
        self._pins: Dict[str, str] = {}  # session -> replica name
        self._pending = 0  # requests admitted, not yet delivered/failed
        self._closed = threading.Event()
        reg = registry if registry is not None else obs.get_registry()
        self.registry = reg
        labels = {"router": name}
        self._m_requests = reg.counter(
            "router_requests_total", "requests admitted", labels)
        self._m_completed = reg.counter(
            "router_completed_total", "requests delivered", labels)
        self._m_failed = reg.counter(
            "router_failed_total", "requests failed after placement", labels)
        self._m_shed = reg.counter(
            "router_shed_total",
            "requests refused at router admission (queue_limit/no replica)",
            labels)
        self._m_reroutes = reg.counter(
            "router_reroutes_total",
            "failover re-placements (a request moved to another replica)",
            labels)
        self._m_spills = reg.counter(
            "router_affinity_spills_total",
            "sessions whose pinned replica died (caller re-encodes)", labels)
        self._m_latency = reg.histogram(
            "router_latency_seconds", "submit → result via the router",
            labels)
        # the generative traffic class rides its OWN instruments (labeled
        # task=generate): a multi-second stream classified into the
        # one-shot latency histogram would wreck every capacity fit and
        # SLO burn gauge built over it
        gen_labels = {**labels, "task": "generate"}
        self._m_gen_requests = reg.counter(
            "router_generate_total", "generate streams admitted", gen_labels)
        self._m_gen_completed = reg.counter(
            "router_generate_completed_total",
            "generate streams fully delivered", gen_labels)
        self._m_gen_failed = reg.counter(
            "router_generate_failed_total",
            "generate streams failed after admission", gen_labels)
        self._m_gen_tokens = reg.counter(
            "router_generate_tokens_total",
            "continuation tokens delivered to callers", gen_labels)
        self._m_gen_latency = reg.histogram(
            "router_generate_seconds",
            "generate stream wall time (admission → last frame)", gen_labels)
        self._gauges = _fleet.ReplicaGauges(fleet=name, registry=reg)
        # fleet_scrape_age_s is computed at EXPORT time (registry collector,
        # weakref so a closed router's collector drops itself): the wedged-
        # scrape-loop condition the gauge exposes is exactly the condition
        # that would stop a scrape-time write from ever reporting it
        router_ref = weakref.ref(self)

        def _scrape_age_collector():
            router = router_ref()
            if router is None or router._closed.is_set():
                raise LookupError("router gone — drop this collector")
            router._publish_scrape_ages()

        reg.register_collector(_scrape_age_collector)
        self.fleet_health = _fleet.FleetHealth(
            self.statuses, name=name, min_serving=min_serving)
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix=f"{name}-dispatch")
        for client in replicas:
            self.add_replica(client)
        self._scrape_interval_s = scrape_interval_s
        self._scraper = threading.Thread(
            target=self._scrape_loop, name=f"{name}-scrape", daemon=True)
        self._scraper.start()

    # -- fleet membership ----------------------------------------------------

    def add_replica(self, client, health_sources: Sequence = ()) -> None:
        """Admit a replica. ``health_sources`` re-scopes process-global
        health contributors (an in-process replica's breakers/SLO trackers)
        under the fleet aggregate — one replica's open breaker must degrade
        its label, not 503 the router (obs.fleet.adopt_source)."""
        slot = _Slot(client)
        slot.scrape = self._safe_scrape(client)
        self._gauges.readmit(client.name)  # a re-joining name publishes again
        with self._lock:
            self._slots[client.name] = slot
        for src in health_sources:
            self.fleet_health.adopt_source(client.name, src)
        obs.event("router_replica_added", router=self.name,
                  replica=client.name)

    def remove_replica(self, name: str) -> None:
        with self._lock:
            slot = self._slots.pop(name, None)
            dead_pins = [s for s, r in self._pins.items() if r == name]
            for s in dead_pins:
                del self._pins[s]
        self.fleet_health.release_sources(name)
        if slot is not None:
            # the replica's telemetry leaves with it: its per-replica gauges
            # drop from /metrics and its history from the fleet series store
            # — a retired replica must not keep steering autoscale signals
            # or export its last queue depth forever
            self._gauges.remove(name)
            self.series.forget({"fleet": self.name, "replica": name})
            obs.event("router_replica_removed", router=self.name,
                      replica=name)

    def replicas(self) -> List[str]:
        with self._lock:
            return list(self._slots)

    # -- scraping / health view ----------------------------------------------

    @staticmethod
    def _safe_scrape(client) -> Dict[str, Any]:
        try:
            return client.scrape()
        except Exception as e:  # a scrape NEVER takes the router down
            return {"up": False, "error": f"{type(e).__name__}: {e}"}

    def refresh(self) -> None:
        """One synchronous scrape sweep (the loop's body; tests and the
        rollout bake call it directly for a current view)."""
        with self._lock:
            slots = list(self._slots.values())
        serving = 0
        for slot in slots:
            slot.scrape = self._safe_scrape(slot.client)
            # the inter-scrape gap this sweep closed — read BEFORE the
            # stamp update, so the history shows the loop's real cadence
            # (a recovered wedge leaves its spike in the series)
            gap = slot.scrape_age()
            slot.last_scrape_mono = time.monotonic()
            state = self._state(slot)
            if state == _fleet.SERVING:
                serving += 1
            s = slot.scrape
            self._gauges.publish(
                slot.name,
                up=1.0 if s.get("up") else 0.0,
                ready=1.0 if s.get("ready") else 0.0,
                queue_depth=float(s.get("queue_depth", 0) or 0),
                inflight=float(slot.inflight),
                breaker_open=1.0 if s.get("breaker_open") else 0.0,
                slo_burn=float(s.get("slo_burn", 0.0) or 0.0),
                stream_burn=float(s.get("stream_burn", 0.0) or 0.0),
                requests_total=(None if s.get("requests_total") is None
                                else float(s["requests_total"])),
            )
            # the fleet history: this sweep's observation, replica-labeled
            self.series.ingest_scrape(self.name, slot.name, s,
                                      scrape_age_s=gap)
        self._gauges.publish_fleet(size=len(slots), serving=serving)

    def _publish_scrape_ages(self) -> None:
        """Live per-slot scrape age into ``fleet_scrape_age_s`` — invoked
        by the registry collector at every export, so a wedged scrape loop
        shows its growing age instead of a frozen near-zero write."""
        with self._lock:
            slots = list(self._slots.values())
        for slot in slots:
            self._gauges.publish(slot.name, scrape_age_s=slot.scrape_age())

    def _scrape_loop(self) -> None:
        while not self._closed.wait(self._scrape_interval_s):
            self.refresh()

    def _state(self, slot: _Slot) -> str:
        s = slot.scrape
        if not s.get("up"):
            return _fleet.DOWN
        if slot.draining or s.get("draining"):
            return _fleet.DRAINING
        if (self._stale_after_s is not None
                and slot.scrape_age() > self._stale_after_s):
            # the view is too old to steer by: a stale-but-up replica's
            # frozen gauges would otherwise keep winning least-loaded
            # placement long after its real queue grew
            return _fleet.DEGRADED
        if not s.get("ready"):
            return _fleet.JOINING
        if s.get("breaker_open"):
            return _fleet.DEGRADED
        if (self.burn_degrade is not None
                and float(s.get("slo_burn", 0.0) or 0.0) > self.burn_degrade):
            return _fleet.DEGRADED
        if (self.burn_degrade is not None
                and float(s.get("stream_burn", 0.0) or 0.0)
                > self.burn_degrade):
            # token-latency burn degrades placement exactly like request
            # burn: a replica streaming stalled tokens is a bad pick even
            # when its whole-request latencies still clear the target
            return _fleet.DEGRADED
        return _fleet.SERVING

    def statuses(self) -> Dict[str, Dict[str, Any]]:
        """Per-replica view for ``obs.FleetHealth`` / ``/statz``."""
        with self._lock:
            slots = list(self._slots.values())
        out = {}
        for slot in slots:
            s = slot.scrape
            out[slot.name] = {
                "state": self._state(slot),
                "router_inflight": slot.inflight,
                "queue_depth": s.get("queue_depth", 0),
                "slo_burn": s.get("slo_burn", 0.0),
                "stream_burn": s.get("stream_burn", 0.0),
                "breaker_open": bool(s.get("breaker_open")),
                "params_version": s.get("params_version", 0),
                "scrape_age_s": round(slot.scrape_age(), 3),
            }
        return out

    # -- placement -----------------------------------------------------------

    def _pick(self, exclude: set, session: Optional[str] = None) -> _Slot:
        """Least-loaded eligible replica; degraded replicas only as a last
        resort; raises when nothing can take the work."""
        with self._lock:
            if session is not None and session in self._pins:
                pinned = self._pins[session]
                slot = self._slots.get(pinned)
                if (slot is None or slot.name in exclude
                        or self._state(slot) in (_fleet.DOWN,
                                                 _fleet.DRAINING)):
                    # the pin is dead: drop it — the caller re-encodes on
                    # whatever the next encode pins (spill-on-death)
                    self._pins.pop(session, None)
                    self._m_spills.inc()
                    raise AffinityLost(
                        f"session {session!r}: pinned replica "
                        f"{pinned!r} is gone — re-encode to re-pin"
                    )
                return slot
            candidates = [s for s in self._slots.values()
                          if s.name not in exclude]
        serving = [s for s in candidates
                   if self._state(s) == _fleet.SERVING]
        pool = serving or [s for s in candidates
                           if self._state(s) == _fleet.DEGRADED]
        if not pool:
            raise RejectedError(
                f"router {self.name!r}: no replica available "
                f"({len(candidates)} known, none serving)"
            )
        return min(pool, key=_Slot.load)

    def _note_inflight(self, slot: _Slot, delta: int) -> None:
        with self._lock:
            slot.inflight += delta

    def _run(self, fut: RouterFuture, kind: str,
             arrays: List[np.ndarray], session: Optional[str],
             pin_on_success: bool, deadline: Optional[float]) -> None:
        tried: set = set()
        attempt = 0
        tr = fut.trace  # None = untraced (no event log / sampled out)
        try:
            while True:
                attempt += 1
                fut.attempts = attempt
                slot = self._pick(tried, session=session)
                timeout_s = self.request_timeout_s
                if deadline is not None:
                    timeout_s = min(timeout_s, deadline - time.monotonic())
                    if timeout_s <= 0:
                        from perceiver_io_tpu.resilience import (
                            DeadlineExceeded,
                        )

                        raise DeadlineExceeded(
                            "router deadline expired before placement"
                        )
                self._note_inflight(slot, 1)
                # one span per placement attempt; its context crosses the
                # RPC as headers, so the replica's spans parent under it
                attempt_ctx = tr.child() if tr is not None else None
                meta: Dict[str, Any] = {}
                t_attempt = time.monotonic()
                try:
                    out = slot.client.call(
                        kind, arrays, session=session, timeout_s=timeout_s,
                        trace=attempt_ctx, meta=meta)
                except BaseException as e:
                    if attempt_ctx is not None:
                        obs.record_span(
                            "router_attempt", attempt_ctx, t_attempt,
                            time.monotonic() - t_attempt, router=self.name,
                            replica=slot.name, kind=kind, attempt=attempt,
                            ok=False, error=type(e).__name__)
                    slot.failures += 1
                    obs.event("router_request_failed", router=self.name,
                              replica=slot.name, kind=kind,
                              error=type(e).__name__, attempt=attempt)
                    if ((session is None or pin_on_success)
                            and self.policy.should_reroute(e, attempt)):
                        # NO response was received — re-placing cannot
                        # duplicate a delivered result. A pinned DECODE
                        # never re-routes (the state lives on one replica);
                        # an ENCODE may (its pin is set only on success, so
                        # re-placing establishes the session elsewhere).
                        tried.add(slot.name)
                        self._m_reroutes.inc()
                        pause = self.policy.backoff.backoff_s(attempt)
                        t_hop = time.monotonic()
                        if pause > 0:
                            time.sleep(pause)
                        if tr is not None:
                            # the failover hop itself: the displaced
                            # request's backoff gap, attributable in the
                            # assembled trace (the chaos drill's pin)
                            obs.record_span(
                                "router_reroute", tr.child(), t_hop,
                                time.monotonic() - t_hop, router=self.name,
                                from_replica=slot.name, attempt=attempt,
                                error=type(e).__name__)
                        continue
                    if session is not None and isinstance(
                            e, (ConnectionError, OSError)) and not pin_on_success:
                        # a pinned decode hit a dying replica mid-request:
                        # same spill semantics as a dead pin at placement
                        with self._lock:
                            self._pins.pop(session, None)
                        self._m_spills.inc()
                        if tr is not None:
                            obs.record_span(
                                "router_affinity_spill", tr.child(),
                                time.monotonic(), 0.0, router=self.name,
                                session=session, replica=slot.name)
                        raise AffinityLost(
                            f"session {session!r}: replica {slot.name!r} "
                            f"died mid-request — re-encode to re-pin"
                        ) from e
                    raise
                finally:
                    self._note_inflight(slot, -1)
                if attempt_ctx is not None:
                    # server_s = replica-reported engine phase sum riding
                    # the RPC meta: the span's dur minus it IS the transport
                    # cost (serialize + wire + deserialize + conn wait) —
                    # what load_bench's transport A/B compares per arm
                    obs.record_span(
                        "router_attempt", attempt_ctx, t_attempt,
                        time.monotonic() - t_attempt, router=self.name,
                        replica=slot.name, kind=kind, attempt=attempt,
                        ok=True, server_s=round(sum(
                            sum(r.values())
                            for r in meta.get("phases") or []), 6))
                slot.failures = 0
                if pin_on_success and session is not None:
                    with self._lock:
                        self._pins[session] = slot.name
                fut.replica = slot.name
                fut.phases = meta.get("phases") or []
                fut._deliver(out[0] if len(out) == 1 else out)
                self._m_completed.inc()
                return
        except BaseException as e:
            self._m_failed.inc()
            fut._fail(e)
        finally:
            with self._lock:
                self._pending -= 1

    def submit(self, *arrays, kind: str = "infer",
               session: Optional[str] = None,
               deadline_s: Optional[float] = None,
               client: Optional[str] = None,
               priority: Optional[str] = None) -> RouterFuture:
        """Route one request; returns a :class:`RouterFuture`.

        ``kind`` names the replica RPC verb (``infer``/``encode``/
        ``decode``). ``session`` engages affinity: an ``encode`` pins the
        session to the replica that served it, a ``decode`` must follow the
        pin. ``deadline_s`` bounds the whole routed lifetime (placement +
        failover + service). With an admission controller installed,
        ``client`` draws the request against that client's token-bucket
        quota and ``priority`` names its service class (default class
        otherwise); over-quota/over-share requests shed HERE with a
        reasoned :class:`RejectedError` and admitted work dispatches in
        weighted-fair class order."""
        if self._closed.is_set():
            raise RouterClosed(f"submit() on closed router {self.name!r}")
        with self._lock:
            if (self.queue_limit is not None
                    and self._pending >= self.queue_limit):
                pending = self._pending
                admitted = False
            else:
                self._pending += 1
                admitted = True
        if not admitted:
            self._m_shed.inc()
            raise RejectedError(
                f"router {self.name!r}: {pending} requests pending "
                f"(limit {self.queue_limit}) — request shed"
            )
        ticket = None
        if self.admission is not None:
            try:
                ticket = self.admission.admit(client=client,
                                              priority=priority)
            except BaseException:
                # the class/quota gate refused (or the router.admit fault
                # site fired): the request was never pending and the shed
                # counts at the router edge too
                with self._lock:
                    self._pending -= 1
                self._m_shed.inc()
                raise
        self._m_requests.inc()
        tr = obs.maybe_trace(self.trace_sample)
        fut = RouterFuture(trace=tr)
        t0 = time.monotonic()
        deadline = None if deadline_s is None else t0 + deadline_s
        arrays = [np.asarray(a) for a in arrays]
        pin = kind == "encode" and session is not None

        def run_and_time():
            self._run(fut, kind, arrays, session, pin, deadline)
            ok = fut._error is None
            latency = (fut.t_done if fut.t_done is not None
                       else time.monotonic()) - t0
            if ok:
                self._m_latency.observe(
                    latency,
                    exemplar=tr.trace_id if tr is not None else None)
            if ticket is not None:
                # close the admission books: the result classifies against
                # the request's CLASS SLO (the per-class burn gauges)
                self.admission.on_result(ticket, latency, ok)
            if tr is not None:
                # the root span: the whole routed lifetime, recorded by the
                # router process (its duration IS the e2e latency the
                # histogram + exemplar observe)
                obs.record_span(
                    "router_request", tr, t0, latency, router=self.name,
                    kind=kind, attempts=fut.attempts, replica=fut.replica,
                    ok=ok, **({} if ok
                              else {"error": type(fut._error).__name__}))
                self.traces.add(tr.trace_id, latency, ok=ok, kind=kind,
                                attempts=fut.attempts, replica=fut.replica)

        if ticket is None:
            self._pool.submit(run_and_time)
        else:
            # weighted-fair dispatch: the thunk enters its class queue and
            # the pool receives an anonymous worker token — each token runs
            # whatever the WFQ says is globally next, so under contention
            # every backlogged class receives weight-proportional service
            self.admission.enqueue(ticket, fut, run_and_time)
            self._pool.submit(self._admission_worker)
        return fut

    def _admission_worker(self) -> None:
        item = (self.admission.pop()
                if self.admission is not None else None)
        if item is None:
            return  # the queue was drained (shutdown) under this token
        _, (_, fn) = item
        fn()

    def latency_exemplars(self, n: int = 4) -> List[str]:
        """Trace ids from the router latency histogram's exemplar ring
        (slowest-first) — the trace link autoscale decisions and alerts
        attach to."""
        return [e["trace"] for e in self._m_latency.exemplars()[:n]]

    def predict(self, *arrays, kind: str = "infer",
                session: Optional[str] = None,
                timeout: Optional[float] = None,
                client: Optional[str] = None,
                priority: Optional[str] = None):
        return self.submit(*arrays, kind=kind, session=session,
                           client=client, priority=priority).result(
            timeout=timeout)

    # -- latent-cache affinity helpers ---------------------------------------

    def encode(self, *arrays, session: str,
               timeout: Optional[float] = None,
               client: Optional[str] = None,
               priority: Optional[str] = None):
        """Encode-once: runs the encoder on the least-loaded replica and pins
        ``session`` there (the latents stay resident on that replica)."""
        return self.predict(*arrays, kind="encode", session=session,
                            timeout=timeout, client=client,
                            priority=priority)

    def decode(self, *arrays, session: str,
               timeout: Optional[float] = None,
               client: Optional[str] = None,
               priority: Optional[str] = None):
        """Decode-many against a pinned session; raises
        :class:`AffinityLost` when the pinned replica (and the latents)
        died — the caller re-``encode()``s, which re-pins."""
        return self.predict(*arrays, kind="decode", session=session,
                            timeout=timeout, client=client,
                            priority=priority)

    def pinned(self, session: str) -> Optional[str]:
        with self._lock:
            return self._pins.get(session)

    # -- the generative workload (task=generate) -----------------------------

    def generate(self, prefix, session: Optional[str] = None,
                 max_new: int = 16, temperature: float = 0.0,
                 top_k: int = 0, seed: int = 0,
                 on_tokens=None,
                 timeout_s: Optional[float] = None,
                 client: Optional[str] = None,
                 priority: Optional[str] = None) -> Dict[str, Any]:
        """Route one streamed continuation (synchronous — generation is a
        long-lived stream, so it runs on the CALLER's thread; wrap it
        yourself for concurrency). Semantics:

        - ``session`` pins like the latent-cache sessions: the stream runs
          on the pinned replica while it lives, and SUCCESS (re-)pins.
        - tokens are ACCEPTED as frames arrive (``on_tokens(tokens, info)``
          per chunk). A replica dying mid-stream does not lose them: the
          pin is dropped, the spill is counted, and the stream resumes on
          another replica by re-encoding from the EXTENDED prefix — with
          the position-folded sampling keys, the continuation is the
          identical stream (the mid-stream chaos drill pins
          ``lost_accepted=0`` by content).
        - admission (``client``/``priority``) draws the stream against the
          caller's class/quota exactly like ``submit``.

        Returns ``{"tokens", "attempts", "reroutes", "spills", "replica",
        "resumed"}``."""
        if self._closed.is_set():
            raise RouterClosed(f"generate() on closed router {self.name!r}")
        ticket = None
        if self.admission is not None:
            try:
                ticket = self.admission.admit(client=client,
                                              priority=priority)
            except BaseException:
                self._m_shed.inc()
                raise
        self._m_gen_requests.inc()
        tr = obs.maybe_trace(self.trace_sample)
        t0 = time.monotonic()
        deadline = None if timeout_s is None else t0 + timeout_s
        prefix = [int(t) for t in np.asarray(prefix).reshape(-1)]
        accepted: list = []
        tried: set = set()
        attempt = 0
        reroutes = spills = 0
        summary: Dict[str, Any] = {}
        ok = False
        try:
            while True:
                attempt += 1
                try:
                    slot = self._pick(tried, session=session)
                except AffinityLost:
                    # a dead pin is NOT fatal for generation: the accepted
                    # tokens live with the caller, so re-encoding from the
                    # extended prefix on any live replica resumes the
                    # stream (spill-on-death re-encode). _pick already
                    # dropped the pin and counted the spill.
                    spills += 1
                    if tr is not None:
                        obs.record_span(
                            "router_affinity_spill", tr.child(),
                            time.monotonic(), 0.0, router=self.name,
                            session=session or "", kind="generate")
                    slot = self._pick(tried, session=None)
                left = None
                if deadline is not None:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        from perceiver_io_tpu.resilience import (
                            DeadlineExceeded,
                        )

                        raise DeadlineExceeded(
                            "generate deadline expired mid-stream")
                self._note_inflight(slot, 1)
                attempt_ctx = tr.child() if tr is not None else None
                t_attempt = time.monotonic()

                def chunk_cb(frame: Dict[str, Any]) -> None:
                    toks = frame.get("tokens")
                    if toks:
                        accepted.extend(int(t) for t in toks)
                        self._m_gen_tokens.inc(len(toks))
                        if on_tokens is not None:
                            on_tokens(toks, frame)

                try:
                    summary = slot.client.generate_stream(
                        prefix + accepted, session=session,
                        max_new=max_new - len(accepted),
                        temperature=temperature, top_k=top_k, seed=seed,
                        on_frame=chunk_cb, timeout_s=left,
                        trace=attempt_ctx)
                except BaseException as e:
                    if attempt_ctx is not None:
                        obs.record_span(
                            "router_attempt", attempt_ctx, t_attempt,
                            time.monotonic() - t_attempt, router=self.name,
                            replica=slot.name, kind="generate",
                            attempt=attempt, ok=False,
                            error=type(e).__name__)
                    slot.failures += 1
                    obs.event("router_request_failed", router=self.name,
                              replica=slot.name, kind="generate",
                              error=type(e).__name__, attempt=attempt)
                    if self.policy.should_reroute(e, attempt):
                        # no result is lost by re-placing: received frames
                        # are accepted, the next attempt's prefix carries
                        # them, and the replica-side cache (if any) died
                        # with the replica
                        tried.add(slot.name)
                        if session is not None:
                            with self._lock:
                                stale = self._pins.get(session) == slot.name
                                if stale:
                                    self._pins.pop(session, None)
                            if stale:
                                self._m_spills.inc()
                                spills += 1
                        self._m_reroutes.inc()
                        reroutes += 1
                        pause = self.policy.backoff.backoff_s(attempt)
                        t_hop = time.monotonic()
                        if pause > 0:
                            time.sleep(pause)
                        if tr is not None:
                            obs.record_span(
                                "router_reroute", tr.child(), t_hop,
                                time.monotonic() - t_hop, router=self.name,
                                from_replica=slot.name, attempt=attempt,
                                error=type(e).__name__)
                        continue
                    raise
                finally:
                    self._note_inflight(slot, -1)
                if attempt_ctx is not None:
                    obs.record_span(
                        "router_attempt", attempt_ctx, t_attempt,
                        time.monotonic() - t_attempt, router=self.name,
                        replica=slot.name, kind="generate", attempt=attempt,
                        ok=True)
                slot.failures = 0
                if session is not None:
                    with self._lock:
                        self._pins[session] = slot.name
                ok = True
                self._m_gen_completed.inc()
                return {
                    "tokens": accepted,
                    "attempts": attempt,
                    "reroutes": reroutes,
                    "spills": spills,
                    "replica": slot.name,
                    "resumed": bool(summary.get("resumed")),
                }
        except BaseException:
            self._m_gen_failed.inc()
            raise
        finally:
            latency = time.monotonic() - t0
            self._m_gen_latency.observe(
                latency, exemplar=tr.trace_id if tr is not None else None)
            if ticket is not None:
                self.admission.on_result(ticket, latency, ok)
            if tr is not None:
                obs.record_span(
                    "router_request", tr, t0, latency, router=self.name,
                    kind="generate", attempts=attempt, ok=ok)
                self.traces.add(tr.trace_id, latency, ok=ok,
                                kind="generate", attempts=attempt)

    # -- drain / rollout -----------------------------------------------------

    def drain_replica(self, name: str, timeout_s: Optional[float] = None,
                      detach: bool = False) -> bool:
        """Stop routing to ``name``, have it finish accepted work, and
        optionally detach it from the fleet. Returns True when the replica
        reported fully drained."""
        with self._lock:
            slot = self._slots.get(name)
        if slot is None:
            raise KeyError(f"unknown replica {name!r}")
        slot.draining = True
        obs.event("router_drain_begin", router=self.name, replica=name)
        try:
            drained = slot.client.drain(timeout_s)
        except Exception as e:
            obs.event("router_drain_failed", router=self.name, replica=name,
                      error=type(e).__name__)
            drained = False
        if detach:
            self.remove_replica(name)
        obs.event("router_drained", router=self.name, replica=name,
                  drained=drained, detached=detach)
        return drained

    def resume_replica(self, name: str) -> None:
        with self._lock:
            slot = self._slots.get(name)
        if slot is None:
            raise KeyError(f"unknown replica {name!r}")
        slot.client.resume()
        slot.draining = False

    def rolling_update(
        self,
        spec: Dict[str, Any],
        bake_s: float = 1.0,
        burn_threshold: float = 2.0,
        poll_s: float = 0.05,
        min_bake_requests: int = 0,
        update_timeout_s: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Roll ``spec`` across the fleet one replica at a time, auto-rolling
        the WHOLE fleet back on regression.

        Per replica: hot-swap (``update_params`` — traffic keeps flowing and
        queues against whichever complete tree is installed; the compiled
        programs and AOT warm pool carry over), then BAKE: watch the
        replica's scraped SLO burn and breaker state for ``bake_s``. A
        post-swap burn above ``burn_threshold``, an opened breaker, or the
        replica going down/unready counts as a regression → every replica
        updated so far (including this one, if reachable) gets
        ``{"kind": "rollback"}`` and the rollout aborts.

        ``min_bake_requests``: when > 0, the bake window additionally waits
        (within ``bake_s``) until the replica has served that many requests
        since the swap — a bake with no traffic proves nothing.
        """
        report: Dict[str, Any] = {
            "spec": spec, "updated": [], "rolled_back": False,
            "regressed": None, "reason": None,
        }
        for name in self.replicas():
            with self._lock:
                slot = self._slots.get(name)
            if slot is None:
                continue  # removed mid-rollout
            if self._state(slot) == _fleet.DOWN:
                report.setdefault("skipped", []).append(name)
                continue
            try:
                version = slot.client.update_params(
                    spec, timeout_s=update_timeout_s)
            except Exception as e:
                report.update(rolled_back=True, regressed=name,
                              reason=f"update failed: {type(e).__name__}: {e}")
                self._rollback(report["updated"])
                return report
            obs.event("router_rollout_swapped", router=self.name,
                      replica=name, version=version)
            report["updated"].append(name)
            reason = self._bake(slot, bake_s, burn_threshold, poll_s,
                                min_bake_requests)
            if reason is not None:
                report.update(rolled_back=True, regressed=name,
                              reason=reason)
                self._rollback(report["updated"])
                return report
        obs.event("router_rollout_complete", router=self.name,
                  replicas=report["updated"])
        return report

    def _bake(self, slot: _Slot, bake_s: float, burn_threshold: float,
              poll_s: float, min_requests: int) -> Optional[str]:
        """Watch one freshly-swapped replica; returns a regression reason or
        None (healthy bake). With ``min_requests`` > 0 the window extends
        (up to 4x ``bake_s``) until the replica actually served that much
        post-swap traffic — a bake with no traffic proves nothing.

        Burn is judged against the fleet series HISTORY, not just this
        poll: every bake poll (and the background scrape loop) lands in
        ``self.series``, and the regression check takes the windowed MAX
        since the swap — a burn spike between two bake polls still rolls
        the fleet back instead of slipping through the gap."""
        t0 = time.monotonic()
        base = None
        burn_key = obs.series_key(
            "fleet_replica_slo_burn",
            {"fleet": self.name, "replica": slot.name})
        while True:
            s = self._safe_scrape(slot.client)
            slot.scrape = s
            slot.last_scrape_mono = time.monotonic()
            self.series.ingest_scrape(self.name, slot.name, s)
            if not s.get("up"):
                return "replica went down post-swap"
            if s.get("breaker_open"):
                return "breaker opened post-swap"
            burn = float(s.get("slo_burn", 0.0) or 0.0)
            # window anchored EXACTLY at the swap (never floored wider): a
            # pre-swap burn sample — say the spike this rollout is fixing —
            # must not roll a healthy swap back
            hist = self.series.window_agg(
                burn_key, window_s=max(time.monotonic() - t0, 0.0),
                agg="max")
            burn = max(burn, hist if hist is not None else 0.0)
            if burn > burn_threshold:
                return (f"SLO burn {burn:.2f} exceeded threshold "
                        f"{burn_threshold:g} post-swap")
            if base is None:
                base = s.get("requests_total")
            now = time.monotonic()
            if now - t0 >= bake_s:
                served = (None if base is None
                          or s.get("requests_total") is None
                          else s["requests_total"] - base)
                if (min_requests <= 0 or served is None
                        or served >= min_requests
                        or now - t0 >= 4 * bake_s):
                    return None
            time.sleep(poll_s)

    def _rollback(self, names: List[str]) -> None:
        for name in names:
            with self._lock:
                slot = self._slots.get(name)
            if slot is None:
                continue
            try:
                slot.client.update_params({"kind": "rollback"})
                obs.event("router_rollout_rolled_back", router=self.name,
                          replica=name)
            except Exception as e:
                obs.event("router_rollback_failed", router=self.name,
                          replica=name, error=type(e).__name__)

    # -- lifecycle -----------------------------------------------------------

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """Drain the whole fleet under ONE shared deadline (``timeout_s``
        bounds the fleet, not each replica — a wedged replica cannot
        multiply the caller's shutdown wait by N). Router admission stays
        open per replica drain semantics — callers stop submitting; used by
        shutdown."""
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        ok = True
        for name in self.replicas():
            left = (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))
            ok = self.drain_replica(name, timeout_s=left) and ok
        return ok

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            pending = self._pending
        out = {
            "pending": pending,
            "requests": self._m_requests.value,
            "completed": self._m_completed.value,
            "failed": self._m_failed.value,
            "shed": self._m_shed.value,
            "reroutes": self._m_reroutes.value,
            "affinity_spills": self._m_spills.value,
            "replicas": self.statuses(),
        }
        if self.admission is not None:
            out["admission"] = self.admission.stats()
        return out

    def close(self) -> None:
        self._closed.set()
        self._scraper.join(timeout=5)
        if self.admission is not None:
            # fail everything still waiting in the class queues explicitly:
            # the pool shutdown below cancels their worker tokens, so an
            # un-drained WFQ entry would leave its future hanging forever
            for ticket, (fut, _) in self.admission.drain_queue():
                fut._fail(RouterClosed(
                    f"router {self.name!r} closed before dispatch"))
                self._m_failed.inc()
                with self._lock:
                    self._pending -= 1
        self._pool.shutdown(wait=True, cancel_futures=True)
        if self.admission is not None:
            self.admission.close()
        self.fleet_health.close()

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
