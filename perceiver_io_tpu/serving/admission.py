"""Admission control at the router's front door: priority classes,
per-client token-bucket quotas, and weighted-fair queueing.

The r12 router treats every request identically — one global queue limit,
FIFO through the dispatch pool. At fleet scale that is exactly wrong: one
bursting client fills the shared queue and every OTHER client's p99 inherits
the backlog. This module gives the router the three standard isolation
primitives, composed so an over-quota client degrades *its own* service
class while the rest of the fleet's tail stays flat:

- :class:`PriorityClass` — a named class with a scheduling ``weight`` and a
  bounded queue share. Requests name their class (``Router.submit(...,
  priority="gold")``) or inherit the controller's default.
- **per-client token buckets** — each distinct ``client`` id draws from its
  own bucket (``rate_per_s`` sustained, ``burst`` ceiling). An empty bucket
  sheds the request *at admission* with a taxonomy-honest
  :class:`~perceiver_io_tpu.resilience.RejectedError` (``reason="quota"``):
  the failover policy treats it exactly like an engine-side rejection, and
  the shed burns the CLIENT'S class SLO, nobody else's.
- **weighted-fair queueing** — admitted requests enter per-class FIFO queues
  tagged with start-time-fair virtual finish times; the dispatch pool pops
  the globally smallest tag. Under contention each backlogged class receives
  service proportional to its weight — a flooded bronze queue cannot starve
  gold — while an idle system degenerates to plain FIFO (tags only matter
  when there is a backlog to order).

Shedding is bounded per CLASS, not globally: each class owns
``queue_limit`` slots (its share of the controller's total, weight-
proportional unless set explicitly), so a class that outruns its share
sheds with ``reason="class_queue_full"`` while the other classes' slots
stay free. Every admission outcome is counted
(``admission_requests_total`` / ``admission_shed_total{reason=}``), queue
state is live (``admission_queue_depth``, ``admission_wait_seconds``), and
each class gets its own :class:`~perceiver_io_tpu.obs.slo.SLOTracker` so
``slo_error_budget_burn_rate{class=...}`` shows exactly whose budget a
noisy neighbor burned (its own).

The ``router.admit`` fault site fires inside :meth:`AdmissionController.
admit` before any token or queue slot is consumed — a chaos drill can
raise/hang the admission edge without corrupting accounting.

Pure host-side python (stdlib + obs + resilience); importable before jax
initializes, like the rest of ``serving``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.obs.slo import SLO, SLOTracker
from perceiver_io_tpu.resilience import RejectedError, faults

__all__ = [
    "AdmissionController",
    "AdmissionTicket",
    "PriorityClass",
    "TokenBucket",
    "parse_priority_classes",
]

FAULT_SITE = "router.admit"

# distinct per-client token buckets kept live; past the cap the least-
# recently-seen bucket is evicted (a returning client restarts with a full
# burst — bounded memory beats perfect accounting for abandoned client ids)
_MAX_CLIENT_BUCKETS = 4096


@dataclasses.dataclass(frozen=True)
class PriorityClass:
    """One service class: scheduling ``weight`` (relative share of dispatch
    under contention) and an optional explicit per-class ``queue_limit``
    (None = a weight-proportional share of the controller's total)."""

    name: str
    weight: float = 1.0
    queue_limit: Optional[int] = None

    def __post_init__(self):
        if not self.name:
            raise ValueError("priority class needs a name")
        if self.weight <= 0:
            raise ValueError(
                f"class {self.name!r}: weight must be positive, "
                f"got {self.weight}")
        if self.queue_limit is not None and self.queue_limit < 1:
            raise ValueError(
                f"class {self.name!r}: queue_limit must be >= 1")


def parse_priority_classes(text: str) -> List[PriorityClass]:
    """``"gold:8,silver:4,bronze:1"`` → priority classes (the CLI grammar;
    a bare name gets weight 1)."""
    classes = []
    for clause in filter(None, (c.strip() for c in text.split(","))):
        name, _, weight = clause.partition(":")
        classes.append(PriorityClass(
            name=name.strip(), weight=float(weight) if weight else 1.0))
    if not classes:
        raise ValueError(f"no priority classes in {text!r}")
    names = [c.name for c in classes]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate priority class names in {names}")
    return classes


class TokenBucket:
    """The standard leaky-bucket quota: ``rate_per_s`` sustained refill up
    to a ``burst`` ceiling. Monotonic-clock; callers serialize access (the
    controller holds its lock)."""

    __slots__ = ("rate_per_s", "burst", "tokens", "_t_last")

    def __init__(self, rate_per_s: float, burst: float,
                 now: Optional[float] = None):
        if rate_per_s <= 0:
            raise ValueError(f"rate_per_s must be positive, got {rate_per_s}")
        if burst < 1:
            raise ValueError(f"burst must be >= 1, got {burst}")
        self.rate_per_s = float(rate_per_s)
        self.burst = float(burst)
        self.tokens = float(burst)  # a fresh client starts with a full burst
        self._t_last = time.monotonic() if now is None else now

    def try_take(self, now: Optional[float] = None, n: float = 1.0) -> bool:
        now = time.monotonic() if now is None else now
        if now > self._t_last:
            self.tokens = min(self.burst,
                              self.tokens + (now - self._t_last)
                              * self.rate_per_s)
            self._t_last = now
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False


class AdmissionTicket:
    """One admitted request's accounting handle: its class, client id, and
    admission stamp (the WFQ wait histogram's anchor)."""

    __slots__ = ("cls", "client", "t_admit")

    def __init__(self, cls: str, client: Optional[str], t_admit: float):
        self.cls = cls
        self.client = client
        self.t_admit = t_admit


class AdmissionController:
    """Priority classes + per-client quotas + WFQ over one router.

    ``admit()`` is the gate (sheds raise :class:`RejectedError` with a
    ``reason`` attribute); ``enqueue()``/``pop()`` are the WFQ the router's
    dispatch pool drives; ``on_result()`` closes each request's accounting
    (per-class SLO classification).

    ``quota`` (rate, burst) applies PER DISTINCT ``client`` id — each
    client draws from its own bucket — and ``client_quotas`` overrides the
    default for named clients (a paying tenant's bigger bucket; with no
    default ``quota``, ONLY the named clients are limited). Requests with
    no client id bypass quotas (the operator's own traffic); classes and
    WFQ still apply. ``client_classes`` maps a client id to its class when
    the caller does not name one explicitly.
    """

    # pitlint PIT-LOCK: queues, depths, buckets, and the virtual clock are
    # hit from every submitter and every dispatch-pool worker — only under
    # _lock
    _guarded_by = {
        "_queues": "_lock",
        "_depth": "_lock",
        "_buckets": "_lock",
        "_finish": "_lock",
        "_vtime": "_lock",
        "_m_shed": "_lock",
    }

    def __init__(
        self,
        classes: Optional[Sequence[PriorityClass]] = None,
        default_class: Optional[str] = None,
        quota: Optional[Tuple[float, float]] = None,
        client_quotas: Optional[Dict[str, Tuple[float, float]]] = None,
        client_classes: Optional[Dict[str, str]] = None,
        queue_limit: int = 256,
        slo: Optional[SLO] = None,
        name: str = "router",
        registry: Optional[obs.MetricsRegistry] = None,
    ):
        classes = list(classes) if classes else [PriorityClass("default")]
        names = [c.name for c in classes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate priority class names in {names}")
        if queue_limit < len(classes):
            raise ValueError(
                f"queue_limit {queue_limit} below one slot per class "
                f"({len(classes)} classes)")
        self.name = name
        self.classes: Dict[str, PriorityClass] = {c.name: c for c in classes}
        self.default_class = default_class or classes[0].name
        if self.default_class not in self.classes:
            raise ValueError(
                f"default class {self.default_class!r} not among {names}")
        self._client_classes = dict(client_classes or {})
        unknown = set(self._client_classes.values()) - set(self.classes)
        if unknown:
            raise ValueError(
                f"client_classes map to unknown classes {sorted(unknown)}")
        if quota is not None:
            TokenBucket(*quota)  # validate rate/burst eagerly
        self.quota = quota
        self.client_quotas = dict(client_quotas or {})
        for spec in self.client_quotas.values():
            TokenBucket(*spec)
        # weight-proportional queue shares (explicit per-class limits win);
        # every class gets at least one slot
        total_w = sum(c.weight for c in classes)
        self._limits = {
            c.name: (c.queue_limit if c.queue_limit is not None
                     else max(1, int(queue_limit * c.weight / total_w)))
            for c in classes
        }
        self._lock = threading.Lock()
        self._queues: Dict[str, deque] = {n: deque() for n in self.classes}
        self._depth: Dict[str, int] = {n: 0 for n in self.classes}
        self._buckets: "OrderedDict[str, TokenBucket]" = OrderedDict()
        self._finish: Dict[str, float] = {n: 0.0 for n in self.classes}
        self._vtime = 0.0
        reg = registry if registry is not None else obs.get_registry()
        self.registry = reg
        self._m_admitted = {
            n: reg.counter(
                "admission_requests_total",
                "requests admitted through the class gate",
                {"router": name, "class": n})
            for n in self.classes
        }
        self._m_shed: Dict[Tuple[str, str], Any] = {}
        self._m_depth = {
            n: reg.gauge(
                "admission_queue_depth",
                "requests waiting in this class's WFQ queue",
                {"router": name, "class": n})
            for n in self.classes
        }
        self._m_wait = {
            n: reg.histogram(
                "admission_wait_seconds",
                "admission → WFQ dispatch pick-up",
                {"router": name, "class": n})
            for n in self.classes
        }
        # per-class SLO accounting: the noisy-neighbor verdict is that the
        # abuser's class burns ITS budget while the victim's stays whole.
        # burn_alert=None — per-class burn must not 503 the router's
        # /healthz (the router-level SLO owns the health wire)
        self._trackers: Dict[str, SLOTracker] = {}
        if slo is not None:
            for n in self.classes:
                self._trackers[n] = SLOTracker(
                    dataclasses.replace(slo, burn_alert=None),
                    registry=reg, labels={"router": name, "class": n})

    # -- the gate ------------------------------------------------------------

    def resolve_class(self, client: Optional[str],
                      priority: Optional[str]) -> str:
        if priority is not None:
            if priority not in self.classes:
                raise ValueError(
                    f"unknown priority class {priority!r}; one of "
                    f"{sorted(self.classes)}")
            return priority
        if client is not None and client in self._client_classes:
            return self._client_classes[client]
        return self.default_class

    def _bucket_locked(self, client: str, now: float) -> Optional[TokenBucket]:
        b = self._buckets.get(client)
        if b is None:
            spec = self.client_quotas.get(client, self.quota)
            if spec is None:
                return None  # no default and not named: unlimited
            b = TokenBucket(*spec, now=now)
            self._buckets[client] = b
            while len(self._buckets) > _MAX_CLIENT_BUCKETS:
                self._buckets.popitem(last=False)
        else:
            self._buckets.move_to_end(client)
        return b

    def _shed_counter_locked(self, cls: str, reason: str):
        key = (cls, reason)
        counter = self._m_shed.get(key)
        if counter is None:
            counter = self._m_shed[key] = self.registry.counter(
                "admission_shed_total",
                "requests refused at the admission gate, by reason",
                {"router": self.name, "class": cls, "reason": reason})
        return counter

    def _shed(self, cls: str, reason: str, message: str) -> RejectedError:
        self._shed_counter_locked(cls, reason).inc()
        tracker = self._trackers.get(cls)
        if tracker is not None:
            tracker.record(ok=False)  # the shed burns THIS class's budget
        err = RejectedError(message)
        err.reason = reason
        return err

    def admit(self, client: Optional[str] = None,
              priority: Optional[str] = None,
              now: Optional[float] = None) -> AdmissionTicket:
        """Gate one request; returns its ticket or raises
        :class:`RejectedError` (``.reason`` in ``quota`` /
        ``class_queue_full``). The fault site fires FIRST — an injected
        admission failure consumes no token and no queue slot."""
        faults.inject(FAULT_SITE)
        cls = self.resolve_class(client, priority)
        now = time.monotonic() if now is None else now
        with self._lock:
            if client is not None:
                bucket = self._bucket_locked(client, now)
                if bucket is not None and not bucket.try_take(now):
                    raise self._shed(
                        cls, "quota",
                        f"client {client!r} over quota "
                        f"({bucket.rate_per_s:g} req/s sustained, burst "
                        f"{bucket.burst:g}) — request shed in class "
                        f"{cls!r}")
            if self._depth[cls] >= self._limits[cls]:
                raise self._shed(
                    cls, "class_queue_full",
                    f"class {cls!r} queue full "
                    f"({self._depth[cls]}/{self._limits[cls]}) — request "
                    f"shed")
            self._depth[cls] += 1
            depth = self._depth[cls]
        self._m_admitted[cls].inc()
        self._m_depth[cls].set(depth)
        return AdmissionTicket(cls, client, now)

    # -- the weighted-fair queue ---------------------------------------------

    def enqueue(self, ticket: AdmissionTicket, *payload: Any) -> None:
        """Append an admitted request to its class queue, tagged with its
        start-time-fair virtual finish time. ``payload`` rides along
        opaquely (the router stores its future + dispatch thunk)."""
        w = self.classes[ticket.cls].weight
        with self._lock:
            tag = max(self._vtime, self._finish[ticket.cls]) + 1.0 / w
            self._finish[ticket.cls] = tag
            self._queues[ticket.cls].append((tag, ticket, payload))

    def pop(self) -> Optional[Tuple[AdmissionTicket, Tuple[Any, ...]]]:
        """Dequeue the globally next request by WFQ order (smallest virtual
        finish tag across the class heads); None when nothing waits."""
        with self._lock:
            best = None
            for cls, q in self._queues.items():
                if q and (best is None or q[0][0] < best[0]):
                    best = (q[0][0], cls)
            if best is None:
                return None
            tag, cls = best
            _, ticket, payload = self._queues[cls].popleft()
            self._vtime = tag
            self._depth[cls] -= 1
            depth = self._depth[cls]
        self._m_depth[cls].set(depth)
        self._m_wait[cls].observe(time.monotonic() - ticket.t_admit)
        return ticket, payload

    def drain_queue(self) -> List[Tuple[AdmissionTicket, Tuple[Any, ...]]]:
        """Pop EVERYTHING still queued (router shutdown: the caller fails
        each request's future explicitly instead of leaving it hanging);
        each drained request counts as a ``closed`` shed."""
        out = []
        while True:
            item = self.pop()
            if item is None:
                return out
            with self._lock:
                counter = self._shed_counter_locked(item[0].cls, "closed")
            counter.inc()
            out.append(item)

    # -- accounting ----------------------------------------------------------

    def on_result(self, ticket: AdmissionTicket, latency_s: float,
                  ok: bool) -> None:
        """Close one admitted request's books (the router calls this when
        the routed dispatch delivers or fails)."""
        tracker = self._trackers.get(ticket.cls)
        if tracker is not None:
            tracker.record(latency_s=latency_s, ok=ok)

    def queued(self) -> int:
        with self._lock:
            return sum(self._depth.values())

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            depth = dict(self._depth)
            shed_counters = dict(self._m_shed)
        shed: Dict[str, int] = {}
        for (cls, reason), counter in shed_counters.items():
            shed[f"{cls}:{reason}"] = int(counter.value)
        out: Dict[str, Any] = {
            "classes": {
                n: {
                    "weight": c.weight,
                    "queue_limit": self._limits[n],
                    "depth": depth[n],
                    "admitted": int(self._m_admitted[n].value),
                }
                for n, c in self.classes.items()
            },
            "default_class": self.default_class,
            "quota": (None if self.quota is None
                      else {"rate_per_s": self.quota[0],
                            "burst": self.quota[1]}),
            "client_quotas": {
                c: {"rate_per_s": r, "burst": b}
                for c, (r, b) in sorted(self.client_quotas.items())
            },
            "shed": shed,
        }
        if self._trackers:
            out["slo_burn"] = {
                n: round(t.burn_rate(), 4)
                for n, t in self._trackers.items()
            }
        return out

    def close(self) -> None:
        for tracker in self._trackers.values():
            tracker.close()
