"""Pluggable replica transports: HTTP twin, pipelined UDS frames, shmem ring.

The router→replica RPC moves full input/output arrays per call. The portable
path (``HttpReplicaClient`` / ``ReplicaServer``) serializes them as npz over
localhost HTTP — correct everywhere, but at real traffic the router tier pays
a per-request serialize+copy+syscall tax that starves the decode batcher
(ROADMAP item 1). This module puts that hop behind a transport choice:

- ``http`` — the existing portable twin (default; nothing changes).
- ``uds`` — a unix-domain-socket framed protocol: length-prefixed frames
  (one ``sendall`` per frame — unix sockets have no Nagle/delayed-ACK, so
  small frames never hit the 40 ms stall the abandoned prototype died on),
  pooled PERSISTENT connections, and PIPELINED requests: multiple in flight
  per connection, responses matched to requests by id, replica health
  piggybacked on every response frame. Arrays ride a raw dtype/shape/bytes
  codec (:func:`pack_raw_arrays`) — no npz/zlib framing on the hot path.
- ``shmem`` — the uds control channel plus a ``multiprocessing.shared_memory``
  slab per replica: fixed-size slots hold request/response array payloads,
  written once by the producer and read IN PLACE by the consumer
  (``np.frombuffer`` views on the replica side — the arrays cross the
  process boundary without a copy); the socket carries only slot indices and
  metadata. Slot ownership is an explicit client-side state machine
  (:class:`SlotRing`): FREE→WRITING→READY→READING→FREE, every transition
  validated under a lock the PIT-LOCK rule audits. A slot whose response
  never arrived while the connection stayed alive is quarantined (LOST, never
  reused) — the replica may still write into it later; reusing it would hand
  a future request a torn payload. Oversized payloads fall back to inline
  uds frames, so slot geometry bounds memory, not request size.

Contract parity — all three transports speak the SAME fabric contract as the
HTTP twin (pinned by the parametrized suite in ``tests/test_transport.py``):

- the error taxonomy crosses the wire (``raise_wire_error`` bodies:
  breaker_open/rejected/deadline/affinity_lost/engine+transient);
- trace headers propagate (``TraceContext.to_headers`` rides the request
  frame; the replica's ``replica_serve`` span parents to the router's);
- the engine's per-part ``phases`` ride back on the response frame;
- session pins, drain/resume, update_params behave identically (admin verbs
  and the streamed generate RPC ride the replica's always-on HTTP twin —
  the transport choice selects the ``call()`` DATA PLANE only);
- at-most-once on timeout: a client-side deadline with the connection still
  ALIVE raises :class:`~perceiver_io_tpu.resilience.DeadlineExceeded`
  (failover FAILs it — the request may have executed; re-placing it would
  be at-least-once). Only a DEAD connection (reset/EOF — the replica cannot
  have a response in flight) surfaces as ``ConnectionError``, the
  dead-replica signature the failover policy re-routes.

Endpoints are keyed by the replica's HTTP port (host-unique): the uds socket
at :func:`uds_path_for`, the slab at :func:`shm_slab_name` — a supervisor
restart on the same port recreates both, and clients reconnect/re-attach
lazily, so router handles stay valid across restarts exactly like HTTP.
"""

from __future__ import annotations

import io
import itertools
import json
import os
import socket
import struct
import tempfile
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.resilience import DeadlineExceeded, faults
from perceiver_io_tpu.serving.replica import (
    HttpReplicaClient,
    ReplicaApp,
    _wire_error,
    raise_wire_error,
)

TRANSPORTS = ("http", "uds", "shmem")

# sanity bounds on inbound frames: a desynced/garbage stream must fail the
# connection, not allocate gigabytes from a corrupt length prefix
_MAX_HEADER = 1 << 20
_MAX_PAYLOAD = 1 << 31


def uds_path_for(port: int, root: Optional[str] = None) -> str:
    """The replica's unix-socket path, keyed by its (host-unique) HTTP port
    so a restart on the same port lands on the same endpoint."""
    return os.path.join(root or tempfile.gettempdir(), f"pit-uds-{port}.sock")


def shm_slab_name(port: int) -> str:
    """The replica's shared-memory slab name (same port keying)."""
    return f"pit_shm_{port}"


# -- raw array codec ----------------------------------------------------------
#
# npz (pack_arrays) re-buffers every array through zipfile machinery; the
# framed transports carry dtype/shape/bytes directly so the replica side can
# reconstruct zero-copy views (np.frombuffer) on the shmem slab. Layout:
#   u32 count, then per array:
#     u8 len(dtype.str) | dtype.str ascii | u8 ndim | u64*ndim shape |
#     u64 nbytes | raw C-order bytes


def _as_wire_arrays(arrays: Sequence[np.ndarray]) -> List[np.ndarray]:
    out = []
    for a in arrays:
        a = np.asarray(a)
        if not a.flags["C_CONTIGUOUS"]:
            # NOTE: guarded — np.ascontiguousarray would also promote 0-d
            # arrays to 1-d, tearing shape parity with the npz twin
            a = np.ascontiguousarray(a)
        out.append(a)
    return out


def raw_arrays_nbytes(arrays: Sequence[np.ndarray]) -> int:
    total = 4
    for a in arrays:
        total += 1 + len(a.dtype.str) + 1 + 8 * a.ndim + 8 + a.nbytes
    return total


def write_raw_arrays(buf: memoryview, arrays: Sequence[np.ndarray]) -> int:
    """Encode ``arrays`` (already C-contiguous) into ``buf`` at offset 0;
    returns bytes written. Raises ValueError if ``buf`` is too small."""
    if raw_arrays_nbytes(arrays) > len(buf):
        raise ValueError("payload exceeds buffer")
    struct.pack_into(">I", buf, 0, len(arrays))
    off = 4
    for a in arrays:
        d = a.dtype.str.encode("ascii")
        struct.pack_into(f">B{len(d)}sB", buf, off, len(d), d, a.ndim)
        off += 1 + len(d) + 1
        for dim in a.shape:
            struct.pack_into(">Q", buf, off, dim)
            off += 8
        struct.pack_into(">Q", buf, off, a.nbytes)
        off += 8
        buf[off:off + a.nbytes] = a.reshape(-1).view(np.uint8).data
        off += a.nbytes
    return off


def pack_raw_arrays(arrays: Sequence[np.ndarray]) -> bytes:
    arrays = _as_wire_arrays(arrays)
    out = bytearray(raw_arrays_nbytes(arrays))
    write_raw_arrays(memoryview(out), arrays)
    return bytes(out)


def read_raw_arrays(buf, copy: bool = True) -> List[np.ndarray]:
    """Decode arrays from ``buf`` (bytes or memoryview). ``copy=False``
    returns views INTO the buffer (the shmem zero-copy read — valid only
    while the caller holds the slot); ``copy=True`` returns owned, writable
    arrays (anything handed to callers)."""
    mv = memoryview(buf)
    (count,) = struct.unpack_from(">I", mv, 0)
    off = 4
    out: List[np.ndarray] = []
    for _ in range(count):
        (dlen,) = struct.unpack_from(">B", mv, off)
        off += 1
        dtype = np.dtype(bytes(mv[off:off + dlen]).decode("ascii"))
        off += dlen
        (ndim,) = struct.unpack_from(">B", mv, off)
        off += 1
        shape = struct.unpack_from(f">{ndim}Q", mv, off) if ndim else ()
        off += 8 * ndim
        (nbytes,) = struct.unpack_from(">Q", mv, off)
        off += 8
        arr = np.frombuffer(mv[off:off + nbytes], dtype=dtype).reshape(shape)
        out.append(arr.copy() if copy else arr)
        off += nbytes
    return out


# -- framed uds protocol ------------------------------------------------------
#
# frame := u32 header_len | header json | payload (header["plen"] bytes),
# written with ONE sendall per frame. Request headers: {id, op, kind,
# session, timeout_s, trace, plen[, slot, slen]}; response headers: {id, ok,
# phases, h, plen[, slot, slen]} or {id, ok: false, error: {...}, h}. "h" is
# the piggybacked health sample ({ready, draining, queue_depth}) every
# response carries — a router gets a fresh liveness read with every reply,
# between scrapes.


def _send_frame(sock: socket.socket, header: Dict[str, Any],
                payload: bytes = b"") -> None:
    header = dict(header, plen=len(payload))
    body = json.dumps(header).encode()
    sock.sendall(struct.pack(">I", len(body)) + body + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise ConnectionError("transport stream closed mid-frame")
        buf += part
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> Tuple[Dict[str, Any], bytes]:
    (hlen,) = struct.unpack(">I", _recv_exact(sock, 4))
    if hlen > _MAX_HEADER:
        raise ConnectionError(f"transport frame header too large ({hlen})")
    header = json.loads(_recv_exact(sock, hlen).decode())
    plen = int(header.get("plen", 0))
    if plen < 0 or plen > _MAX_PAYLOAD:
        raise ConnectionError(f"transport frame payload too large ({plen})")
    payload = _recv_exact(sock, plen) if plen else b""
    return header, payload


# public aliases for OTHER planes built on the same frame format — the
# elastic buddy-mirror channel (resilience/elastic.py) ships host-side
# checkpoint shards over these frames so there is exactly one length-
# prefixed wire protocol in the tree (same desync-fails-the-connection
# bounds as the replica data plane)
send_frame = _send_frame
recv_frame = _recv_frame


# -- the shared-memory slot ring ----------------------------------------------

FREE = "free"
WRITING = "writing"
READY = "ready"
READING = "reading"
LOST = "lost"

_FORWARD = {  # the legal forward transitions of one request's lifecycle
    (FREE, WRITING), (WRITING, READY), (READY, READING),
}


class SlotRing:
    """Client-side slot ownership over one replica's shared-memory slab.

    The slab itself is dumb bytes; correctness lives in this state machine.
    Each slot is FREE until a request claims it (WRITING), publishes it to
    the replica (READY — the control frame carrying the slot index provides
    the happens-before edge), and consumes the in-place response (READING)
    before releasing. Transitions outside ``_FORWARD`` raise — an
    out-of-order touch is a protocol bug, not a recoverable condition.
    ``quarantine`` parks a slot as LOST when its response never arrived on a
    LIVE connection: the replica may still write into it, so handing it to a
    new request would tear that request's payload. LOST slots are reclaimed
    only by :meth:`invalidate` (the slab handle is being dropped).
    """

    # pitlint PIT-LOCK: slot states are touched by every router worker
    # thread concurrently — all transitions happen under _lock
    _guarded_by = {"_states": "_lock", "_free": "_lock"}

    def __init__(self, shm, slots: int, slot_bytes: int):
        self._shm = shm
        self.slots = int(slots)
        self.slot_bytes = int(slot_bytes)
        self._lock = threading.Lock()
        self._states = [FREE] * self.slots
        self._free = list(range(self.slots))

    def acquire(self, timeout_s: float = 5.0) -> int:
        """FREE→WRITING; blocks briefly under slot pressure, then raises
        RejectedError-shaped pressure as a plain TimeoutError (callers fall
        back to the inline path)."""
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                if self._free:
                    idx = self._free.pop()
                    self._states[idx] = WRITING
                    return idx
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"no free shmem slot within {timeout_s:g}s "
                    f"({self.counts()})")
            time.sleep(0.001)

    def _transition(self, idx: int, new: str) -> None:
        with self._lock:
            old = self._states[idx]
            if (old, new) not in _FORWARD:
                raise RuntimeError(
                    f"illegal slot transition {old}->{new} (slot {idx})")
            self._states[idx] = new

    def mark_ready(self, idx: int) -> None:
        self._transition(idx, READY)

    def mark_reading(self, idx: int) -> None:
        self._transition(idx, READING)

    def release(self, idx: int) -> None:
        """Return a held slot to FREE (idempotent; LOST stays LOST — see
        :meth:`quarantine`)."""
        with self._lock:
            if self._states[idx] in (FREE, LOST):
                return
            self._states[idx] = FREE
            self._free.append(idx)

    def quarantine(self, idx: int) -> None:
        """Park a slot whose response never arrived while the connection
        stayed alive — the replica may still write into it."""
        with self._lock:
            if self._states[idx] in (FREE, LOST):
                return
            self._states[idx] = LOST

    def counts(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {}
            for s in self._states:
                out[s] = out.get(s, 0) + 1
            return out

    def view(self, idx: int) -> memoryview:
        off = _SLAB_HEADER + idx * self.slot_bytes
        return memoryview(self._shm.buf)[off:off + self.slot_bytes]

    def invalidate(self) -> None:
        """Drop the slab handle (replica died: its restart creates a FRESH
        segment under the same name, so this mapping can never see it)."""
        with self._lock:
            self._states = [FREE] * self.slots
            self._free = list(range(self.slots))
        try:
            self._shm.close()
        except (OSError, BufferError):
            pass


# slab names CREATED by this process (the replica side). attach_slab skips
# its tracker workaround for these: in-process fabrics (tests) would
# otherwise double-unregister one tracker entry
_OWNED_SLABS: set = set()

# the slab self-describes its geometry in a fixed header, so clients
# DISCOVER slots/slot_bytes instead of assuming them (a client guessing a
# larger slot size than the replica allocated would write past slot bounds)
_SLAB_MAGIC = b"PITSLAB1"
_SLAB_HEADER = 64  # magic(8) + u32 slots + u64 slot_bytes, padded


def create_slab(port: int, slots: int, slot_bytes: int):
    """Replica side: create (re-create over a stale predecessor) the slab,
    geometry stamped into its header."""
    from multiprocessing import shared_memory

    name = shm_slab_name(port)
    size = _SLAB_HEADER + slots * slot_bytes
    try:
        shm = shared_memory.SharedMemory(name=name, create=True, size=size)
    except FileExistsError:
        stale = shared_memory.SharedMemory(name=name)
        stale.close()
        stale.unlink()
        shm = shared_memory.SharedMemory(name=name, create=True, size=size)
    struct.pack_into(f">{len(_SLAB_MAGIC)}sIQ", shm.buf, 0,
                     _SLAB_MAGIC, slots, slot_bytes)
    _OWNED_SLABS.add(name)
    return shm


def attach_slab(port: int):
    """Client side: attach the replica's slab; returns ``(shm, slots,
    slot_bytes)`` read from the header. Python 3.10's resource tracker
    registers ATTACHMENTS for destruction at process exit — the router
    would unlink a live replica's slab when it exits — so the attachment is
    explicitly unregistered (the replica owns the lifetime)."""
    from multiprocessing import resource_tracker, shared_memory

    name = shm_slab_name(port)
    shm = shared_memory.SharedMemory(name=name)
    if name not in _OWNED_SLABS:
        try:
            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception:
            pass  # tracker layout differs across versions; leak-warn only
    magic, slots, slot_bytes = struct.unpack_from(
        f">{len(_SLAB_MAGIC)}sIQ", shm.buf, 0)
    if magic != _SLAB_MAGIC:
        shm.close()
        raise ConnectionError(
            f"slab {name!r} has no geometry header (torn or foreign)")
    return shm, int(slots), int(slot_bytes)


# -- the replica-side uds server ----------------------------------------------


class UdsReplicaServer:
    """The replica half of the uds/shmem data plane: a unix-socket listener
    over one :class:`ReplicaApp`, serving pipelined framed requests.

    One dedicated BLOCKING accept thread (never a poll timer — the abandoned
    prototype's 5 s stalls came from tying wakeups to accept timing), one
    reader thread per connection, a shared worker pool per server so slow
    calls never head-of-line-block the frame reader, and a per-connection
    write lock so concurrent responses interleave at frame granularity.
    Payloads arriving by slot index are read as zero-copy views on the slab;
    the response is written back into the SAME slot (the client holds it out
    of FREE for the whole exchange) when it fits, inline otherwise.
    """

    # pitlint PIT-LOCK: the live-connection set is mutated by the accept
    # thread and swept by close() — touched only under _lock
    _guarded_by = {"_conns": "_lock"}

    def __init__(self, app: ReplicaApp, path: str,
                 slab=None, slot_bytes: int = 0, workers: int = 8):
        self.app = app
        self.path = path
        self._slab = slab
        self._slot_bytes = int(slot_bytes)
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix=f"{app.name}-uds")
        self._lock = threading.Lock()
        self._conns: List[socket.socket] = []
        self._closing = threading.Event()
        self._health_lock = threading.Lock()
        self._health_cache: Tuple[float, Dict[str, Any]] = (-1.0, {})

    def start(self) -> str:
        if self._listener is not None:
            return self.path
        try:
            os.unlink(self.path)  # a stale endpoint from a killed
        except FileNotFoundError:  # predecessor on this port
            pass
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        listener.bind(self.path)
        listener.listen(64)
        self._listener = listener
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"{self.app.name}-uds-accept",
            daemon=True)
        self._accept_thread.start()
        return self.path

    def _accept_loop(self) -> None:
        while not self._closing.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            with self._lock:
                self._conns.append(conn)
            threading.Thread(
                target=self._conn_loop, args=(conn,),
                name=f"{self.app.name}-uds-conn", daemon=True).start()

    def _conn_loop(self, conn: socket.socket) -> None:
        send_lock = threading.Lock()
        try:
            while True:
                header, payload = _recv_frame(conn)
                if header.get("op") == "ping":
                    with send_lock:
                        _send_frame(conn, {"id": header.get("id"),
                                           "ok": True, "h": self._health()})
                    continue
                self._pool.submit(
                    self._serve_one, conn, send_lock, header, payload)
        except (ConnectionError, OSError, ValueError):
            pass  # client went away / stream desynced: drop the connection
        finally:
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _slot_view(self, slot: int) -> memoryview:
        off = _SLAB_HEADER + slot * self._slot_bytes
        return memoryview(self._slab.buf)[off:off + self._slot_bytes]

    def _health(self) -> Dict[str, Any]:
        """The piggyback sample — cached briefly (it walks the engines)."""
        now = time.monotonic()
        with self._health_lock:
            stamp, cached = self._health_cache
            if now - stamp < 0.1:
                return cached
        app = self.app
        sample = {
            "ready": app.ready,
            "draining": any(e.draining for e in app.engines.values()),
            "queue_depth": sum(e.backlog for e in app.engines.values()),
        }
        with self._health_lock:
            self._health_cache = (now, sample)
        return sample

    def _serve_one(self, conn: socket.socket, send_lock: threading.Lock,
                   header: Dict[str, Any], payload: bytes) -> None:
        rid = header.get("id")
        slot = int(header.get("slot", -1))
        try:
            faults.inject("transport.recv")
            if slot >= 0:
                view = self._slot_view(slot)
                arrays = read_raw_arrays(
                    view[:int(header["slen"])], copy=False)
            else:
                arrays = read_raw_arrays(payload, copy=True)
            trace = obs.TraceContext.from_headers(header.get("trace") or {})
            meta: Dict[str, Any] = {}
            out = _as_wire_arrays(self.app.call(
                header["kind"], arrays,
                session=header.get("session"),
                timeout_s=header.get("timeout_s"),
                trace=trace, meta=meta))
            resp: Dict[str, Any] = {"id": rid, "ok": True,
                                    "h": self._health()}
            if meta.get("phases"):
                resp["phases"] = meta["phases"][:64]  # parity with X-Phases
            body = b""
            if slot >= 0 and raw_arrays_nbytes(out) <= self._slot_bytes:
                resp["slot"] = slot
                resp["slen"] = write_raw_arrays(self._slot_view(slot), out)
            else:
                resp["slot"] = -1  # response outgrew the slot: inline
                body = pack_raw_arrays(out)
            with send_lock:
                faults.inject("transport.send")
                _send_frame(conn, resp, body)
        except BaseException as e:  # mirrored, never a stack trace
            err = json.loads(_wire_error(e).decode())
            try:
                with send_lock:
                    _send_frame(conn, {"id": rid, "ok": False, "error": err,
                                       "h": self._health()})
            except OSError:
                pass  # client already gone

    def close(self) -> None:
        self._closing.set()
        if self._listener is not None:
            try:
                # close() alone does not wake a thread blocked in accept();
                # shutdown() does — without it every close eats the full
                # accept-thread join timeout
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        with self._lock:
            conns, self._conns = list(self._conns), []
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        self._pool.shutdown(wait=False)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)
            self._accept_thread = None
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass


# -- the router-side clients --------------------------------------------------


class _Pending:
    __slots__ = ("event", "header", "payload", "error")

    def __init__(self):
        self.event = threading.Event()
        self.header: Optional[Dict[str, Any]] = None
        self.payload: bytes = b""
        self.error: Optional[BaseException] = None


class _UdsConn:
    """One persistent pipelined connection: a send lock serializes frame
    writes, a reader thread matches response ids to pending waiters, and a
    connection death fails EVERY pending request with the dead-replica
    ConnectionError signature (the failover policy re-routes those — the
    replica is gone, no response can be in flight)."""

    # pitlint PIT-LOCK: the pending map is touched by every caller thread
    # and the reader thread — only under _lock
    _guarded_by = {"_pending": "_lock"}

    def __init__(self, path: str, name: str):
        self._name = name
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.connect(path)
        self._send_lock = threading.Lock()
        self._lock = threading.Lock()
        self._pending: Dict[int, _Pending] = {}
        self.dead = False
        self._reader = threading.Thread(
            target=self._read_loop, name=f"{name}-uds-reader", daemon=True)
        self._reader.start()

    def _read_loop(self) -> None:
        try:
            while True:
                header, payload = _recv_frame(self._sock)
                with self._lock:
                    p = self._pending.pop(int(header.get("id", -1)), None)
                if p is not None:  # orphans (timed-out ids) are dropped
                    p.header, p.payload = header, payload
                    p.event.set()
        except (ConnectionError, OSError, ValueError) as e:
            self._fail_all(e)

    def _fail_all(self, cause: BaseException) -> None:
        self.dead = True
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            pending, self._pending = list(self._pending.values()), {}
        err = ConnectionError(
            f"replica {self._name!r}: connection closed / failed to "
            f"connect ({type(cause).__name__}: {cause})")
        err.__cause__ = cause
        for p in pending:
            p.error = err
            p.event.set()

    def send(self, rid: int, header: Dict[str, Any],
             payload: bytes) -> _Pending:
        p = _Pending()
        with self._lock:
            self._pending[rid] = p
        try:
            with self._send_lock:
                faults.inject("transport.send")
                _send_frame(self._sock, header, payload)
        except (ConnectionError, OSError) as e:
            self._fail_all(e)
        return p

    def forget(self, rid: int) -> None:
        with self._lock:
            self._pending.pop(rid, None)

    def close(self) -> None:
        self._fail_all(ConnectionError("client closed"))


class UdsReplicaClient:
    """Router-side handle speaking the framed uds data plane for ``call()``;
    admin verbs (scrape/drain/resume/update_params/quit) and the streamed
    generate RPC ride the replica's always-on HTTP twin. ``health`` holds
    the latest piggybacked liveness sample (stamped with the receive time)."""

    transport = "uds"

    # pitlint PIT-LOCK: the connection pool is rebuilt by any caller thread
    # on reconnect — touched only under _lock
    _guarded_by = {"_conns": "_lock"}

    def __init__(self, name: str, base_url: str, timeout_s: float = 120.0,
                 pool_size: int = 2, path: Optional[str] = None):
        self.name = name
        self.timeout_s = timeout_s
        self._http = HttpReplicaClient(name, base_url, timeout_s=timeout_s)
        port = int(base_url.rstrip("/").rsplit(":", 1)[1])
        self.port = port
        self.path = path or uds_path_for(port)
        self._pool_size = max(1, int(pool_size))
        self._lock = threading.Lock()
        self._conns: List[_UdsConn] = []
        self._rr = itertools.count()
        self._ids = itertools.count(1)
        self.health: Optional[Dict[str, Any]] = None
        self.health_stamp: float = -1.0

    # -- connection pool -----------------------------------------------------

    def _conn(self) -> _UdsConn:
        turn = next(self._rr)
        with self._lock:
            self._conns = [c for c in self._conns if not c.dead]
            if len(self._conns) >= self._pool_size:
                return self._conns[turn % len(self._conns)]
        try:
            conn = _UdsConn(self.path, self.name)
        except (ConnectionError, OSError, FileNotFoundError) as e:
            raise ConnectionError(
                f"replica {self.name!r}: connection closed / failed to "
                f"connect ({type(e).__name__}: {e})") from e
        with self._lock:
            self._conns.append(conn)
        return conn

    # -- the data plane ------------------------------------------------------

    def _roundtrip(self, header: Dict[str, Any], payload: bytes,
                   timeout_s: Optional[float],
                   ) -> Tuple[Dict[str, Any], bytes]:
        """Send one request frame and wait for its id-matched response.

        At-most-once on timeout: if the wait expires with the connection
        still alive, the request MAY have executed (or still be executing) —
        this raises DeadlineExceeded, which the failover policy FAILs,
        never re-routes. A dead connection raises ConnectionError instead
        (no response can be in flight) and the router re-places the work.
        """
        conn = self._conn()
        rid = next(self._ids)
        header = dict(header, id=rid)
        p = conn.send(rid, header, payload)
        # the replica enforces timeout_s server-side (DeadlineExceeded comes
        # back as a taxonomy frame); the client-side wait is a safety net
        # set BEYOND it so the server's verdict always wins the race
        wait_s = (timeout_s if timeout_s is not None else self.timeout_s)
        if not p.event.wait(timeout=wait_s + 5.0):
            conn.forget(rid)
            raise DeadlineExceeded(
                f"replica {self.name!r}: no response within {wait_s:g}s "
                f"(connection alive — not re-routed: the request may have "
                f"executed)")
        if p.error is not None:
            raise p.error
        faults.inject("transport.recv")
        header = p.header or {}
        h = header.get("h")
        if h is not None:
            self.health, self.health_stamp = h, time.monotonic()
        return header, p.payload

    def _finish_call(self, resp: Dict[str, Any], payload,
                     meta: Optional[Dict[str, Any]]) -> List[np.ndarray]:
        if not resp.get("ok"):
            raise_wire_error(
                json.dumps(resp.get("error", {})).encode(), self.name)
        if meta is not None and resp.get("phases"):
            meta["phases"] = resp["phases"]
        return read_raw_arrays(payload, copy=True)

    # reads straight off a slot view; _finish_call's copy=True is what makes
    # this safe (the arrays own their bytes before the caller frees the slot)
    _finish_call_view = _finish_call

    def call(self, kind: str, arrays: Sequence[np.ndarray],
             session: Optional[str] = None,
             timeout_s: Optional[float] = None,
             trace: Optional[obs.TraceContext] = None,
             meta: Optional[Dict[str, Any]] = None) -> List[np.ndarray]:
        header = {
            "op": "call", "kind": kind, "session": session,
            "timeout_s": timeout_s,
            "trace": trace.to_headers() if trace is not None else None,
        }
        resp, payload = self._roundtrip(
            header, pack_raw_arrays(arrays), timeout_s)
        return self._finish_call(resp, payload, meta)

    # -- admin plane: the HTTP twin ------------------------------------------

    def generate_stream(self, *args, **kwargs):
        return self._http.generate_stream(*args, **kwargs)

    def scrape(self, timeout_s: float = 5.0) -> Dict[str, Any]:
        return self._http.scrape(timeout_s=timeout_s)

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        return self._http.drain(timeout_s)

    def resume(self) -> None:
        self._http.resume()

    def update_params(self, spec: Dict[str, Any],
                      timeout_s: Optional[float] = None) -> int:
        return self._http.update_params(spec, timeout_s)

    def quit(self) -> None:
        self._http.quit()

    def close(self) -> None:
        with self._lock:
            conns, self._conns = list(self._conns), []
        for c in conns:
            c.close()


class ShmemReplicaClient(UdsReplicaClient):
    """The shmem data plane: request arrays are written ONCE into a slot of
    the replica's slab (state machine in :class:`SlotRing`), the uds control
    frame carries only the slot index + metadata, and the replica reads the
    payload in place and writes the response back into the same slot.
    Payloads that outgrow a slot (or slot exhaustion) fall back to inline
    uds frames — geometry bounds memory, never request size."""

    transport = "shmem"

    # pitlint PIT-LOCK: the lazily-attached ring handle is swapped on
    # replica death/reattach by any caller thread — only under _ring_lock
    _guarded_by = {"_ring": "_ring_lock"}

    def __init__(self, name: str, base_url: str, timeout_s: float = 120.0,
                 pool_size: int = 2, path: Optional[str] = None):
        super().__init__(name, base_url, timeout_s=timeout_s,
                         pool_size=pool_size, path=path)
        self._ring_lock = threading.Lock()
        self._ring: Optional[SlotRing] = None

    def ring(self) -> Optional[SlotRing]:
        """The attached slot ring (lazily attached; geometry is read from
        the slab's header — never assumed). None while the replica's slab
        does not exist yet."""
        with self._ring_lock:
            if self._ring is not None:
                return self._ring
        try:
            shm, slots, slot_bytes = attach_slab(self.port)
        except (FileNotFoundError, ConnectionError):
            return None
        ring = SlotRing(shm, slots, slot_bytes)
        with self._ring_lock:
            if self._ring is None:
                self._ring = ring
            return self._ring

    def _drop_ring(self) -> None:
        """The replica died: its restart creates a FRESH segment under the
        same name — this mapping can never see it, so drop and re-attach."""
        with self._ring_lock:
            ring, self._ring = self._ring, None
        if ring is not None:
            ring.invalidate()

    def call(self, kind: str, arrays: Sequence[np.ndarray],
             session: Optional[str] = None,
             timeout_s: Optional[float] = None,
             trace: Optional[obs.TraceContext] = None,
             meta: Optional[Dict[str, Any]] = None) -> List[np.ndarray]:
        arrays = _as_wire_arrays(arrays)
        ring = self.ring()
        if ring is None or raw_arrays_nbytes(arrays) > ring.slot_bytes:
            return super().call(kind, arrays, session=session,
                                timeout_s=timeout_s, trace=trace, meta=meta)
        try:
            idx = ring.acquire()
        except TimeoutError:  # slot pressure: inline fallback, never block
            return super().call(kind, arrays, session=session,
                                timeout_s=timeout_s, trace=trace, meta=meta)
        try:
            slen = write_raw_arrays(ring.view(idx), arrays)
            ring.mark_ready(idx)
            header = {
                "op": "call", "kind": kind, "session": session,
                "timeout_s": timeout_s,
                "trace": trace.to_headers() if trace is not None else None,
                "slot": idx, "slen": slen,
            }
            try:
                resp, payload = self._roundtrip(header, b"", timeout_s)
            except DeadlineExceeded:
                # no response on a LIVE connection: the replica may still
                # write into the slot — quarantine it, never reuse it
                ring.quarantine(idx)
                raise
            except ConnectionError:
                self._drop_ring()  # a restarted replica makes a fresh slab
                raise
            ring.mark_reading(idx)
            if resp.get("ok") and int(resp.get("slot", -1)) == idx:
                # copy=True owns the arrays BEFORE release frees the slot
                return self._finish_call_view(
                    resp, ring.view(idx)[:int(resp["slen"])], meta)
            return self._finish_call(resp, payload, meta)
        finally:
            ring.release(idx)

    def close(self) -> None:
        super().close()
        self._drop_ring()


# -- factory ------------------------------------------------------------------


def make_client(transport: str, name: str, port: int,
                host: str = "127.0.0.1", timeout_s: float = 120.0,
                **kwargs):
    """Build the router-side client for one replica on ``transport``."""
    base_url = f"http://{host}:{port}"
    if transport == "http":
        return HttpReplicaClient(name, base_url, timeout_s=timeout_s)
    if transport == "uds":
        return UdsReplicaClient(name, base_url, timeout_s=timeout_s,
                                **kwargs)
    if transport == "shmem":
        return ShmemReplicaClient(name, base_url, timeout_s=timeout_s,
                                  **kwargs)
    raise ValueError(
        f"unknown transport {transport!r}; one of {TRANSPORTS}")


def serve_transport(app: ReplicaApp, transport: str, port: int,
                    slots: int = 16, slot_bytes: int = 4 << 20,
                    ) -> Optional[UdsReplicaServer]:
    """Replica side: start the extra data-plane server for ``transport``
    next to the always-on HTTP twin (None for ``http``). The caller owns
    ``close()``; the slab (shmem) is created here and unlinked on close."""
    if transport == "http":
        return None
    if transport not in TRANSPORTS:
        raise ValueError(
            f"unknown transport {transport!r}; one of {TRANSPORTS}")
    slab = None
    if transport == "shmem":
        slab = create_slab(port, slots, slot_bytes)
    server = UdsReplicaServer(app, uds_path_for(port), slab=slab,
                              slot_bytes=slot_bytes)
    server.start()
    if slab is not None:
        base_close = server.close

        def close_with_slab():
            base_close()
            try:
                slab.unlink()
            except (OSError, FileNotFoundError):
                pass
            try:
                # in-flight np.frombuffer views may still pin the mapping
                # (BufferError); the segment is already unlinked and the OS
                # frees it when the last mapping drops
                slab.close()
            except (OSError, BufferError):
                pass

        server.close = close_with_slab  # type: ignore[method-assign]
    return server
