"""Replica-side RPC shim: one serving process behind the router tier.

A *replica* is one process-wide set of serving engines (the fused MLM path
plus the encode/decode latent-cache split — ``mlm_apply_fns``) exposed over a
localhost HTTP surface the router consumes. The wire protocol is deliberately
boring — stdlib HTTP, ``np.savez`` bodies — because the interesting contracts
are semantic, not syntactic:

- **arrays in, arrays out** (``POST /rpc/infer|encode|decode``): request body
  is an npz of positional input arrays; a 200 response body is an npz of the
  output pytree's leaves. Anything else is a JSON error that MIRRORS the
  replica-side exception class across the process boundary (rejected /
  breaker_open / deadline / affinity_lost / engine+transient-bool), so the
  router's failover policy classifies a remote failure exactly as it would a
  local one.
- **latent-cache sessions live ON the replica** (``/rpc/encode?session=S``
  stores the latents; ``/rpc/decode?session=S`` reads them): the whole point
  of affinity routing is that the encoded state never re-crosses the wire.
  A replica that died (or restarted) answers a decode for a session it never
  saw with ``affinity_lost`` — the router drops the pin and the caller
  re-encodes (spill-on-death).
- **admin verbs are the rollout surface**: ``/admin/drain`` stops admission
  and returns once accepted work finished (``ServingEngine.drain``),
  ``/admin/resume`` re-opens, ``/admin/update_params`` hot-swaps the served
  tree from a params *spec* (checkpoint path / deploy publication dir
  (digest-verified on load) / reinit seed / scale factor / ``rollback`` to
  the previous tree — kept in memory exactly for the router's
  auto-rollback), ``/admin/quit`` exits cleanly.
- **readiness is explicit** (``GET /statz`` → ``replica.ready``): true only
  once every engine's warm pool is live (the ``engine_ready`` gauges), which
  is what gates a (re)started replica's join — a replica mid-warmup is
  scraped as JOINING and receives no traffic.

``python -m perceiver_io_tpu.serving.replica --port P --preset tiny --cpu``
runs a synthetic-init replica (tests, ``tools/load_bench.py --replicas``);
``--checkpoint/--tokenizer`` serves a real train run (``cli/serve.py
--replicas`` spawns exactly this). SIGTERM/SIGINT drain gracefully and exit
0. ``PIT_FAULTS`` (env) applies inside the replica process, so chaos drills
target one replica's dispatch path (``engine.dispatch.<engine-name>``)
without code changes.

:class:`LocalReplica` is the in-process twin of the HTTP client — the same
call/scrape/drain/update surface over engines in THIS process (tier-1 tests,
single-host load sweeps) with a ``kill()`` that simulates the dead-replica
transport signature (connection errors, sessions lost).
"""

from __future__ import annotations

import argparse
import io
import json
import socket
import sys
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.resilience import (
    AffinityLost,
    BreakerOpen,
    DeadlineExceeded,
    RejectedError,
    classify_error,
    faults,
)

_MAX_SESSIONS = 1024  # FIFO-evicted; a session is one encode's latents


class RemoteEngineError(RuntimeError):
    """A replica-side engine error mirrored across the RPC boundary; carries
    the remote classification as the ``transient`` attribute the taxonomy
    honors (``classify_error``), so failover decisions survive the hop."""

    def __init__(self, message: str, transient: bool):
        super().__init__(message)
        self.transient = transient


# -- wire format -------------------------------------------------------------


def pack_arrays(arrays: Sequence[np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **{f"arr{i}": np.asarray(a) for i, a in enumerate(arrays)})
    return buf.getvalue()


def unpack_arrays(data: bytes) -> List[np.ndarray]:
    with np.load(io.BytesIO(data)) as z:
        return [z[f"arr{i}"] for i in range(len(z.files))]


def _error_body(kind: str, message: str, transient: bool = False) -> bytes:
    return json.dumps(
        {"error": kind, "message": message, "transient": transient}
    ).encode()


_ERROR_KINDS = {
    BreakerOpen: "breaker_open",
    RejectedError: "rejected",
    DeadlineExceeded: "deadline",
    AffinityLost: "affinity_lost",
}


# -- streamed-frame wire format (the generate RPC) ---------------------------
#
# A generate response is a SEQUENCE of length-prefixed frames — 4-byte
# big-endian length + a JSON payload — written incrementally (chunked
# transfer encoding on the HTTP twin), so the router/caller observes tokens
# as they decode instead of waiting out the stream. Token-chunk frames carry
# per-step phase timestamps (`chunk_ms`, `pos`, `steps`); the terminal frame
# is either the `done` summary or an `error` frame mirroring the replica
# exception (the streaming counterpart of `_wire_error` — by the time a
# mid-stream error occurs, the 200 status line is long gone).


def pack_frame(payload: Dict[str, Any]) -> bytes:
    body = json.dumps(payload).encode()
    return len(body).to_bytes(4, "big") + body


def read_frames(read: Callable[[int], bytes]):
    """Yield JSON frames from a ``read(n)`` byte source until EOF. ``read``
    may return short; EOF mid-frame raises ConnectionError (the dead-replica
    signature the failover policy re-routes)."""

    def read_exact(n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            part = read(n - len(buf))
            if not part:
                if buf:
                    raise ConnectionError(
                        "generate stream truncated mid-frame")
                return None
            buf += part
        return buf

    while True:
        header = read_exact(4)
        if header is None:
            return
        body = read_exact(int.from_bytes(header, "big"))
        if body is None:
            raise ConnectionError("generate stream truncated at frame body")
        yield json.loads(body.decode())


def _wire_error(exc: BaseException) -> bytes:
    for cls, kind in _ERROR_KINDS.items():
        if isinstance(exc, cls):
            return _error_body(kind, str(exc))
    return _error_body(
        "engine", f"{type(exc).__name__}: {exc}",
        transient=classify_error(exc) == "transient",
    )


def raise_wire_error(body: bytes, replica: str) -> None:
    """Client side: re-raise the replica's mirrored exception."""
    try:
        err = json.loads(body.decode())
    except (ValueError, UnicodeDecodeError):
        raise RemoteEngineError(
            f"replica {replica!r}: unparseable error body", transient=False)
    kind, msg = err.get("error", "engine"), err.get("message", "")
    prefix = f"replica {replica!r}: "
    if kind == "breaker_open":
        raise BreakerOpen(prefix + msg)
    if kind == "rejected":
        raise RejectedError(prefix + msg)
    if kind == "deadline":
        raise DeadlineExceeded(prefix + msg)
    if kind == "affinity_lost":
        raise AffinityLost(prefix + msg)
    raise RemoteEngineError(prefix + msg, transient=bool(err.get("transient")))


# -- the replica application -------------------------------------------------


class ReplicaApp:
    """One replica's serving state: engines keyed by RPC verb, the latent
    session store, and the params spec machinery (update / in-memory
    rollback) the rolling rollout drives.

    ``params_factory(spec) -> raw param tree`` realizes ``checkpoint`` /
    ``reinit`` specs (the process entry point knows how to build its model);
    ``scale`` and ``rollback`` are handled here. The previous raw tree is
    kept in memory so a rollback is an instant re-install, never a reload.
    """

    def __init__(
        self,
        engines: Dict[str, Any],
        params,
        params_factory: Optional[Callable[[Dict[str, Any]], Any]] = None,
        name: str = "replica",
        registry: Optional[obs.MetricsRegistry] = None,
        assume_ready: bool = False,
        drain_timeout_s: float = 60.0,
        generator=None,
        stream_slo: Optional[obs.SLO] = None,
    ):
        if not engines:
            raise ValueError("ReplicaApp needs at least one engine")
        self.name = name
        self.engines = dict(engines)
        self.drain_timeout_s = drain_timeout_s
        self._params = params
        self._prev_params = None
        self._params_factory = params_factory
        self._update_lock = threading.Lock()
        self._assume_ready = assume_ready
        self._sessions: "OrderedDict[str, Any]" = OrderedDict()
        self._sessions_lock = threading.Lock()
        self.quit_event = threading.Event()
        reg = registry if registry is not None else obs.get_registry()
        # the generative workload (task=generate): an ARGenerator serving
        # streamed continuations with replica-resident session caches —
        # pinned by the router exactly like the latent-cache sessions
        self.generator = generator
        self._gen_store = None
        # stream-shaped SLO (TTFT/ITL targets): classified from the
        # caller-visible frame clock in generate(), scraped as stream_burn
        self.stream_slo_tracker = None
        if (generator is not None and stream_slo is not None
                and stream_slo.stream_signals):
            self.stream_slo_tracker = obs.SLOTracker(
                stream_slo, registry=reg, labels={"replica": name})
        self._gen_lock = threading.Lock()
        self._gen_active = 0        # streams in flight (under _gen_lock)
        self._gen_requests = 0      # streams served (under _gen_lock)
        self._gen_draining = threading.Event()
        if generator is not None:
            from perceiver_io_tpu.inference.generate import (
                GenerateSessionStore,
            )

            # a continuous-batching generator owns arena slots behind its
            # resident sessions — store evictions must free them (epoch-
            # checked in the engine, so a stale handle is a no-op)
            self._gen_store = GenerateSessionStore(
                registry=reg, name=name,
                on_evict=getattr(generator, "release_session", None))
            self._m_gen_requests = reg.counter(
                "replica_generate_requests_total",
                "streamed generate RPCs served",
                {"replica": name, "task": "generate"})
            self._m_gen_tokens = reg.counter(
                "replica_generate_tokens_total",
                "tokens streamed to callers",
                {"replica": name, "task": "generate"})
            self._m_gen_active = reg.gauge(
                "replica_generate_active",
                "generate streams in flight",
                {"replica": name, "task": "generate"})
        self._m_version = reg.gauge(
            "replica_params_version",
            "monotonic count of installed param trees (0 = the boot tree)",
            {"replica": name})
        self._m_sessions = reg.gauge(
            "replica_sessions", "latent-cache sessions resident",
            {"replica": name})

    # -- traffic -------------------------------------------------------------

    def call(self, kind: str, arrays: List[np.ndarray],
             session: Optional[str] = None,
             timeout_s: Optional[float] = None,
             trace: Optional[obs.TraceContext] = None,
             meta: Optional[Dict[str, Any]] = None) -> List[np.ndarray]:
        """Serve one RPC verb. ``trace`` (the caller's propagated context)
        attaches a ``replica_serve`` span and flows into the engine;
        ``meta``, when a dict is passed, is filled with the engine future's
        per-part ``phases`` — the attribution that previously died at the
        engine boundary now crosses the RPC (the HTTP shim rides it back as
        the ``X-Phases`` response header; ``LocalReplica`` fills it
        directly — parity pinned by the fabric tests)."""
        if trace is None:  # untraced: no span bookkeeping at all
            return self._call_inner(kind, arrays, session, timeout_s,
                                    None, meta)
        t0 = time.monotonic()
        serve_ctx = trace.child()
        try:
            out = self._call_inner(kind, arrays, session, timeout_s,
                                   serve_ctx, meta)
        except BaseException as e:
            obs.record_span("replica_serve", serve_ctx, t0,
                            time.monotonic() - t0, replica=self.name,
                            kind=kind, ok=False, error=type(e).__name__)
            raise
        obs.record_span("replica_serve", serve_ctx, t0,
                        time.monotonic() - t0, replica=self.name, kind=kind,
                        ok=True)
        return out

    def _call_inner(self, kind: str, arrays: List[np.ndarray],
                    session: Optional[str],
                    timeout_s: Optional[float],
                    trace: Optional[obs.TraceContext],
                    meta: Optional[Dict[str, Any]]) -> List[np.ndarray]:
        import jax

        engine = self.engines.get(kind)
        if engine is None:
            raise ValueError(
                f"unknown rpc kind {kind!r}; one of {sorted(self.engines)}"
            )
        if kind == "decode" and session is not None:
            with self._sessions_lock:
                latents = self._sessions.get(session)
            if latents is None:
                raise AffinityLost(
                    f"session {session!r} not resident on replica "
                    f"{self.name!r} (encoded elsewhere, or lost to a restart)"
                )
            arrays = [latents, *arrays]
        fut = engine.submit(*arrays, trace=trace)
        out = fut.result(timeout=timeout_s)
        if meta is not None:
            meta["phases"] = fut.phases
        if kind == "encode" and session is not None:
            with self._sessions_lock:
                self._sessions[session] = out
                while len(self._sessions) > _MAX_SESSIONS:
                    self._sessions.popitem(last=False)
                self._m_sessions.set(len(self._sessions))
            # the latents stay HERE (that is the point of affinity); the
            # caller gets the batch/latent geometry as its ack
            return [np.asarray(np.asarray(out).shape, np.int64)]
        return [np.asarray(leaf) for leaf in jax.tree.leaves(out)]

    # -- the generative workload (task=generate) -----------------------------

    def generate(self, prefix: Sequence[int],
                 session: Optional[str] = None,
                 max_new: int = 16,
                 temperature: float = 0.0,
                 top_k: int = 0,
                 seed: int = 0,
                 on_frame: Optional[Callable[[Dict[str, Any]], None]] = None,
                 trace: Optional[obs.TraceContext] = None) -> Dict[str, Any]:
        """Serve one streamed continuation of ``prefix`` (the FULL accepted
        sequence — prompt plus any previously streamed tokens the caller
        holds). When ``session`` names a resident cache whose sequence is
        exactly ``prefix``, decoding continues incrementally; anything else
        (first call, evicted, replica restarted, spilled pin) re-encodes
        from the prefix — which, with the position-folded sampling keys,
        reproduces the identical stream. Frames go to ``on_frame``: token
        chunks with per-step phase timestamps, then a final ``done``
        summary. Returns the summary."""
        if self.generator is None:
            raise ValueError(
                f"replica {self.name!r} serves no generate task")
        if self._gen_draining.is_set():
            raise RejectedError(
                f"replica {self.name!r} is draining — not admitting new "
                "generate streams")
        from perceiver_io_tpu.inference.generate import SamplingConfig

        prefix = [int(t) for t in np.asarray(prefix).reshape(-1)]
        sampling = SamplingConfig(temperature=temperature, top_k=top_k,
                                  seed=seed).normalized()
        with self._gen_lock:
            self._gen_active += 1
            self._m_gen_active.set(self._gen_active)
        t0 = time.monotonic()
        serve_ctx = trace.child() if trace is not None else None
        resident = self._gen_store.match(session, prefix)
        chunks = 0
        # the caller-visible frame clock: TTFT/ITL as this stream's consumer
        # experienced them (the ground truth the engine histograms reconcile
        # against, and the sample the stream SLO classifies)
        t_first: Optional[float] = None
        t_prev = t0
        itl_sum, itl_n = 0.0, 0

        def chunk_cb(tokens: List[int], info: Dict[str, Any]) -> None:
            nonlocal chunks, t_first, t_prev, itl_sum, itl_n
            now = time.monotonic()
            if t_first is None:
                t_first = now
            elif tokens:
                itl_sum += now - t_prev
                itl_n += len(tokens)
            t_prev = now
            chunks += 1
            self._m_gen_tokens.inc(len(tokens))
            if serve_ctx is not None:
                # one span per chunked decode dispatch: multi-step tail
                # attribution — which chunk of which stream burned the time
                dur = info["chunk_ms"] / 1e3
                obs.record_span(
                    "generate_step", serve_ctx.child(),
                    time.monotonic() - dur, dur, replica=self.name,
                    pos=info["pos"], steps=info["steps"])
            if on_frame is not None:
                on_frame({"tokens": tokens, **info})

        try:
            tokens, ses = self.generator.generate(
                prefix, max_new, sampling, on_chunk=chunk_cb,
                session=resident, trace=serve_ctx)
        except BaseException as e:
            if self.stream_slo_tracker is not None:
                # a died stream is bad on every configured stream signal
                self.stream_slo_tracker.record_stream(
                    ttft_s=(None if t_first is None else t_first - t0),
                    itl_s=(itl_sum / itl_n if itl_n else None), ok=False)
            if serve_ctx is not None:
                obs.record_span(
                    "replica_generate", serve_ctx, t0,
                    time.monotonic() - t0, replica=self.name, ok=False,
                    error=type(e).__name__)
            raise
        finally:
            with self._gen_lock:
                self._gen_active -= 1
                self._gen_requests += 1
                self._m_gen_active.set(self._gen_active)
        if ses is not None and len(ses.seq) < self.generator.max_seq_len:
            self._gen_store.put(session, ses)
        else:
            # the continuation exhausted the absolute position budget (or
            # the engine kept no resident state): retire the pin for real —
            # reason-labeled, so drills assert on metrics, not logs
            self._gen_store.remove(session, "finished")
        self._m_gen_requests.inc()
        if self.stream_slo_tracker is not None:
            self.stream_slo_tracker.record_stream(
                ttft_s=(None if t_first is None else t_first - t0),
                itl_s=(itl_sum / itl_n if itl_n else None), ok=True)
        summary = {
            "done": True,
            "tokens_total": len(tokens),
            "chunks": chunks,
            "resumed": resident is not None,
            "ms": round((time.monotonic() - t0) * 1e3, 3),
        }
        if serve_ctx is not None:
            obs.record_span(
                "replica_generate", serve_ctx, t0, time.monotonic() - t0,
                replica=self.name, ok=True, tokens=len(tokens),
                resumed=resident is not None)
        if on_frame is not None:
            on_frame(summary)
        return summary

    # -- rollout surface -----------------------------------------------------

    def update_params(self, spec: Dict[str, Any]) -> int:
        """Hot-swap from a params spec; returns the new version. The engines
        keep their compiled programs (same treedef/avals ⇒ no recompile; the
        AOT warm pool carries over), so a swap is params-preparation time,
        not a compile family."""
        kind = spec.get("kind")
        with self._update_lock:
            if kind == "rollback":
                if self._prev_params is None:
                    raise ValueError("nothing to roll back to")
                tree = self._prev_params
            elif kind == "scale":
                factor = float(spec["factor"])
                tree = _scale_tree(self._params, factor)
            elif kind in ("reinit", "checkpoint", "publication"):
                if self._params_factory is None:
                    raise ValueError(
                        f"this replica cannot realize {kind!r} specs "
                        "(no params factory)"
                    )
                tree = self._params_factory(spec)
            else:
                raise ValueError(
                    f"unknown params spec kind {kind!r}; one of "
                    "rollback|scale|reinit|checkpoint|publication"
                )
            for engine in self.engines.values():
                engine.update_params(tree)
            # the swap RPC answers only once every worker INSTALLED the
            # staged tree (bounded: a worker wedged in a dispatch must not
            # hang the admin surface) — the rollout's bake then watches the
            # new tree from its first poll, never a half-swapped replica
            deadline = time.monotonic() + 10.0
            while (any(e.params_pending for e in self.engines.values())
                   and time.monotonic() < deadline):
                time.sleep(0.002)
            self._prev_params, self._params = self._params, tree
            self._m_version.inc()
            version = int(self._m_version.value)
        obs.event("replica_params_update", replica=self.name, kind=kind,
                  version=version)
        return version

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        timeout_s = self.drain_timeout_s if timeout_s is None else timeout_s
        from perceiver_io_tpu.inference.engine import drain_engines

        # close every door first (drain_engines discipline): generate
        # streams stop admitting before the engines drain, then accepted
        # streams finish within the shared deadline
        self._gen_draining.set()
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        ok = drain_engines(self.engines.values(), timeout_s)
        while True:
            with self._gen_lock:
                active = self._gen_active
            if active == 0:
                return ok
            if deadline is not None and time.monotonic() >= deadline:
                obs.event("replica_generate_drain_timeout",
                          replica=self.name, active=active)
                return False
            time.sleep(0.01)

    def resume(self) -> None:
        self._gen_draining.clear()
        for engine in self.engines.values():
            engine.resume_admission()

    # -- introspection -------------------------------------------------------

    @property
    def ready(self) -> bool:
        return self._assume_ready or all(
            e.ready for e in self.engines.values()
        )

    def status(self) -> Dict[str, Any]:
        """The scrape body the router's load/health view is built from."""
        engines = {}
        queue_depth = inflight = 0
        breaker_open = False
        slo_burn = 0.0
        for key, e in self.engines.items():
            backlog = e.backlog
            queue_depth += backlog
            inflight += e.inflight
            b_open = e.breaker is not None and e.breaker.state == "open"
            breaker_open = breaker_open or b_open
            burn = (e.slo_tracker.burn_rate()
                    if e.slo_tracker is not None
                    and e.slo_tracker.sample_count()
                    >= e.slo_tracker.slo.min_samples else 0.0)
            slo_burn = max(slo_burn, burn)
            engines[key] = {
                "ready": e.ready, "draining": e.draining,
                "backlog": backlog, "breaker_open": b_open,
                "slo_burn": round(burn, 4),
            }
        stream_burn = 0.0
        tr = self.stream_slo_tracker
        if tr is not None:
            for signal in tr.slo.stream_signals:
                # same min_samples quiet period as the request burn: one
                # slow first stream must not degrade a fresh replica
                if tr.stream_sample_count(signal) >= tr.slo.min_samples:
                    stream_burn = max(stream_burn,
                                      tr.stream_burn_rate(signal))
        with self._sessions_lock:
            sessions = len(self._sessions)
        with self._gen_lock:
            gen_active, gen_requests = self._gen_active, self._gen_requests
        return {
            "name": self.name,
            "ready": self.ready,
            # generate streams count as requests (the autoscaler's offered-
            # rate signal must see the second traffic class) and as load
            # (queue_depth steers least-loaded placement)
            "requests_total": gen_requests + sum(
                e.requests_served for e in self.engines.values()),
            "draining": (self._gen_draining.is_set()
                         or any(e.draining for e in self.engines.values())),
            "queue_depth": queue_depth + gen_active,
            "inflight": inflight + gen_active,
            "breaker_open": breaker_open,
            "slo_burn": round(slo_burn, 4),
            "stream_burn": round(stream_burn, 4),
            "params_version": int(self._m_version.value),
            "sessions": sessions,
            "generate_sessions": (len(self._gen_store)
                                  if self._gen_store is not None else 0),
            "generate_active": gen_active,
            # continuous-batching engines expose their dispatch aggregates
            # (slot occupancy, steps/dispatch) — absent for per-session ones
            "decode_batching": (self.generator.stats()
                                if hasattr(self.generator, "stats")
                                else None),
            "engines": engines,
        }

    def close(self) -> None:
        for engine in self.engines.values():
            engine.close()
        closer = getattr(self.generator, "close", None)
        if closer is not None:
            closer()
        if self.stream_slo_tracker is not None:
            self.stream_slo_tracker.close()


def _scale_tree(tree, factor: float):
    import jax
    import jax.numpy as jnp

    return jax.tree.map(
        lambda x: x * factor
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
        tree,
    )


# -- the HTTP surface --------------------------------------------------------


class _TrackedHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that can SEVER live keep-alive connections.

    ``server_close`` only closes the listener; with pooled persistent
    router connections (r22), handler threads keep serving on their open
    sockets after shutdown — a "closed" replica would keep answering. The
    dead-replica contract (ConnectionError, the failover taxonomy's
    reroute class) requires close to cut every live connection, matching
    the uds server's close semantics."""

    daemon_threads = True

    # pitlint PIT-LOCK: accepted sockets are added by the accept loop and
    # discarded by handler threads — touched only under _live_lock
    _guarded_by = {"_live": "_live_lock"}

    def __init__(self, *args, **kwargs):
        self._live: set = set()
        self._live_lock = threading.Lock()
        super().__init__(*args, **kwargs)

    def get_request(self):
        sock, addr = super().get_request()
        with self._live_lock:
            self._live.add(sock)
        return sock, addr

    def shutdown_request(self, request):
        with self._live_lock:
            self._live.discard(request)
        super().shutdown_request(request)

    def close_all_connections(self) -> None:
        with self._live_lock:
            live, self._live = list(self._live), set()
        for sock in live:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class ReplicaServer:
    """Loopback HTTP server over one :class:`ReplicaApp` (the replica-side
    half of the RPC shim; ``HttpReplicaClient`` is the router-side half)."""

    def __init__(self, app: ReplicaApp, host: str = "127.0.0.1",
                 port: int = 0,
                 registry: Optional[obs.MetricsRegistry] = None):
        self.app = app
        self._host = host
        self._port = port
        self._registry = registry if registry is not None else obs.get_registry()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    @property
    def url(self) -> Optional[str]:
        return f"http://{self._host}:{self.port}" if self._httpd else None

    def start(self) -> str:
        if self._httpd is not None:
            return self.url
        app, registry = self.app, self._registry

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"  # keep-alive: the client pools
            # persistent connections, and 1.1 gives Content-Length framed
            # bodies on both sides
            disable_nagle_algorithm = True  # small response frames must not
            # sit behind the peer's delayed ACK (the ~40 ms stall mode)

            def log_message(self, *args) -> None:
                pass  # RPC traffic must not spam the replica's stderr

            def _reply(self, code: int, body: bytes,
                       ctype: str = "application/json",
                       extra_headers: Optional[Dict[str, str]] = None,
                       ) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _query(self) -> Dict[str, str]:
                if "?" not in self.path:
                    return {}
                out = {}
                for pair in self.path.split("?", 1)[1].split("&"):
                    k, _, v = pair.partition("=")
                    out[k] = v
                return out

            def _body(self) -> bytes:
                n = int(self.headers.get("Content-Length", 0))
                return self.rfile.read(n) if n else b""

            def do_GET(self) -> None:
                path = self.path.split("?", 1)[0]
                if path == "/healthz":
                    ok, detail = obs.healthz()
                    self._reply(200 if ok else 503,
                                json.dumps(detail).encode() + b"\n")
                elif path == "/statz":
                    ok, detail = obs.healthz()
                    body = {"replica": app.status(), "health": detail,
                            **registry.snapshot()}
                    self._reply(200, json.dumps(body).encode() + b"\n")
                else:
                    self._reply(404, _error_body("not_found", path))

            def _stream_generate(self, q: Dict[str, str]) -> None:
                """The generate RPC: body = npz([prefix ids]); response =
                length-prefixed JSON frames under chunked transfer encoding
                (the streaming twin of the arrays-in/arrays-out verbs)."""
                trace = obs.TraceContext.from_headers(self.headers)
                arrays = unpack_arrays(self._body())
                prefix = arrays[0].reshape(-1)
                started = False

                def send_chunk(data: bytes) -> None:
                    self.wfile.write(f"{len(data):X}\r\n".encode()
                                     + data + b"\r\n")
                    self.wfile.flush()

                def on_frame(frame: Dict[str, Any]) -> None:
                    nonlocal started
                    if not started:
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/octet-stream")
                        self.send_header("Transfer-Encoding", "chunked")
                        self.end_headers()
                        started = True
                    send_chunk(pack_frame(frame))

                try:
                    app.generate(
                        prefix,
                        session=q.get("session"),
                        max_new=int(q.get("max_new", 16)),
                        temperature=float(q.get("temperature", 0.0)),
                        top_k=int(q.get("top_k", 0)),
                        seed=int(q.get("seed", 0)),
                        on_frame=on_frame,
                        trace=trace,
                    )
                except BaseException as e:
                    if not started:
                        self._reply(503, _wire_error(e))
                        return
                    # mid-stream failure: the status line is gone — mirror
                    # the exception as a terminal error frame instead
                    err = json.loads(_wire_error(e).decode())
                    send_chunk(pack_frame(err))
                if not started:
                    self._reply(200, b"")  # degenerate: nothing streamed
                    return
                self.wfile.write(b"0\r\n\r\n")  # terminal chunk
                self.wfile.flush()

            def do_POST(self) -> None:
                path = self.path.split("?", 1)[0]
                q = self._query()
                try:
                    if path == "/rpc/generate":
                        self._stream_generate(q)
                    elif path.startswith("/rpc/"):
                        kind = path[len("/rpc/"):]
                        timeout_s = (float(q["timeout_s"])
                                     if "timeout_s" in q else None)
                        # the propagated trace context rides the request
                        # headers; the engine's per-part phase attribution
                        # rides BACK as a response header (the npz body
                        # stays pure arrays)
                        trace = obs.TraceContext.from_headers(self.headers)
                        meta: Dict[str, Any] = {}
                        out = app.call(kind, unpack_arrays(self._body()),
                                       session=q.get("session"),
                                       timeout_s=timeout_s, trace=trace,
                                       meta=meta)
                        extra = {}
                        if meta.get("phases"):
                            # headers must stay under http.client's 64 KB
                            # line limit: a many-part request (hundreds of
                            # engine parts) would otherwise fail an
                            # ALREADY-SERVED rpc at the router's response
                            # parse — cap the attribution, never the result
                            body_json = json.dumps(meta["phases"][:64])
                            if len(body_json) <= 32768:
                                extra["X-Phases"] = body_json
                        self._reply(200, pack_arrays(out),
                                    "application/octet-stream",
                                    extra_headers=extra)
                    elif path == "/admin/drain":
                        timeout_s = (float(q["timeout_s"])
                                     if "timeout_s" in q else None)
                        drained = app.drain(timeout_s)
                        self._reply(200, json.dumps(
                            {"drained": drained}).encode())
                    elif path == "/admin/resume":
                        app.resume()
                        self._reply(200, b"{}")
                    elif path == "/admin/update_params":
                        spec = json.loads(self._body().decode() or "{}")
                        version = app.update_params(spec)
                        self._reply(200, json.dumps(
                            {"params_version": version}).encode())
                    elif path == "/admin/quit":
                        self._reply(200, b"{}")
                        app.quit_event.set()
                    else:
                        self._reply(404, _error_body("not_found", path))
                except BaseException as e:  # mirrored, never a stack trace
                    self._reply(503, _wire_error(e))

        self._httpd = _TrackedHTTPServer((self._host, self._port), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"{self.app.name}-rpc", daemon=True,
        )
        self._thread.start()
        return self.url

    def close(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            # sever live keep-alive connections too: pooled router clients
            # must see the dead-replica ConnectionError, not stale service
            self._httpd.close_all_connections()
            self._httpd = None
            if self._thread is not None:
                self._thread.join(timeout=5)
                self._thread = None


# -- the router-side clients -------------------------------------------------


class HttpReplicaClient:
    """Router-side handle to one replica process. Transport failures (dead
    replica, mid-request ``kill -9``) surface as ``ConnectionError`` with the
    taxonomy's transient markers — the failover policy re-routes them.

    Requests ride POOLED persistent HTTP/1.1 connections with TCP_NODELAY
    set on both sides: the previous one-urllib-connection-per-call pattern
    wrote headers and body as separate segments, and Nagle holding the
    second segment behind the peer's delayed ACK put a ~40 ms mode on
    small-frame round-trips (the documented trap from the abandoned
    transport prototype — ROADMAP item 1). A request that fails on a pooled
    connection is NOT transparently resent (the replica may have executed
    it); it surfaces as ConnectionError and the failover policy decides."""

    # pitlint PIT-LOCK: idle pooled connections are checked out/in by every
    # router worker thread concurrently — touched only under _pool_lock
    _guarded_by = {"_pool": "_pool_lock"}

    def __init__(self, name: str, base_url: str, timeout_s: float = 120.0,
                 pool_size: int = 4):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        hostport = self.base_url.split("://", 1)[-1]
        host, _, port = hostport.partition(":")
        self._host, self._port = host, int(port or 80)
        self._pool_size = max(1, int(pool_size))
        self._pool_lock = threading.Lock()
        self._pool: List[Any] = []  # idle http.client.HTTPConnection

    def _checkout(self, timeout_s: float):
        import http.client

        with self._pool_lock:
            conn = self._pool.pop() if self._pool else None
        if conn is None:
            conn = http.client.HTTPConnection(
                self._host, self._port, timeout=timeout_s)
        else:
            conn.timeout = timeout_s
        if conn.sock is not None:
            conn.sock.settimeout(timeout_s)
        return conn

    def _checkin(self, conn) -> None:
        with self._pool_lock:
            if len(self._pool) < self._pool_size:
                self._pool.append(conn)
                return
        conn.close()

    def close(self) -> None:
        with self._pool_lock:
            pool, self._pool = list(self._pool), []
        for conn in pool:
            conn.close()

    def _request(self, method: str, path: str, body: Optional[bytes] = None,
                 timeout_s: Optional[float] = None,
                 headers: Optional[Dict[str, str]] = None,
                 meta: Optional[Dict[str, Any]] = None) -> bytes:
        import http.client

        conn = self._checkout(
            timeout_s if timeout_s is not None else self.timeout_s)
        try:
            if conn.sock is None:
                conn.connect()
                # no-delay on the client side too: the request's header and
                # body writes must not wait out the replica's delayed ACK
                conn.sock.setsockopt(
                    socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            faults.inject("transport.send")
            conn.request(method, path, body=body,
                         headers={"Content-Type": "application/octet-stream",
                                  **(headers or {})})
            resp = conn.getresponse()
            data = resp.read()
            faults.inject("transport.recv")
            status = resp.status
            if meta is not None and status < 400:
                phases = resp.getheader("X-Phases")
                if phases:
                    try:
                        meta["phases"] = json.loads(phases)
                    except ValueError:
                        pass  # a torn header degrades attribution only
            reusable = not resp.will_close
        except (http.client.HTTPException, ConnectionError, OSError) as e:
            conn.close()
            raise ConnectionError(
                f"replica {self.name!r}: connection closed / failed to "
                f"connect ({type(e).__name__}: {e})"
            ) from e
        if reusable:
            self._checkin(conn)
        else:
            conn.close()
        if status >= 400:
            # taxonomy bodies ride error statuses (the body was fully read,
            # so the connection above stayed reusable)
            raise_wire_error(data, self.name)
        return data

    def call(self, kind: str, arrays: Sequence[np.ndarray],
             session: Optional[str] = None,
             timeout_s: Optional[float] = None,
             trace: Optional[obs.TraceContext] = None,
             meta: Optional[Dict[str, Any]] = None) -> List[np.ndarray]:
        """One RPC verb. ``trace`` propagates the caller's span context to
        the replica as headers; ``meta`` (a dict, filled in place) receives
        the replica engine's per-part ``phases`` from the response header —
        the router surfaces them on its futures."""
        q = []
        if session is not None:
            q.append(f"session={session}")
        if timeout_s is not None:
            q.append(f"timeout_s={timeout_s:g}")
        path = f"/rpc/{kind}" + ("?" + "&".join(q) if q else "")
        out = self._request("POST", path, pack_arrays(arrays),
                            timeout_s=timeout_s,
                            headers=(trace.to_headers()
                                     if trace is not None else None),
                            meta=meta)
        return unpack_arrays(out)

    def generate_stream(self, prefix: Sequence[int],
                        session: Optional[str] = None,
                        max_new: int = 16,
                        temperature: float = 0.0,
                        top_k: int = 0,
                        seed: int = 0,
                        on_frame: Optional[Callable[[Dict[str, Any]], None]]
                        = None,
                        timeout_s: Optional[float] = None,
                        trace: Optional[obs.TraceContext] = None
                        ) -> Dict[str, Any]:
        """The streamed generate RPC: frames (token chunks with per-step
        phase stamps, then the ``done`` summary) are delivered to
        ``on_frame`` AS THEY ARRIVE; returns the summary. A mid-stream
        error frame re-raises the replica's mirrored exception; a cut
        connection raises ConnectionError — the caller (router) decides
        what already-received tokens mean (they are accepted: re-encode
        from the extended prefix)."""
        import urllib.error
        import urllib.request

        q = [f"max_new={int(max_new)}", f"temperature={float(temperature):g}",
             f"top_k={int(top_k)}", f"seed={int(seed)}"]
        if session is not None:
            q.append(f"session={session}")
        req = urllib.request.Request(
            self.base_url + "/rpc/generate?" + "&".join(q),
            data=pack_arrays([np.asarray(prefix, np.int64)]),
            method="POST",
            headers={"Content-Type": "application/octet-stream",
                     **(trace.to_headers() if trace is not None else {})},
        )
        summary: Optional[Dict[str, Any]] = None
        try:
            with urllib.request.urlopen(
                req, timeout=timeout_s if timeout_s is not None
                else self.timeout_s
            ) as resp:
                for frame in read_frames(resp.read):
                    if "error" in frame:
                        raise_wire_error(
                            json.dumps(frame).encode(), self.name)
                    if frame.get("done"):
                        summary = frame
                    if on_frame is not None:
                        on_frame(frame)
        except urllib.error.HTTPError as e:
            raise_wire_error(e.read(), self.name)
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            if isinstance(e, ConnectionError) and "truncated" in str(e):
                raise
            reason = getattr(e, "reason", e)
            raise ConnectionError(
                f"replica {self.name!r}: connection closed / failed to "
                f"connect ({type(reason).__name__}: {reason})"
            ) from e
        if summary is None:
            raise ConnectionError(
                f"replica {self.name!r}: generate stream ended without a "
                "done frame")
        return summary

    def scrape(self, timeout_s: float = 5.0) -> Dict[str, Any]:
        """The replica's ``/statz`` ``replica`` block, plus ``up``. Never
        raises: an unreachable replica scrapes as ``{"up": False}``."""
        try:
            body = self._request("GET", "/statz", timeout_s=timeout_s)
            status = json.loads(body.decode()).get("replica", {})
            status["up"] = True
            return status
        except Exception as e:
            return {"up": False, "error": f"{type(e).__name__}: {e}"}

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        q = f"?timeout_s={timeout_s:g}" if timeout_s is not None else ""
        body = self._request(
            "POST", "/admin/drain" + q,
            timeout_s=(timeout_s + 10.0) if timeout_s is not None else None,
        )
        return bool(json.loads(body.decode()).get("drained"))

    def resume(self) -> None:
        self._request("POST", "/admin/resume")

    def update_params(self, spec: Dict[str, Any],
                      timeout_s: Optional[float] = None) -> int:
        body = self._request("POST", "/admin/update_params",
                             json.dumps(spec).encode(), timeout_s=timeout_s)
        return int(json.loads(body.decode())["params_version"])

    def quit(self) -> None:
        try:
            self._request("POST", "/admin/quit", timeout_s=5.0)
        except Exception:
            pass  # already gone is fine


class LocalReplica:
    """In-process twin of :class:`HttpReplicaClient` over a
    :class:`ReplicaApp` — the tier-1/test/local-bench transport.

    ``kill()`` simulates ``kill -9``: every subsequent (and in-flight) call
    raises the dead-replica ``ConnectionError`` signature, the session store
    is wiped (the latents died with the 'process'), and scrapes report
    ``up=False`` — until ``revive()`` (the supervisor-restart analogue, which
    also resets admission and reports not-ready until re-warmed)."""

    def __init__(self, app: ReplicaApp):
        self.app = app
        self.name = app.name
        self._dead = threading.Event()

    def _check_dead(self) -> None:
        if self._dead.is_set():
            raise ConnectionError(
                f"replica {self.name!r}: connection closed (replica killed)"
            )

    def call(self, kind: str, arrays: Sequence[np.ndarray],
             session: Optional[str] = None,
             timeout_s: Optional[float] = None,
             trace: Optional[obs.TraceContext] = None,
             meta: Optional[Dict[str, Any]] = None) -> List[np.ndarray]:
        self._check_dead()
        # same trace/meta surface as HttpReplicaClient (parity pinned by
        # the fabric tests): the context flows into the app, the engine's
        # phase attribution flows back through meta
        out = self.app.call(kind, list(arrays), session=session,
                            timeout_s=timeout_s, trace=trace, meta=meta)
        # a kill LANDING mid-request: the work may have run, but the
        # response never reached the router (at-most-once delivery is about
        # responses, not executions)
        self._check_dead()
        return out

    def generate_stream(self, prefix: Sequence[int],
                        session: Optional[str] = None,
                        max_new: int = 16,
                        temperature: float = 0.0,
                        top_k: int = 0,
                        seed: int = 0,
                        on_frame: Optional[Callable[[Dict[str, Any]], None]]
                        = None,
                        timeout_s: Optional[float] = None,
                        trace: Optional[obs.TraceContext] = None
                        ) -> Dict[str, Any]:
        """In-process twin of the streamed generate RPC, with the kill
        semantics of a cut connection: a ``kill()`` landing mid-stream
        suppresses every later frame and raises the dead-replica
        ConnectionError — frames already delivered were accepted (exactly
        the at-most-once boundary the HTTP twin has)."""
        self._check_dead()

        def gated(frame: Dict[str, Any]) -> None:
            self._check_dead()  # the wire died: nothing further arrives
            if on_frame is not None:
                on_frame(frame)

        summary = self.app.generate(
            prefix, session=session, max_new=max_new,
            temperature=temperature, top_k=top_k, seed=seed,
            on_frame=gated, trace=trace)
        self._check_dead()
        return summary

    def scrape(self, timeout_s: float = 5.0) -> Dict[str, Any]:
        if self._dead.is_set():
            return {"up": False, "error": "replica killed"}
        status = self.app.status()
        status["up"] = True
        return status

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        self._check_dead()
        return self.app.drain(timeout_s)

    def resume(self) -> None:
        self._check_dead()
        self.app.resume()

    def update_params(self, spec: Dict[str, Any],
                      timeout_s: Optional[float] = None) -> int:
        self._check_dead()
        return self.app.update_params(spec)

    def quit(self) -> None:
        self.app.quit_event.set()

    def kill(self) -> None:
        self._dead.set()
        with self.app._sessions_lock:
            self.app._sessions.clear()
        if self.app._gen_store is not None:
            # the generation caches died with the 'process'
            self.app._gen_store.clear()

    def revive(self) -> None:
        self.app.resume()
        self._dead.clear()


# -- the replica process entry point -----------------------------------------


def _load_publication_spec(spec: Dict[str, Any]):
    """Realize a ``{"kind": "publication", "path": DIR}`` params spec: the
    deploy-loop rollout surface (``perceiver_io_tpu.deploy``). The load
    VERIFIES the manifest's content digest on the replica — even with the
    router-side admission gate already passed, a tree corrupted between
    gate and install raises here instead of serving."""
    from perceiver_io_tpu.deploy import load_publication

    tree, _ = load_publication(spec["path"], verify_digest=True)
    return tree


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        description="one serving replica behind the router tier "
                    "(perceiver_io_tpu.serving)")
    parser.add_argument("--port", type=int, default=0,
                        help="RPC port (0 = ephemeral; announced on stderr)")
    parser.add_argument("--name", default="replica")
    parser.add_argument("--cpu", action="store_true",
                        help="pin the CPU backend before jax initializes")
    parser.add_argument("--transport", choices=("http", "uds", "shmem"),
                        default="http",
                        help="data plane for the call() RPC: 'uds' adds a "
                             "pipelined unix-socket frame server, 'shmem' "
                             "adds the shared-memory slot slab on top; the "
                             "HTTP surface stays up either way (admin verbs "
                             "+ the streamed generate RPC ride it)")
    parser.add_argument("--shm_slots", type=int, default=16,
                        help="shmem transport: slots in the replica's slab")
    parser.add_argument("--shm_slot_mb", type=float, default=4.0,
                        help="shmem transport: slot size; payloads past it "
                             "fall back to inline uds frames")
    src = parser.add_argument_group("model source")
    src.add_argument("--task", choices=("mlm", "generate"), default="mlm",
                     help="workload class: 'mlm' = the fill-mask engines "
                          "(infer/encode/decode); 'generate' = the "
                          "Perceiver-AR causal LM with the streamed "
                          "generate RPC + session cache")
    src.add_argument("--preset", choices=("tiny", "flagship"), default=None,
                     help="synthetic-init preset (tests/benches; no "
                          "checkpoint needed; task picks the mlm or ar "
                          "variant)")
    src.add_argument("--seed", type=int, default=0,
                     help="preset mode: param init seed")
    src.add_argument("--checkpoint", default=None,
                     help="serve a train_mlm (or, with --task generate, "
                          "train_ar) checkpoint dir instead")
    src.add_argument("--tokenizer", default=None,
                     help="tokenizer json (checkpoint mode)")
    src.add_argument("--step", type=int, default=None)
    src.add_argument("--generate_chunk", type=int, default=8,
                     help="generate task: decode steps per chunked "
                          "dispatch (= streaming granularity)")
    src.add_argument("--decode_batching", action="store_true",
                     help="generate task: continuous batching — pool "
                          "session caches into a slotted arena and pack "
                          "every active stream's steps into ONE batched "
                          "dispatch (token streams identical either way)")
    src.add_argument("--decode_slots", type=int, default=8,
                     help="decode batching: initial arena slots per "
                          "prefill width (power-of-two-bucketed; doubles "
                          "under pressure up to 8x)")
    eng = parser.add_argument_group("engine (mirrors cli/serve.py)")
    eng.add_argument("--max_batch", type=int, default=8)
    eng.add_argument("--max_delay_ms", type=float, default=0.0)
    eng.add_argument("--dtype", choices=("float32", "bfloat16"),
                     default="float32")
    eng.add_argument("--quantize", choices=("none", "int8", "int4"),
                     default="none")
    eng.add_argument("--group_size", type=int, default=None,
                     help="int4 quantization group size along the reduction "
                          "dim (default 128)")
    eng.add_argument("--compile_cache", default=None)
    eng.add_argument("--no_warmup", action="store_true")
    eng.add_argument("--queue_limit", type=int, default=None)
    eng.add_argument("--request_deadline_s", type=float, default=None)
    eng.add_argument("--dispatch_retries", type=int, default=2)
    eng.add_argument("--breaker_failures", type=int, default=0)
    eng.add_argument("--breaker_cooldown_s", type=float, default=5.0)
    eng.add_argument("--heartbeat_deadline_s", type=float, default=None)
    eng.add_argument("--slo_p99_ms", type=float, default=None)
    eng.add_argument("--slo_availability", type=float, default=0.999)
    eng.add_argument("--slo_ttft_ms", type=float, default=None,
                     help="generate task: time-to-first-token target — "
                          "streams over it burn the stream SLO "
                          "(stream_burn in the scrape)")
    eng.add_argument("--slo_itl_ms", type=float, default=None,
                     help="generate task: mean inter-token-latency target "
                          "per stream (same burn wire as --slo_ttft_ms)")
    eng.add_argument("--trace_sample", type=float, default=0.0,
                     help="head-sampling rate for engine-MINTED traces, "
                          "i.e. requests arriving without a propagated "
                          "router context. Default 0: behind a router the "
                          "sampling decision belongs to the router (an "
                          "unsampled request arrives context-less, and a "
                          "replica re-minting for it would double-sample); "
                          "raise only for standalone replica use")
    parser.add_argument("--drain_timeout_s", type=float, default=60.0,
                        help="graceful-exit bound: SIGTERM/SIGINT stop "
                             "admission and wait this long for accepted "
                             "work before exiting")
    parser.add_argument("--events_jsonl", default=None,
                        help="append THIS replica's runtime events and "
                             "request-trace spans as JSON lines here (each "
                             "fleet process writes its own log; "
                             "tools/trace_assemble.py merges them into "
                             "per-request trace trees)")
    parser.add_argument("--events_max_mb", type=float, default=64.0,
                        help="rotate the events file past this size "
                             "(3 numbered segments kept); 0 disables "
                             "rotation. serve.py --replicas forwards its "
                             "--events_max_mb here")
    return parser


def _build_app(args):
    """Returns ``(app, max_seq_len)`` for the warmup example."""
    import jax

    from perceiver_io_tpu.inference.engine import ServingEngine, mlm_apply_fns

    if args.task == "generate":
        return _build_generate_app(args)
    if args.checkpoint:
        if not args.tokenizer:
            raise SystemExit("--checkpoint mode needs --tokenizer")
        from perceiver_io_tpu.data.tokenizer import load_tokenizer
        from perceiver_io_tpu.inference import load_mlm_checkpoint

        tokenizer = load_tokenizer(args.tokenizer)
        model, params, max_seq_len = load_mlm_checkpoint(
            args.checkpoint, tokenizer, step=args.step,
            dtype="bfloat16" if args.dtype == "bfloat16" else None,
        )

        def params_factory(spec):
            if spec.get("kind") == "publication":
                return _load_publication_spec(spec)
            if spec.get("kind") != "checkpoint":
                raise ValueError(f"checkpoint replica got spec {spec!r}")
            _, new_params, _ = load_mlm_checkpoint(
                spec.get("path", args.checkpoint), tokenizer,
                step=spec.get("step"),
                dtype="bfloat16" if args.dtype == "bfloat16" else None,
            )
            return new_params
    else:
        from perceiver_io_tpu.models.presets import flagship_mlm, tiny_mlm

        tiny = (args.preset or "tiny") == "tiny"
        build = tiny_mlm if tiny else flagship_mlm
        vocab = 503 if tiny else 10003
        max_seq_len = 64 if tiny else 512
        model = build(vocab_size=vocab, max_seq_len=max_seq_len)
        ids0 = np.zeros((1, max_seq_len), np.int32)

        def init_params(seed: int):
            return model.init(
                {"params": jax.random.key(seed),
                 "masking": jax.random.key(seed + 1)},
                ids0, ids0 == 0,
            )["params"]

        params = init_params(args.seed)

        def params_factory(spec):
            if spec.get("kind") == "publication":
                return _load_publication_spec(spec)
            if spec.get("kind") != "reinit":
                raise ValueError(f"preset replica got spec {spec!r}")
            return init_params(int(spec.get("seed", 0)))

    slo = None
    if args.slo_p99_ms is not None:
        slo = obs.SLO(latency_target_s=args.slo_p99_ms / 1e3,
                      availability_target=args.slo_availability,
                      name=args.name, burn_alert=None)
    common = dict(
        max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms,
        compute_dtype="bfloat16" if args.dtype == "bfloat16" else None,
        quantize=None if args.quantize == "none" else args.quantize,
        group_size=args.group_size,
        queue_limit=args.queue_limit,
        request_deadline_s=args.request_deadline_s,
        dispatch_retries=args.dispatch_retries,
        breaker_failures=args.breaker_failures,
        breaker_cooldown_s=args.breaker_cooldown_s,
        heartbeat_deadline_s=args.heartbeat_deadline_s,
        compile_cache=args.compile_cache,
        slo=slo,
        trace_sample=args.trace_sample,
    )
    fns = mlm_apply_fns(model)
    engines = {
        kind: ServingEngine(fn, params, name=f"{args.name}-{kind}", **common)
        for kind, fn in fns.items()
    }
    app = ReplicaApp(
        engines, params, params_factory=params_factory, name=args.name,
        assume_ready=args.no_warmup, drain_timeout_s=args.drain_timeout_s,
    )
    return app, max_seq_len


def _build_generate_app(args):
    """The generate-task replica: a Perceiver-AR model behind the streamed
    RPC (plus a dense-forward ``infer`` engine — scoring/perplexity calls
    ride the ordinary arrays verb)."""
    import jax

    from perceiver_io_tpu.inference.engine import ServingEngine
    from perceiver_io_tpu.inference.generate import ARGenerator

    compute_dtype = "bfloat16" if args.dtype == "bfloat16" else None
    if args.checkpoint:
        if not args.tokenizer:
            raise SystemExit("--checkpoint mode needs --tokenizer")
        from perceiver_io_tpu.data.tokenizer import load_tokenizer
        from perceiver_io_tpu.inference.generate import load_ar_checkpoint

        tokenizer = load_tokenizer(args.tokenizer)
        model, params, max_seq_len = load_ar_checkpoint(
            args.checkpoint, tokenizer, step=args.step,
            dtype="bfloat16" if args.dtype == "bfloat16" else None,
        )

        def params_factory(spec):
            if spec.get("kind") == "publication":
                return _load_publication_spec(spec)
            if spec.get("kind") != "checkpoint":
                raise ValueError(f"checkpoint replica got spec {spec!r}")
            _, new_params, _ = load_ar_checkpoint(
                spec.get("path", args.checkpoint), tokenizer,
                step=spec.get("step"),
                dtype="bfloat16" if args.dtype == "bfloat16" else None,
            )
            return new_params
    else:
        from perceiver_io_tpu.models.presets import flagship_ar, tiny_ar

        tiny = (args.preset or "tiny") == "tiny"
        build = tiny_ar if tiny else flagship_ar
        max_seq_len = 64 if tiny else 512
        model = build()
        ids0 = np.zeros((1, max_seq_len), np.int32)

        def init_params(seed: int):
            import jax as _jax

            return model.init(
                {"params": _jax.random.key(seed)}, ids0, ids0 == 0,
            )["params"]

        params = init_params(args.seed)

        def params_factory(spec):
            if spec.get("kind") == "publication":
                return _load_publication_spec(spec)
            if spec.get("kind") != "reinit":
                raise ValueError(f"preset replica got spec {spec!r}")
            return init_params(int(spec.get("seed", 0)))

    if getattr(args, "decode_batching", False):
        from perceiver_io_tpu.inference.batching import ContinuousBatcher

        generator = ContinuousBatcher(
            model, params, max_seq_len=max_seq_len,
            chunk=args.generate_chunk, slots=args.decode_slots,
            max_slots=args.decode_slots * 8,
            compute_dtype=compute_dtype, name=f"{args.name}-gen",
            compile_cache=args.compile_cache,
            heartbeat_deadline_s=args.heartbeat_deadline_s,
        )
    else:
        generator = ARGenerator(
            model, params, max_seq_len=max_seq_len,
            chunk=args.generate_chunk,
            compute_dtype=compute_dtype, name=f"{args.name}-gen",
        )

    def infer_apply(p, token_ids, pad_mask):
        return model.apply({"params": p}, token_ids, pad_mask)

    slo = None
    if args.slo_p99_ms is not None:
        slo = obs.SLO(latency_target_s=args.slo_p99_ms / 1e3,
                      availability_target=args.slo_availability,
                      name=args.name, burn_alert=None)
    stream_slo = None
    if args.slo_ttft_ms is not None or args.slo_itl_ms is not None:
        stream_slo = obs.SLO(
            latency_target_s=(args.slo_p99_ms / 1e3
                              if args.slo_p99_ms is not None else 1.0),
            availability_target=args.slo_availability,
            name=f"{args.name}-stream", burn_alert=None,
            ttft_target_s=(args.slo_ttft_ms / 1e3
                           if args.slo_ttft_ms is not None else None),
            itl_target_s=(args.slo_itl_ms / 1e3
                          if args.slo_itl_ms is not None else None))
    engines = {
        "infer": ServingEngine(
            infer_apply, params, name=f"{args.name}-infer",
            max_batch=args.max_batch, max_delay_ms=args.max_delay_ms,
            compute_dtype=compute_dtype,
            queue_limit=args.queue_limit,
            request_deadline_s=args.request_deadline_s,
            dispatch_retries=args.dispatch_retries,
            breaker_failures=args.breaker_failures,
            breaker_cooldown_s=args.breaker_cooldown_s,
            heartbeat_deadline_s=args.heartbeat_deadline_s,
            compile_cache=args.compile_cache,
            slo=slo,
            trace_sample=args.trace_sample,
        ),
    }
    app = ReplicaApp(
        engines, params, params_factory=params_factory, name=args.name,
        assume_ready=args.no_warmup, drain_timeout_s=args.drain_timeout_s,
        generator=generator, stream_slo=stream_slo,
    )
    return app, max_seq_len


def _warm(app: ReplicaApp, args, max_seq_len: int) -> None:
    if args.task == "generate":
        # prefill-width family + the chunked decode program, then the dense
        # scoring engine's buckets — off the serving path
        def warm_generate():
            try:
                app.generator.warmup()
                ids = np.zeros((1, max_seq_len), np.int32)
                pad = np.zeros((1, max_seq_len), bool)
                app.engines["infer"].warmup(ids, pad)
            except Exception as e:
                print(f"replica: generate warmup failed "
                      f"({type(e).__name__}: {e})", file=sys.stderr)

        threading.Thread(target=warm_generate, name="replica-warm-generate",
                         daemon=True).start()
        return
    ids = np.zeros((1, max_seq_len), np.int32)
    pad = np.zeros((1, max_seq_len), bool)
    positions = np.zeros((1, 2), np.int32)
    app.engines["infer"].warmup(ids, pad, positions, background=True)
    app.engines["encode"].warmup(ids, pad, background=True)

    def warm_decode():
        # the decoder's warmup example needs one latent row
        try:
            latents = app.engines["encode"].predict(ids, pad)
            app.engines["decode"].warmup(latents, positions, background=True)
        except Exception as e:
            print(f"replica: decoder warmup failed ({type(e).__name__}: {e})",
                  file=sys.stderr)

    threading.Thread(target=warm_decode, name="replica-warm-decode",
                     daemon=True).start()


def main(argv: Optional[Sequence[str]] = None) -> None:
    args = build_parser().parse_args(argv)
    if args.cpu:
        from perceiver_io_tpu.utils.platform import ensure_cpu_only

        ensure_cpu_only()
    if args.events_jsonl:
        obs.configure_event_log(
            args.events_jsonl,
            max_bytes=(int(args.events_max_mb * 1024 * 1024)
                       if args.events_max_mb > 0 else None))

    app, max_seq_len = _build_app(args)
    server = ReplicaServer(app, port=args.port)
    url = server.start()
    extra_server = None
    if args.transport != "http":
        from perceiver_io_tpu.serving.transport import serve_transport

        extra_server = serve_transport(
            app, args.transport, server.port, slots=args.shm_slots,
            slot_bytes=int(args.shm_slot_mb * 1024 * 1024))
    print(f"replica {args.name!r}: listening on {url}"
          + (f" (+{args.transport} {extra_server.path})"
             if extra_server is not None else ""),
          file=sys.stderr, flush=True)
    if not args.no_warmup:
        _warm(app, args, max_seq_len)

    import signal

    def _on_signal(signum, frame):
        # graceful drain: stop admitting, finish accepted work, exit 0 —
        # the same contract cli/serve.py honors (a supervisor rotation must
        # not drop the queue)
        print(f"replica {args.name!r}: signal {signum} — draining",
              file=sys.stderr, flush=True)
        flight = getattr(app.generator, "flight", None)
        if flight is not None:
            # last words: the scheduler's recent decision ring goes to the
            # event log BEFORE the drain, so a post-mortem on a killed
            # replica sees why its final rounds idled
            flight.dump(f"signal_{signum}")
        app.quit_event.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _on_signal)
        except ValueError:
            pass  # not the main thread (programmatic use)

    try:
        app.quit_event.wait()
    finally:
        app.drain(args.drain_timeout_s)
        if extra_server is not None:
            extra_server.close()
        server.close()
        app.close()
        obs.configure_event_log(None)
    print(f"replica {args.name!r}: drained and exiting", file=sys.stderr,
          flush=True)


if __name__ == "__main__":
    main()
