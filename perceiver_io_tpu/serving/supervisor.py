"""Replica process supervision: spawn, babysit, restart-with-backoff,
runtime grow/shrink.

The supervisor owns the PROCESS half of the fleet story (the router owns the
TRAFFIC half): it spawns N replica processes (``serving.replica`` CLI),
watches them, and restarts any that die — with capped exponential backoff
(:class:`~perceiver_io_tpu.resilience.RetryPolicy`), on the same port (so
the router's client handle stays valid across a restart), never more than
``max_restarts`` times per replica (a crash-looping replica is detached, not
hammered). The fleet is ELASTIC at runtime: ``add_replica()`` grows it (the
autoscaler's scale-up edge — the newcomer JOINs through the router's
readiness gate) and ``retire()`` shrinks it gracefully (drain RPC → SIGTERM
→ SIGKILL only as a last resort; the port releases with the process and the
babysitter can never restart a retirement).

A restarted replica REJOINS only after its warm pool is live: the router's
scrape loop sees it as JOINING (``ready=False``) until every engine's
``engine_ready`` gauge flips — the restart is invisible to traffic beyond
the failover blip, which is the whole point.

Child-process hygiene reuses the r4 ``--spawn_hosts`` wiring lessons
(``cli/common.py``): children write to LOG FILES, never undrained pipes (a
chatty child deadlocks a pipe at ~64KB); the CPU backend is pinned via the
child's env; SIGTERM gives a child its graceful drain (the replica CLI's
signal handler) before SIGKILL.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.resilience import RetryPolicy
from perceiver_io_tpu.serving.replica import HttpReplicaClient
from perceiver_io_tpu.serving.transport import make_client


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def default_replica_argv(name: str, port: int,
                         extra: Sequence[str] = (),
                         transport: str = "http") -> List[str]:
    """The standard child command: ``python -m
    perceiver_io_tpu.serving.replica --port P --name NAME [extra...]``.
    A non-default ``transport`` rides along so the spawned replica serves
    the matching data plane (its endpoints are keyed by the port)."""
    argv = [sys.executable, "-m", "perceiver_io_tpu.serving.replica",
            "--port", str(port), "--name", name]
    if transport != "http":
        argv += ["--transport", transport]
    return argv + list(extra)


class _Replica:
    def __init__(self, name: str, port: int):
        self.name = name
        self.port = port
        self.proc: Optional[subprocess.Popen] = None
        self.log = None
        self.restarts = 0
        self.restart_at: Optional[float] = None  # backoff gate
        self.failed = False  # crash-looped past max_restarts


class ReplicaSupervisor:
    """Spawn and babysit ``count`` replica processes.

    ``argv_builder(name, port) -> argv`` builds each child's full command
    (default: the ``serving.replica`` CLI via :func:`default_replica_argv`
    with ``extra_args``). ``cpu=True`` pins ``JAX_PLATFORMS=cpu`` in the
    children (the offline fleet; on a real TPU the one local chip cannot
    host N replicas anyway — multi-chip fleets run one replica per chip via
    explicit ``argv_builder`` device selection).
    """

    # pitlint PIT-LOCK: fleet membership is mutated by add_replica/retire
    # (the autoscaler's actuation thread) while the babysitter thread
    # iterates it — touched only under _lock
    _guarded_by = {
        "_replicas": "_lock",
        "_clients": "_lock",
        "_m_restarts": "_lock",
    }

    def __init__(
        self,
        count: int = 3,
        extra_args: Sequence[str] = (),
        argv_builder: Optional[Callable[[str, int], List[str]]] = None,
        base_name: str = "r",
        cpu: bool = True,
        restart_policy: Optional[RetryPolicy] = None,
        max_restarts: int = 5,
        poll_s: float = 0.2,
        log_dir: Optional[str] = None,
        registry: Optional[obs.MetricsRegistry] = None,
        transport: str = "http",
    ):
        if count < 1:
            raise ValueError(f"count must be >= 1, got {count}")
        self.count = count
        self.transport = transport
        self._argv_builder = argv_builder or (
            lambda name, port: default_replica_argv(
                name, port, extra=extra_args, transport=transport)
        )
        self._cpu = cpu
        self._policy = restart_policy or RetryPolicy(
            max_retries=max_restarts, base_s=0.25, max_s=5.0)
        self.max_restarts = max_restarts
        self._poll_s = poll_s
        self._log_dir = log_dir
        self._base_name = base_name
        self._next_index = count
        self._lock = threading.Lock()
        self._replicas: Dict[str, _Replica] = {
            f"{base_name}{i}": _Replica(f"{base_name}{i}", _free_port())
            for i in range(count)
        }
        self._clients: Dict[str, HttpReplicaClient] = {
            name: make_client(transport, name, rep.port)
            for name, rep in self._replicas.items()
        }
        self._registry = (registry if registry is not None
                          else obs.get_registry())
        self._m_restarts = {
            name: self._restart_counter(name) for name in self._replicas
        }
        self._stopping = threading.Event()
        self._monitor: Optional[threading.Thread] = None

    def _restart_counter(self, name: str):
        return self._registry.counter(
            "fleet_replica_restarts_total",
            "unexpected replica exits the supervisor restarted",
            {"replica": name})

    # -- lifecycle -----------------------------------------------------------

    def _env(self) -> Dict[str, str]:
        env = dict(os.environ)
        if self._cpu:
            env["JAX_PLATFORMS"] = "cpu"
        # children must resolve the package even when the parent imported it
        # from a path not on the default sys.path (cli/common.py pattern)
        import perceiver_io_tpu

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(perceiver_io_tpu.__file__)))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        return env

    def _spawn(self, rep: _Replica) -> None:
        if rep.log is None:
            if self._log_dir is not None:
                os.makedirs(self._log_dir, exist_ok=True)
                rep.log = open(
                    os.path.join(self._log_dir, f"{rep.name}.log"), "a")
            else:
                rep.log = tempfile.NamedTemporaryFile(
                    mode="w+", prefix=f"replica_{rep.name}_", suffix=".log",
                    delete=False)
        argv = self._argv_builder(rep.name, rep.port)
        # log FILES, never undrained pipes (cli/common.py: a child that
        # emits ~64KB into a pipe nobody reads deadlocks)
        rep.proc = subprocess.Popen(
            argv, env=self._env(), stdout=rep.log,
            stderr=subprocess.STDOUT, text=True,
        )
        obs.event("replica_spawned", replica=rep.name, port=rep.port,
                  pid=rep.proc.pid, restarts=rep.restarts)

    def start(self) -> List[HttpReplicaClient]:
        """Spawn the fleet and start the babysitter; returns the clients
        (hand them to a :class:`Router`). Does NOT wait for readiness —
        ``wait_ready()`` does, or let the router's JOINING state gate."""
        with self._lock:
            reps = list(self._replicas.values())
            clients = list(self._clients.values())
        for rep in reps:
            if rep.proc is None:  # add_replica may already have spawned it
                self._spawn(rep)
        self._monitor = threading.Thread(
            target=self._watch, name="replica-supervisor", daemon=True)
        self._monitor.start()
        return clients

    def add_replica(self, name: Optional[str] = None) -> HttpReplicaClient:
        """Grow the fleet by one replica at runtime (the autoscaler's
        scale-up edge): allocate a fresh port, spawn the child, and return
        its client — hand it to ``Router.add_replica``. Does NOT wait for
        readiness: the router scrapes the newcomer as JOINING until its
        warm pool is live, so traffic never sees a cold replica."""
        with self._lock:
            if name is None:
                name = f"{self._base_name}{self._next_index}"
                self._next_index += 1
            if name in self._replicas:
                raise ValueError(f"replica {name!r} already exists")
            rep = _Replica(name, _free_port())
            client = make_client(self.transport, name, rep.port)
            self._replicas[name] = rep
            self._clients[name] = client
            self._m_restarts[name] = self._restart_counter(name)
        self._spawn(rep)
        return client

    def retire(self, name: str, drain_timeout_s: float = 30.0,
               term_timeout_s: float = 10.0) -> bool:
        """Shrink the fleet by one replica: graceful drain (the replica
        finishes every accepted request) → SIGTERM (its signal handler
        exits 0) → SIGKILL only past ``term_timeout_s``. The replica leaves
        the supervised set FIRST, so the babysitter can never restart a
        retirement, and its port is released with the process. Returns
        whether the replica reported fully drained.

        Callers draining through a router (``Router.drain_replica(...,
        detach=True)``) should retire AFTER the router detach — the router
        stops placing work, this call reaps the process."""
        with self._lock:
            rep = self._replicas.pop(name, None)
            client = self._clients.pop(name, None)
            self._m_restarts.pop(name, None)
        if rep is None:
            raise KeyError(f"unknown replica {name!r}")
        rep.failed = True  # a babysitter holding a stale snapshot skips it
        drained = False
        if rep.proc is not None and rep.proc.poll() is None:
            try:
                drained = bool(client.drain(drain_timeout_s))
            except Exception:
                pass  # an unresponsive replica still gets the SIGTERM drain
            rep.proc.terminate()
            try:
                rep.proc.wait(timeout=term_timeout_s)
            except subprocess.TimeoutExpired:
                rep.proc.kill()
                rep.proc.wait(timeout=5)
        if rep.log is not None:
            rep.log.close()
            rep.log = None
        # the retired replica's restart counter leaves /metrics with it
        # (autoscale churn mints monotonically-new names — without this the
        # exposition grows one dead counter per retirement, forever)
        self._registry.remove("fleet_replica_restarts_total",
                              {"replica": name})
        obs.event("replica_retired", replica=name, port=rep.port,
                  drained=drained)
        return drained

    def clients(self) -> List[HttpReplicaClient]:
        with self._lock:
            return list(self._clients.values())

    def client(self, name: str) -> HttpReplicaClient:
        with self._lock:
            return self._clients[name]

    def ports(self) -> Dict[str, int]:
        """``{name: http_port}`` for the current fleet — the key every
        transport endpoint derives from (``uds_path_for``/``shm_slab_name``),
        so callers can build a SECOND client set over the same replicas
        (load_bench's transport A/B runs http and uds/shmem arms against
        one live fleet)."""
        with self._lock:
            return {name: rep.port for name, rep in self._replicas.items()}

    def wait_ready(self, timeout_s: float = 180.0,
                   names: Optional[Sequence[str]] = None) -> None:
        """Block until every (named) replica scrapes ready — the AOT warm
        pool is live and traffic can flow without a compile wall."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            clients = dict(self._clients)
        waiting = list(names if names is not None else clients)
        while waiting:
            waiting = [
                n for n in waiting
                if not clients[n].scrape(timeout_s=2.0).get("ready")
            ]
            if not waiting:
                return
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"replicas not ready within {timeout_s:g}s: {waiting}"
                )
            time.sleep(self._poll_s)

    # -- the babysitter ------------------------------------------------------

    def _watch(self) -> None:
        while not self._stopping.wait(self._poll_s):
            with self._lock:
                reps = list(self._replicas.values())
                counters = dict(self._m_restarts)
            for rep in reps:
                if rep.proc is None or rep.failed:
                    continue
                rc = rep.proc.poll()
                if rc is None:
                    continue
                now = time.monotonic()
                if rep.restart_at is None:
                    rep.restarts += 1
                    counter = counters.get(rep.name)
                    if counter is not None:
                        counter.inc()
                    if rep.restarts > self.max_restarts:
                        rep.failed = True
                        obs.event("replica_crash_looped", replica=rep.name,
                                  rc=rc, restarts=rep.restarts)
                        print(
                            f"[supervisor] replica {rep.name!r} crash-looped "
                            f"({rep.restarts} restarts) — detaching",
                            file=sys.stderr,
                        )
                        continue
                    pause = self._policy.backoff_s(rep.restarts)
                    rep.restart_at = now + pause
                    obs.event("replica_exited", replica=rep.name, rc=rc,
                              restart_in_s=round(pause, 3),
                              restarts=rep.restarts)
                if now >= rep.restart_at:
                    rep.restart_at = None
                    self._spawn(rep)

    def note_stable(self, name: str) -> None:
        """Reset a replica's restart budget after proven stability (callers
        decide what 'stable' means — e.g. N minutes serving)."""
        with self._lock:
            self._replicas[name].restarts = 0

    # -- chaos / teardown ----------------------------------------------------

    def kill(self, name: str, sig: int = signal.SIGKILL) -> int:
        """Send ``sig`` to a replica (the chaos drill's ``kill -9``); returns
        the pid. The babysitter restarts it with backoff."""
        with self._lock:
            rep = self._replicas[name]
        if rep.proc is None or rep.proc.poll() is not None:
            raise RuntimeError(f"replica {name!r} is not running")
        pid = rep.proc.pid
        os.kill(pid, sig)
        obs.event("replica_killed", replica=name, pid=pid, sig=int(sig))
        return pid

    def pid(self, name: str) -> Optional[int]:
        with self._lock:
            rep = self._replicas[name]
        return rep.proc.pid if rep.proc is not None else None

    def restarts(self, name: str) -> int:
        with self._lock:
            return self._replicas[name].restarts

    def stop(self, timeout_s: float = 20.0) -> None:
        """Graceful fleet shutdown: quit RPC → SIGTERM (drain) → SIGKILL."""
        self._stopping.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
        with self._lock:
            replicas = dict(self._replicas)
            clients = dict(self._clients)
        for name, rep in replicas.items():
            if rep.proc is None or rep.proc.poll() is not None:
                continue
            clients[name].quit()
        deadline = time.monotonic() + timeout_s
        for rep in replicas.values():
            if rep.proc is None:
                continue
            left = max(0.1, deadline - time.monotonic())
            try:
                rep.proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                rep.proc.terminate()
                try:
                    rep.proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    rep.proc.kill()
                    rep.proc.wait(timeout=5)
        for rep in replicas.values():
            if rep.log is not None:
                rep.log.close()

    def log_path(self, name: str) -> Optional[str]:
        with self._lock:
            rep = self._replicas[name]
        return rep.log.name if rep.log is not None else None

    def __enter__(self) -> "ReplicaSupervisor":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
