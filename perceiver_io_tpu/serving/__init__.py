"""Multi-replica serving fabric: router tier, replica RPC shim, supervisor.

One serving process is a single point of failure no matter how self-healing
its engine is (r9): a wedged dispatch or a killed process is a full outage.
This package composes the ingredients r6–r11 built — ``engine_ready``/queue
/breaker/SLO-burn gauges, ``update_params`` hot-swap, AOT warm pools,
graceful drain — into redundancy:

- :mod:`replica` — one serving process behind the fleet: engines exposed
  over a localhost RPC surface (arrays in/out, mirrored error classes,
  latent-cache sessions resident ON the replica), plus the in-process
  :class:`LocalReplica` twin for tests and single-host sweeps.
- :mod:`supervisor` — spawns and babysits the replica processes:
  restart-with-backoff on crash, rejoin gated on the warm pool
  (``engine_ready``), crash-loop detachment.
- :mod:`router` — the traffic tier: least-loaded health-aware dispatch,
  transparent failover (zero lost accepted requests when a replica dies),
  latent-cache affinity with spill-on-death, graceful drain, and rolling
  rollout with fleet-wide auto-rollback.
- :mod:`transport` — pluggable router→replica data planes for the array
  RPC: the portable HTTP twin, pipelined unix-socket frames, and the
  zero-copy shared-memory slot ring (``make_client`` / ``--transport``).
- :mod:`admission` — the router's front-door policy: priority classes,
  per-client token-bucket quotas, and weighted-fair queueing, so one
  bursting client degrades its own SLO class instead of the fleet's.
- :mod:`autoscale` — the actuation half of the control loop: an
  ``Autoscaler`` drives replica spawn / drain-then-retire from the
  windowed SLO-burn and queue series in the router's fleet store, seeded
  by the measured per-replica capacity fit, with hold-down + hysteresis
  so a bursty minute never flaps the fleet.

Importing this package never initializes a jax backend.
"""

from perceiver_io_tpu.serving.admission import (
    AdmissionController,
    PriorityClass,
    TokenBucket,
    parse_priority_classes,
)
from perceiver_io_tpu.serving.autoscale import (
    Autoscaler,
    AutoscalePolicy,
    CallbackPool,
    SupervisorPool,
)
from perceiver_io_tpu.serving.replica import (
    HttpReplicaClient,
    LocalReplica,
    RemoteEngineError,
    ReplicaApp,
    ReplicaServer,
)
from perceiver_io_tpu.serving.router import Router, RouterClosed, RouterFuture
from perceiver_io_tpu.serving.supervisor import (
    ReplicaSupervisor,
    default_replica_argv,
)
from perceiver_io_tpu.serving.transport import (
    TRANSPORTS,
    ShmemReplicaClient,
    SlotRing,
    UdsReplicaClient,
    UdsReplicaServer,
    make_client,
    serve_transport,
)

__all__ = [
    "AdmissionController",
    "Autoscaler",
    "AutoscalePolicy",
    "CallbackPool",
    "HttpReplicaClient",
    "LocalReplica",
    "PriorityClass",
    "RemoteEngineError",
    "ReplicaApp",
    "ReplicaServer",
    "ReplicaSupervisor",
    "Router",
    "RouterClosed",
    "RouterFuture",
    "ShmemReplicaClient",
    "SlotRing",
    "SupervisorPool",
    "TRANSPORTS",
    "TokenBucket",
    "UdsReplicaClient",
    "UdsReplicaServer",
    "default_replica_argv",
    "make_client",
    "parse_priority_classes",
]
