"""Interop with the reference implementation's published artifacts.

The reference ships pretrained PyTorch-Lightning checkpoints and its transfer
workflow starts from them (reference ``README.md:46-48``,
``train/train_seq_clf.py:18-28``): users download ``epoch=…-val_loss=….ckpt``
files and hand them to ``--mlm_checkpoint`` / ``--clf_checkpoint``. For "same
capabilities" that entry point must work here too, so this module converts a
Lightning checkpoint's torch ``state_dict`` into this framework's flax params
pytree — numerically exact (golden-tested at 2e-5 end to end) — and can write
the result as an Orbax checkpoint directory that the existing
``--mlm_checkpoint`` / ``--clf_checkpoint`` / ``restore_params`` paths consume
unchanged.

Key-space being translated (reference ``perceiver/model.py``):

- ``PerceiverMLM`` holds named submodules → keys ``encoder.…`` / ``decoder.…``
  (``model.py:296-303``); ``PerceiverIO`` is a ``Sequential`` → positional keys
  ``0.…`` / ``1.…`` (``model.py:321-325``). Lightning prefixes everything with
  ``model.`` (``lightning.py:87,183``).
- an encoder layer is ``Sequential(cross_attention_layer,
  self_attention_block)`` where each attention layer is
  ``Sequential(Residual(attn), Residual(mlp))`` (``model.py:29-44``), so torch
  paths look like ``layer_1.0.0.module.q_norm.weight`` — positional Sequential
  indices plus the ``Residual.module`` wrapper — while the flax tree uses the
  named modules ``layer_1.cross_attention_layer.cross_attention.q_norm.scale``.
- ``torch.nn.MultiheadAttention`` stores merged ``in_proj_weight`` when
  q/k/v dims agree, separate ``{q,k,v}_proj_weight`` otherwise; both map onto
  this framework's always-split ``q_proj``/``k_proj``/``v_proj`` params.

Tokenizer-artifact interop (the HF ``tokenizers`` JSON schema the reference
caches, e.g. ``.cache/imdb-tokenizer-10003.json``) lives in
``data/tokenizer.py``; together the two make a reference checkpoint + its
exact vocab fully usable from this framework.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Mapping, Optional, Tuple

import numpy as np

__all__ = [
    "convert_state_dict",
    "load_lightning_checkpoint",
    "import_lightning_checkpoint",
    "convert_hparams",
    "export_orbax_checkpoint",
    "export_state_dict",
    "export_lightning_checkpoint",
]


# -- small pytree helpers ----------------------------------------------------


def _assign(tree: Dict[str, Any], path: List[str], value) -> None:
    node = tree
    for key in path[:-1]:
        node = node.setdefault(key, {})
    if path[-1] in node:
        raise ValueError(f"duplicate parameter at {'/'.join(path)}")
    node[path[-1]] = value


def _np(t) -> np.ndarray:
    """torch tensor / array-like → float32 numpy copy (params are f32)."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.array(t, dtype=np.float32)


# -- per-module translators ---------------------------------------------------
#
# Each takes the remaining torch path (already split on '.') and returns the
# flax path, or buffers MHA leaves for post-processing.


def _translate_linear(rest: List[str], name: str) -> Tuple[List[str], bool]:
    """torch Linear → flax Dense: weight is (out, in) → kernel (in, out)."""
    if rest == ["weight"]:
        return [name, "kernel"], True
    if rest == ["bias"]:
        return [name, "bias"], False
    raise KeyError(f"unexpected Linear leaf {rest!r}")


def _translate_ln(rest: List[str], name: str) -> List[str]:
    if rest == ["weight"]:
        return [name, "scale"]
    if rest == ["bias"]:
        return [name, "bias"]
    raise KeyError(f"unexpected LayerNorm leaf {rest!r}")


def _translate_mlp(rest: List[str]) -> Tuple[List[str], bool]:
    """Reference mlp = Sequential(LN, Linear, GELU, Linear) (model.py:20-26):
    positional children 0/1/3 → named norm/dense_1/dense_2."""
    idx, leaf = rest[0], rest[1:]
    if idx == "0":
        return ["mlp"] + _translate_ln(leaf, "norm"), False
    if idx == "1":
        path, transpose = _translate_linear(leaf, "dense_1")
        return ["mlp"] + path, transpose
    if idx == "3":
        path, transpose = _translate_linear(leaf, "dense_2")
        return ["mlp"] + path, transpose
    raise KeyError(f"unexpected mlp child {rest!r}")


def _translate_attn_module(rest: List[str], kind: str) -> Tuple[List[str], bool, bool]:
    """CrossAttention / SelfAttention body (model.py:77-116).

    Returns (flax_path, transpose, is_mha_leaf). MHA leaves keep their torch
    name as the final path element; a later pass splits/merges them into
    q_proj/k_proj/v_proj/out_proj.
    """
    name = "cross_attention" if kind == "cross" else "self_attention"
    if rest[0] in ("q_norm", "kv_norm", "norm"):
        return [name] + _translate_ln(rest[1:], rest[0]), False, False
    if rest[:2] == ["attention", "attention"]:
        # MultiHeadAttention wrapper (.attention) around nn.MultiheadAttention
        # (.attention) — model.py:59-74
        return [name, "attention", ".".join(rest[2:])], False, True
    raise KeyError(f"unexpected attention leaf {rest!r}")


def _translate_attn_layer(rest: List[str], kind: str) -> Tuple[List[str], bool, bool]:
    """cross/self_attention_layer = Sequential(Residual(attn), Residual(mlp))
    (model.py:29-40): child 0.module = attention, 1.module = mlp."""
    if rest[:2] == ["0", "module"]:
        return _translate_attn_module(rest[2:], kind)
    if rest[:2] == ["1", "module"]:
        path, transpose = _translate_mlp(rest[2:])
        return path, transpose, False
    raise KeyError(f"unexpected attention-layer child {rest!r}")


def _translate_encoder(rest: List[str]) -> Optional[Tuple[List[str], bool, bool]]:
    head = rest[0]
    if head == "input_adapter":
        sub = rest[1:]
        if sub == ["text_embedding", "weight"]:
            # embedding matrices are (vocab, C) in both frameworks
            return ["input_adapter", "text_embedding", "embedding"], False, False
        if sub == ["pos_encoding"]:
            return ["input_adapter", "pos_encoding"], False, False
        if sub == ["position_encoding"]:
            # ImageInputAdapter's Fourier-encoding BUFFER (adapter.py:51) —
            # deterministic, recomputed at trace time here; not a parameter
            return None
        raise KeyError(f"unexpected input_adapter leaf {sub!r}")
    if head == "latent":
        return ["latent"], False, False
    if head in ("layer_1", "layer_n"):
        # perceiver layer = Sequential(cross_attention_layer,
        # self_attention_block) (model.py:150-160)
        idx, sub = rest[1], rest[2:]
        if idx == "0":
            path, transpose, is_mha = _translate_attn_layer(sub, "cross")
            return [head, "cross_attention_layer"] + path, transpose, is_mha
        if idx == "1":
            layer_i, layer_rest = sub[0], sub[1:]
            path, transpose, is_mha = _translate_attn_layer(layer_rest, "self")
            return (
                [head, "self_attention_block", f"layer_{int(layer_i)}"] + path,
                transpose,
                is_mha,
            )
        raise KeyError(f"unexpected perceiver-layer child {rest!r}")
    raise KeyError(f"unexpected encoder key {'.'.join(rest)!r}")


def _translate_decoder(rest: List[str]) -> Optional[Tuple[List[str], bool, bool]]:
    head = rest[0]
    if head == "output":
        return ["output"], False, False
    if head == "cross_attention":
        path, transpose, is_mha = _translate_attn_layer(rest[1:], "cross")
        return ["cross_attention_layer"] + path, transpose, is_mha
    if head == "output_adapter":
        if rest[1] != "linear":
            raise KeyError(f"unexpected output_adapter leaf {rest[1:]!r}")
        path, transpose = _translate_linear(rest[2:], "linear")
        return ["output_adapter"] + path, transpose, False
    raise KeyError(f"unexpected decoder key {'.'.join(rest)!r}")


# -- MHA merge/split ----------------------------------------------------------


def _finalize_mha(group: Dict[str, np.ndarray], where: str) -> Dict[str, Any]:
    """torch nn.MultiheadAttention tensors → split q/k/v/out params.

    Merged layout (kdim == vdim == embed_dim): ``in_proj_weight`` rows stack
    q, k, v; separate layout otherwise (``{q,k,v}_proj_weight``). Bias is
    always the stacked ``in_proj_bias``.
    """
    out_w = group.get("out_proj.weight")
    if out_w is None:
        raise ValueError(f"attention at {where} missing out_proj.weight")
    e = out_w.shape[0]
    if "in_proj_weight" in group:
        w = group["in_proj_weight"]
        qw, kw, vw = w[:e], w[e:2 * e], w[2 * e:]
    else:
        qw, kw, vw = (
            group["q_proj_weight"], group["k_proj_weight"], group["v_proj_weight"]
        )
    bias = group.get("in_proj_bias")
    if bias is None:
        # the reference always builds nn.MultiheadAttention with bias=True,
        # so this is a malformed/foreign checkpoint — name the path instead
        # of dying on a bare KeyError
        raise ValueError(
            f"attention at {where} missing in_proj_bias (bias=False "
            f"checkpoints are not the reference layout)"
        )
    return {
        "q_proj": {"kernel": qw.T.copy(), "bias": bias[:e].copy()},
        "k_proj": {"kernel": kw.T.copy(), "bias": bias[e:2 * e].copy()},
        "v_proj": {"kernel": vw.T.copy(), "bias": bias[2 * e:].copy()},
        "out_proj": {"kernel": out_w.T.copy(), "bias": group["out_proj.bias"].copy()},
    }


# -- public API ---------------------------------------------------------------

_SKIPPED_KEY_RE = re.compile(
    # torchmetrics Accuracy state, CrossEntropyLoss buffers, masking counters —
    # training bookkeeping with no equivalent in a params pytree
    r"^(loss\.|acc\.|masking\.)"
)


def convert_state_dict(state_dict: Mapping[str, Any]) -> Dict[str, Any]:
    """Reference torch ``state_dict`` → flax params pytree.

    Accepts the state_dict of a Lightning module (``model.…`` prefix), a bare
    ``PerceiverMLM`` (``encoder.…``/``decoder.…``), a bare ``PerceiverIO``
    (Sequential: ``0.…``/``1.…``), or a bare ``PerceiverEncoder`` (keys start
    at ``input_adapter``/``latent``/``layer_…`` — returned under an
    ``encoder`` root).
    """
    params: Dict[str, Any] = {}
    mha_groups: Dict[Tuple[str, ...], Dict[str, np.ndarray]] = {}

    for key, value in state_dict.items():
        parts = key.split(".")
        if parts[0] == "model":
            parts = parts[1:]
        if _SKIPPED_KEY_RE.match(".".join(parts)):
            continue
        if parts[0] in ("encoder", "0"):
            root, rest = "encoder", parts[1:]
            translated = _translate_encoder(rest)
        elif parts[0] in ("decoder", "1"):
            root, rest = "decoder", parts[1:]
            translated = _translate_decoder(rest)
        elif parts[0] in ("input_adapter", "latent", "layer_1", "layer_n"):
            root = "encoder"
            translated = _translate_encoder(parts)
        else:
            raise KeyError(f"unrecognized checkpoint key {key!r}")
        if translated is None:  # deterministic buffer — recomputed, not stored
            continue
        path, transpose, is_mha = translated
        arr = _np(value)
        if is_mha:
            *prefix, torch_name = path
            mha_groups.setdefault(tuple([root] + prefix), {})[torch_name] = arr
        else:
            _assign(params, [root] + path, arr.T.copy() if transpose else arr)

    for prefix, group in mha_groups.items():
        _assign(params, list(prefix), _finalize_mha(group, "/".join(prefix)))
    return params


def load_lightning_checkpoint(
    path: str, allow_unsafe_pickle: bool = False
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Read a Lightning ``.ckpt`` (a torch pickle) → (state_dict, hparams).

    torch is only needed here, at the import boundary — never on the device
    path.

    Loads with ``weights_only=True`` first: these files are third-party
    artifacts, and an unrestricted pickle executes arbitrary code at load
    time. Lightning checkpoints store an ``argparse.Namespace`` in
    ``hyper_parameters``, which the safe loader admits via
    ``add_safe_globals``. Only when a checkpoint needs classes outside that
    allowlist does ``allow_unsafe_pickle=True`` (an explicit caller opt-in,
    surfaced as ``--unsafe_load`` on the import CLI) fall back to the
    unrestricted loader, with a warning.
    """
    import argparse as _argparse

    import torch

    import pickle

    try:
        with torch.serialization.safe_globals([_argparse.Namespace]):
            ckpt = torch.load(path, map_location="cpu", weights_only=True)
    # Only unpickling failures get the --unsafe_load advice/fallback: a
    # missing file (OSError) or corrupted archive (torch RuntimeError)
    # fails identically under the unrestricted loader, and advising users
    # to disable a security control for those would teach the wrong habit.
    except pickle.UnpicklingError as e:
        if not allow_unsafe_pickle:
            raise ValueError(
                f"checkpoint {path!r} does not load under torch's safe "
                f"weights-only unpickler ({type(e).__name__}: {e}); if you "
                f"trust its origin, retry with allow_unsafe_pickle=True "
                f"(CLI: --unsafe_load)"
            ) from e
        import warnings

        warnings.warn(
            f"loading {path!r} with the unrestricted pickle loader — this "
            f"executes code embedded in the file; only do this for artifacts "
            f"you trust",
            stacklevel=2,
        )
        ckpt = torch.load(path, map_location="cpu", weights_only=False)
    if "state_dict" not in ckpt:  # a bare state_dict file also works
        return ckpt, {}
    hparams = ckpt.get("hyper_parameters", {}) or {}
    if not isinstance(hparams, dict):  # Lightning may store an argparse Namespace
        hparams = dict(vars(hparams))
    return ckpt["state_dict"], hparams


_HPARAM_RENAMES = {
    # reference argparse names (lightning.py:26-40) → this framework's
    # (cli/common.py MODEL_HPARAM_KEYS)
    "num_encoder_cross_attention_heads": "num_cross_attention_heads",
    "num_encoder_self_attention_heads": "num_self_attention_heads",
    "num_encoder_self_attention_layers_per_block":
        "num_self_attention_layers_per_block",
}


def convert_hparams(hparams: Mapping[str, Any]) -> Dict[str, Any]:
    """Reference Lightning hparams → this framework's arg names (shape knobs
    pass through; encoder-prefixed head counts are renamed)."""
    out: Dict[str, Any] = {}
    for key, value in hparams.items():
        out[_HPARAM_RENAMES.get(key, key)] = value
    return out


def import_lightning_checkpoint(
    path: str, encoder_only: bool = False, allow_unsafe_pickle: bool = False
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Lightning ``.ckpt`` → (flax params pytree, converted hparams).

    ``encoder_only=True`` returns just the ``encoder`` subtree — the transfer
    entry (reference ``train_seq_clf.py:18-24`` moves the pretrained MLM
    encoder into a fresh classifier). ``allow_unsafe_pickle``: see
    :func:`load_lightning_checkpoint`.
    """
    state_dict, hparams = load_lightning_checkpoint(
        path, allow_unsafe_pickle=allow_unsafe_pickle
    )
    params = convert_state_dict(state_dict)
    if encoder_only:
        params = {"encoder": params["encoder"]}
    return params, convert_hparams(hparams)


# -- reverse interop: flax params → reference torch checkpoint ---------------


def _emit_linear(out: Dict[str, np.ndarray], dense: Mapping[str, Any],
                 prefix: str) -> None:
    out[f"{prefix}.weight"] = _np(dense["kernel"]).T.copy()
    out[f"{prefix}.bias"] = _np(dense["bias"]).copy()


def _emit_ln(out: Dict[str, np.ndarray], ln: Mapping[str, Any],
             prefix: str) -> None:
    out[f"{prefix}.weight"] = _np(ln["scale"]).copy()
    out[f"{prefix}.bias"] = _np(ln["bias"]).copy()


def _emit_mlp(out: Dict[str, np.ndarray], mlp: Mapping[str, Any],
              prefix: str) -> None:
    # Sequential(LN, Linear, GELU, Linear) → positional 0/1/3 (model.py:20-26)
    _emit_ln(out, mlp["norm"], f"{prefix}.0")
    _emit_linear(out, mlp["dense_1"], f"{prefix}.1")
    _emit_linear(out, mlp["dense_2"], f"{prefix}.3")


def _emit_mha(out: Dict[str, np.ndarray], attn: Mapping[str, Any],
              prefix: str) -> None:
    """Split q/k/v/out params → torch ``nn.MultiheadAttention`` tensors.

    torch stores the MERGED ``in_proj_weight`` iff kdim == vdim == embed_dim
    (the layout ``_finalize_mha`` splits on import) and the separate
    ``{q,k,v}_proj_weight`` otherwise; the bias is always the stacked
    ``in_proj_bias``. flax kernels are (in, out) → torch weights (out, in).
    """
    qw = _np(attn["q_proj"]["kernel"]).T
    kw = _np(attn["k_proj"]["kernel"]).T
    vw = _np(attn["v_proj"]["kernel"]).T
    if qw.shape == kw.shape == vw.shape:
        out[f"{prefix}.in_proj_weight"] = np.concatenate([qw, kw, vw], axis=0).copy()
    else:
        out[f"{prefix}.q_proj_weight"] = qw.copy()
        out[f"{prefix}.k_proj_weight"] = kw.copy()
        out[f"{prefix}.v_proj_weight"] = vw.copy()
    out[f"{prefix}.in_proj_bias"] = np.concatenate([
        _np(attn["q_proj"]["bias"]),
        _np(attn["k_proj"]["bias"]),
        _np(attn["v_proj"]["bias"]),
    ]).copy()
    _emit_linear(out, attn["out_proj"], f"{prefix}.out_proj")


def _emit_attn_layer(out: Dict[str, np.ndarray], layer: Mapping[str, Any],
                     prefix: str, kind: str) -> None:
    """cross/self_attention_layer → Sequential(Residual(attn), Residual(mlp))
    = ``{prefix}.0.module`` / ``{prefix}.1.module`` (model.py:29-44)."""
    name = "cross_attention" if kind == "cross" else "self_attention"
    mod = layer[name]
    body = f"{prefix}.0.module"
    for norm in ("q_norm", "kv_norm", "norm"):
        if norm in mod:
            _emit_ln(out, mod[norm], f"{body}.{norm}")
    _emit_mha(out, mod["attention"], f"{body}.attention.attention")
    _emit_mlp(out, layer["mlp"], f"{prefix}.1.module")


def _emit_encoder(out: Dict[str, np.ndarray], enc: Mapping[str, Any],
                  root: str) -> None:
    adapter = enc.get("input_adapter", {})
    known = {"text_embedding", "pos_encoding"}
    if set(adapter) != known:
        # image models land here too: the flax ImageInputAdapter holds NO
        # params (its Fourier encoding is a deterministic buffer), so their
        # encoder tree has no input_adapter subtree at all
        raise ValueError(
            f"export supports the reference's TEXT models (input_adapter "
            f"with text_embedding + pos_encoding params); this encoder's "
            f"input_adapter params are {sorted(adapter) or '{}'} — image "
            f"adapters carry only a deterministic Fourier buffer in the "
            f"reference, so there is nothing to export for them"
        )
    out[f"{root}.input_adapter.text_embedding.weight"] = _np(
        adapter["text_embedding"]["embedding"]).copy()
    out[f"{root}.input_adapter.pos_encoding"] = _np(
        adapter["pos_encoding"]).copy()
    out[f"{root}.latent"] = _np(enc["latent"]).copy()
    for head in ("layer_1", "layer_n"):
        if head not in enc:
            continue  # num_layers == 1 has no shared layer_n
        layer = enc[head]
        _emit_attn_layer(out, layer["cross_attention_layer"],
                         f"{root}.{head}.0", "cross")
        block = layer["self_attention_block"]
        for i in range(len(block)):
            _emit_attn_layer(out, block[f"layer_{i}"],
                             f"{root}.{head}.1.{i}", "self")


def _emit_decoder(out: Dict[str, np.ndarray], dec: Mapping[str, Any],
                  root: str) -> None:
    out[f"{root}.output"] = _np(dec["output"]).copy()
    _emit_attn_layer(out, dec["cross_attention_layer"],
                     f"{root}.cross_attention", "cross")
    _emit_linear(out, dec["output_adapter"]["linear"],
                 f"{root}.output_adapter.linear")


def export_state_dict(
    params: Mapping[str, Any],
    layout: str = "mlm",
    lightning_prefix: bool = True,
) -> Dict[str, np.ndarray]:
    """flax params pytree → reference torch ``state_dict`` (the inverse of
    :func:`convert_state_dict`, for moving checkpoints BACK to the reference).

    ``layout``: ``'mlm'`` emits the ``PerceiverMLM`` named-child keys
    (``encoder.…``/``decoder.…``, reference ``model.py:296-303``);
    ``'classifier'`` emits the ``PerceiverIO`` Sequential's positional keys
    (``0.…``/``1.…``, ``model.py:321-325``). ``lightning_prefix`` adds the
    ``model.`` prefix Lightning modules carry (``lightning.py:87,183``).
    Round-trip exactness (``convert_state_dict(export_state_dict(p)) == p``)
    and strict ``load_state_dict`` into reference-shaped torch modules are
    pinned by ``tests/test_interop.py``.
    """
    if layout not in ("mlm", "classifier"):
        raise ValueError(f"layout must be 'mlm' or 'classifier', got {layout!r}")
    enc_root, dec_root = (
        ("encoder", "decoder") if layout == "mlm" else ("0", "1")
    )
    out: Dict[str, np.ndarray] = {}
    _emit_encoder(out, params["encoder"], enc_root)
    _emit_decoder(out, params["decoder"], dec_root)
    if lightning_prefix:
        out = {f"model.{k}": v for k, v in out.items()}
    return out


_HPARAM_RENAMES_BACK = {v: k for k, v in _HPARAM_RENAMES.items()}


def export_lightning_checkpoint(
    params: Mapping[str, Any],
    path: str,
    hparams: Optional[Mapping[str, Any]] = None,
    layout: str = "mlm",
    epoch: int = 0,
    global_step: int = 0,
) -> None:
    """Write ``params`` as a Lightning-style ``.ckpt`` the REFERENCE can load
    (``LitMLM.load_from_checkpoint`` / ``--mlm_checkpoint`` over there): a
    torch pickle with ``state_dict`` (``model.``-prefixed), Lightning's
    ``hyper_parameters`` (arg names renamed back to the reference's
    encoder-prefixed spellings), and the epoch/step envelope. The file loads
    under torch's safe ``weights_only=True`` unpickler — plain tensors and a
    plain dict, no embedded code.
    """
    import torch

    state_dict = {
        k: torch.from_numpy(np.ascontiguousarray(v))
        for k, v in export_state_dict(params, layout=layout).items()
    }
    hp = {
        _HPARAM_RENAMES_BACK.get(k, k): v
        for k, v in (hparams or {}).items()
        if _is_jsonable(v)
    }
    torch.save(
        {
            "state_dict": state_dict,
            "hyper_parameters": hp,
            "epoch": int(epoch),
            "global_step": int(global_step),
            # PL >= 1.8's load_from_checkpoint runs checkpoint migration,
            # which indexes this key before touching the state_dict — real
            # Lightning files always carry it
            "pytorch-lightning_version": "1.5.0",
        },
        path,
    )


def _is_jsonable(v) -> bool:
    try:
        json.dumps(v)
        return True
    except (TypeError, ValueError):
        return False


def export_orbax_checkpoint(
    params: Dict[str, Any],
    directory: str,
    hparams: Optional[Dict[str, Any]] = None,
) -> None:
    """Write ``params`` as an Orbax checkpoint directory in this framework's
    run layout, so ``--mlm_checkpoint DIR`` / ``--clf_checkpoint DIR`` /
    ``restore_params(DIR, …)`` consume an imported reference checkpoint
    exactly like a native one.

    Only the params subtree is stored (an imported torch checkpoint has no
    compatible optimizer state); every restore path in
    ``training/checkpoint.py`` does a partial pytree restore, so that is
    sufficient for transfer and inference.
    """
    import orbax.checkpoint as ocp

    from perceiver_io_tpu.training.checkpoint import HPARAMS_FILE

    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    with ocp.CheckpointManager(
        directory, options=ocp.CheckpointManagerOptions(max_to_keep=1)
    ) as mngr:
        mngr.save(
            0, args=ocp.args.Composite(state=ocp.args.StandardSave({"params": params}))
        )
        mngr.wait_until_finished()
    if hparams is not None:
        with open(os.path.join(directory, HPARAMS_FILE), "w") as f:
            json.dump(hparams, f, indent=2, sort_keys=True, default=str)
