"""perceiver_io_tpu — a TPU-native (JAX/XLA/Pallas/pjit) Perceiver IO framework.

A from-scratch rebuild of the capabilities of the reference PyTorch/Lightning
implementation (DartingMelody/perceiver-io): generic Perceiver encoder/decoder
core with injected modality adapters, MLM pretraining, encoder transfer, and
image classification — designed TPU-first:

- pure-functional flax.linen modules jitted end-to-end,
- SPMD over a `jax.sharding.Mesh` (data/model/sequence axes) instead of DDP,
- a fused Pallas latent-attention kernel on the hot path,
- host-side data/tokenizer pipeline feeding device prefetch.

Public API mirrors the reference package surface (reference
`perceiver/__init__.py:1-13`).
"""

import jax as _jax

# Sharding-invariant PRNG (the modern jax default; this build ships it off):
# the same key must draw the same bits whether a step runs replicated or
# pjit-sharded — the checkpoint round-trip "restored replicated state
# continues IDENTICALLY to the live sharded run" guarantee, and the basis of
# the multi-host lockstep claims, both depend on it.
_jax.config.update("jax_threefry_partitionable", True)

from perceiver_io_tpu.models.adapters import (
    InputAdapter,
    OutputAdapter,
    ImageInputAdapter,
    TextInputAdapter,
    ClassificationOutputAdapter,
    TextOutputAdapter,
)
from perceiver_io_tpu.models.flow import (
    DenseSpatialOutputAdapter,
    OpticalFlowInputAdapter,
    build_optical_flow_model,
)
from perceiver_io_tpu.models.multimodal import (
    AudioInputAdapter,
    AudioOutputAdapter,
    MultimodalInputAdapter,
    MultimodalOutputAdapter,
    VideoInputAdapter,
    VideoOutputAdapter,
    build_multimodal_autoencoder,
)
from perceiver_io_tpu.models.perceiver import (
    PerceiverARLM,
    PerceiverEncoder,
    PerceiverDecoder,
    PerceiverIO,
    PerceiverMLM,
)
from perceiver_io_tpu.inference import (
    MLMPredictor,
    Predictor,
    export_forward,
    load_exported,
)
from perceiver_io_tpu.ops.masking import TextMasking

__version__ = "0.1.0"

__all__ = [
    "DenseSpatialOutputAdapter",
    "OpticalFlowInputAdapter",
    "build_optical_flow_model",
    "AudioInputAdapter",
    "AudioOutputAdapter",
    "MultimodalInputAdapter",
    "MultimodalOutputAdapter",
    "VideoInputAdapter",
    "VideoOutputAdapter",
    "build_multimodal_autoencoder",
    "InputAdapter",
    "OutputAdapter",
    "ImageInputAdapter",
    "TextInputAdapter",
    "ClassificationOutputAdapter",
    "TextOutputAdapter",
    "PerceiverEncoder",
    "PerceiverDecoder",
    "PerceiverIO",
    "PerceiverARLM",
    "PerceiverMLM",
    "TextMasking",
    "MLMPredictor",
    "Predictor",
    "export_forward",
    "load_exported",
]
