from perceiver_io_tpu.models.adapters import (
    InputAdapter,
    OutputAdapter,
    ImageInputAdapter,
    TextInputAdapter,
    ClassificationOutputAdapter,
    TextOutputAdapter,
)
from perceiver_io_tpu.models.flow import (
    DenseSpatialOutputAdapter,
    OpticalFlowInputAdapter,
    build_optical_flow_model,
    end_point_error,
)
from perceiver_io_tpu.models.multimodal import (
    AudioInputAdapter,
    AudioOutputAdapter,
    MultimodalInputAdapter,
    MultimodalOutputAdapter,
    VideoInputAdapter,
    VideoOutputAdapter,
    build_multimodal_autoencoder,
    multimodal_autoencoding_loss,
)
from perceiver_io_tpu.models.perceiver import (
    PerceiverEncoder,
    PerceiverDecoder,
    PerceiverIO,
    PerceiverMLM,
)

__all__ = [
    "AudioInputAdapter",
    "AudioOutputAdapter",
    "MultimodalInputAdapter",
    "MultimodalOutputAdapter",
    "VideoInputAdapter",
    "VideoOutputAdapter",
    "build_multimodal_autoencoder",
    "multimodal_autoencoding_loss",
    "DenseSpatialOutputAdapter",
    "OpticalFlowInputAdapter",
    "build_optical_flow_model",
    "end_point_error",
    "InputAdapter",
    "OutputAdapter",
    "ImageInputAdapter",
    "TextInputAdapter",
    "ClassificationOutputAdapter",
    "TextOutputAdapter",
    "PerceiverEncoder",
    "PerceiverDecoder",
    "PerceiverIO",
    "PerceiverMLM",
]
