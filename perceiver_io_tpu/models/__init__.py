from perceiver_io_tpu.models.adapters import (
    InputAdapter,
    OutputAdapter,
    ImageInputAdapter,
    TextInputAdapter,
    ClassificationOutputAdapter,
    TextOutputAdapter,
)
from perceiver_io_tpu.models.perceiver import (
    PerceiverEncoder,
    PerceiverDecoder,
    PerceiverIO,
    PerceiverMLM,
)

__all__ = [
    "InputAdapter",
    "OutputAdapter",
    "ImageInputAdapter",
    "TextInputAdapter",
    "ClassificationOutputAdapter",
    "TextOutputAdapter",
    "PerceiverEncoder",
    "PerceiverDecoder",
    "PerceiverIO",
    "PerceiverMLM",
]
