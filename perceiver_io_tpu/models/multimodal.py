"""Audio/video/label multimodal adapters and the Kinetics-style autoencoder.

The reference repo implements neither audio nor video (its adapters stop at
text and images, ``perceiver/adapter.py``); these cover the Perceiver IO
paper's multimodal autoencoding task and are the second proof (after
``models/flow.py``) that the injected-adapter contract (reference
``perceiver/adapter.py:9-32``) generalizes: the encoder/decoder core is reused
unchanged.

Input side:

- ``AudioInputAdapter``: raw waveform (B, T, C_a) grouped into patches of
  ``samples_per_patch`` consecutive samples per token + 1D Fourier encodings.
- ``VideoInputAdapter``: (B, T, H, W, C) cut into space-time patches
  (reshape/transpose only — XLA folds this into a copy) + 3D Fourier
  encodings over the patch grid.
- ``MultimodalInputAdapter``: composes named sub-adapters into ONE token
  stream: each modality's channels are padded to a common width with a
  *trainable* padding vector and tagged with a learned modality embedding
  (the paper's modality-alignment scheme), then token streams are
  concatenated along the M axis. The Perceiver encoder is modality-blind —
  one cross-attention reads the fused stream.

Output side (the decoder's learned query array spans all modalities; rows are
split back out per modality — learning free per-query vectors subsumes the
paper's query = position-encoding + modality-embedding construction):

- ``AudioOutputAdapter`` / ``VideoOutputAdapter``: linear head per decoder
  query to one patch of samples/pixels, un-patchified to the original shape.
- ``MultimodalOutputAdapter``: routes contiguous query-row spans to named
  sub-adapters and returns a dict of per-modality outputs.

``build_multimodal_autoencoder`` assembles video+audio → latent →
video+audio+label: reconstruction of both modalities plus classification from
one extra query (multi-task, as in the paper's Kinetics-700 experiment).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from perceiver_io_tpu.models.adapters import (
    ClassificationOutputAdapter,
    InputAdapter,
    OutputAdapter,
)
from perceiver_io_tpu.ops.attention import (
    _LinearParams,
    torch_linear_bias_init,
    torch_linear_kernel_init,
)
from perceiver_io_tpu.ops.pallas_matmul import linear_apply
from perceiver_io_tpu.ops.fourier import (
    fourier_position_encodings,
    num_position_encoding_channels,
    spatial_positions,
)

Array = jax.Array


def _check_divisible(size: int, patch: int, what: str) -> int:
    if size % patch != 0:
        raise ValueError(f"{what}: size {size} not divisible by patch {patch}")
    return size // patch


class AudioInputAdapter(InputAdapter):
    """Waveform (B, num_samples, C_a) → (B, num_samples/p, p·C_a + pos).

    One token per patch of ``samples_per_patch`` consecutive samples, plus 1D
    Fourier position encodings over patch positions (the audio featurization
    of the Perceiver IO paper's multimodal experiments).
    """

    num_samples: int = 48000
    samples_per_patch: int = 16
    num_audio_channels: int = 1
    num_frequency_bands: int = 64
    dtype: jnp.dtype = jnp.float32

    @property
    def num_tokens(self) -> int:
        return _check_divisible(self.num_samples, self.samples_per_patch, "audio")

    @property
    def num_input_channels(self) -> int:
        return (
            self.samples_per_patch * self.num_audio_channels
            + num_position_encoding_channels(1, self.num_frequency_bands)
        )

    @nn.compact
    def __call__(self, x: Array) -> Array:
        b, *rest = x.shape
        if tuple(rest) != (self.num_samples, self.num_audio_channels):
            raise ValueError(
                f"Input audio shape {tuple(rest)} != required "
                f"({self.num_samples}, {self.num_audio_channels})"
            )
        m = self.num_tokens
        x = x.reshape(b, m, self.samples_per_patch * self.num_audio_channels)

        pos = spatial_positions((m,))
        enc = fourier_position_encodings(pos, self.num_frequency_bands)
        enc = jnp.broadcast_to(enc.astype(self.dtype), (b, *enc.shape))
        return jnp.concatenate([x.astype(self.dtype), enc], axis=-1)


class VideoInputAdapter(InputAdapter):
    """Video (B, T, H, W, C) → (B, grid_size, patch_voxels·C + pos).

    Space-time patches of ``patch_shape = (pt, ph, pw)`` voxels; 3D Fourier
    encodings over the (T/pt, H/ph, W/pw) patch grid. Pure reshape/transpose —
    no convolution — so XLA lowers it to a single relayout feeding the
    encoder's cross-attention KV projection.
    """

    video_shape: Tuple[int, int, int, int] = (16, 224, 224, 3)  # (T, H, W, C)
    patch_shape: Tuple[int, int, int] = (1, 4, 4)
    num_frequency_bands: int = 32
    dtype: jnp.dtype = jnp.float32

    @property
    def grid_shape(self) -> Tuple[int, int, int]:
        t, h, w, _ = self.video_shape
        pt, ph, pw = self.patch_shape
        return (
            _check_divisible(t, pt, "video time"),
            _check_divisible(h, ph, "video height"),
            _check_divisible(w, pw, "video width"),
        )

    @property
    def num_tokens(self) -> int:
        return math.prod(self.grid_shape)

    @property
    def num_patch_channels(self) -> int:
        return math.prod(self.patch_shape) * self.video_shape[-1]

    @property
    def num_input_channels(self) -> int:
        return self.num_patch_channels + num_position_encoding_channels(
            3, self.num_frequency_bands
        )

    @nn.compact
    def __call__(self, x: Array) -> Array:
        b, *rest = x.shape
        if tuple(rest) != tuple(self.video_shape):
            raise ValueError(
                f"Input video shape {tuple(rest)} != required {self.video_shape}"
            )
        (gt, gh, gw), (pt, ph, pw) = self.grid_shape, self.patch_shape
        c = self.video_shape[-1]
        x = x.reshape(b, gt, pt, gh, ph, gw, pw, c)
        x = x.transpose(0, 1, 3, 5, 2, 4, 6, 7)
        x = x.reshape(b, self.num_tokens, self.num_patch_channels)

        pos = spatial_positions(self.grid_shape)
        enc = fourier_position_encodings(pos, self.num_frequency_bands)
        enc = enc.reshape(self.num_tokens, -1)
        enc = jnp.broadcast_to(enc.astype(self.dtype), (b, *enc.shape))
        return jnp.concatenate([x.astype(self.dtype), enc], axis=-1)


class MultimodalInputAdapter(InputAdapter):
    """Fuse named sub-adapters into one (B, ΣM_i, common + E) token stream.

    Per modality: channels are right-padded from C_i to ``max_i C_i`` with a
    trainable padding vector, then a learned modality embedding of
    ``num_modality_channels`` is appended — so the encoder can tell modalities
    apart while staying modality-blind structurally. ``adapters`` is a
    sequence of (name, InputAdapter) pairs; order fixes the token layout.
    """

    adapters: Sequence[Tuple[str, InputAdapter]] = ()
    num_modality_channels: int = 8
    dtype: jnp.dtype = jnp.float32

    @property
    def common_channels(self) -> int:
        return max(a.num_input_channels for _, a in self.adapters)

    @property
    def num_input_channels(self) -> int:
        return self.common_channels + self.num_modality_channels

    @property
    def num_tokens(self) -> int:
        return sum(a.num_tokens for _, a in self.adapters)

    @nn.compact
    def __call__(self, x: dict) -> Array:
        if not self.adapters:
            raise ValueError("MultimodalInputAdapter needs at least one adapter")
        common = self.common_channels
        streams = []
        for name, adapter in self.adapters:
            tokens = adapter(x[name])  # (B, M_i, C_i)
            b, m, c = tokens.shape
            parts = [tokens]
            if c < common:
                pad = self.param(
                    f"{name}_padding",
                    nn.initializers.truncated_normal(0.02),
                    (common - c,),
                )
                parts.append(
                    jnp.broadcast_to(pad.astype(self.dtype), (b, m, common - c))
                )
            if self.num_modality_channels:
                emb = self.param(
                    f"{name}_modality",
                    nn.initializers.truncated_normal(0.02),
                    (self.num_modality_channels,),
                )
                parts.append(
                    jnp.broadcast_to(
                        emb.astype(self.dtype), (b, m, self.num_modality_channels)
                    )
                )
            streams.append(jnp.concatenate(parts, axis=-1))
        return jnp.concatenate(streams, axis=1)


class AudioOutputAdapter(OutputAdapter):
    """One decoder query per audio patch; linear head back to raw samples."""

    num_samples: int = 48000
    samples_per_patch: int = 16
    num_audio_channels: int = 1
    num_output_channels: int = 512
    dtype: jnp.dtype = jnp.float32

    @property
    def output_shape(self) -> Tuple[int, int]:
        return (
            _check_divisible(self.num_samples, self.samples_per_patch, "audio"),
            self.num_output_channels,
        )

    @nn.compact
    def __call__(self, x: Array) -> Array:
        b = x.shape[0]
        w, bias = _LinearParams(
            x.shape[-1], self.samples_per_patch * self.num_audio_channels,
            kernel_init=torch_linear_kernel_init,
            bias_init=torch_linear_bias_init(self.num_output_channels),
            name="linear")()
        x = linear_apply(x, w, bias, self.dtype)
        return x.reshape(b, self.num_samples, self.num_audio_channels)


class VideoOutputAdapter(OutputAdapter):
    """One decoder query per space-time patch; linear head to patch voxels,
    un-patchified back to (B, T, H, W, C).

    ``as_patches=True`` skips the un-patchify (returns the raw
    (B, N_patches, pt·ph·pw·C) head output): the training loss is an
    elementwise MSE, so it can run in patch space against a patchified
    target — the same element set, so the loss value agrees to fp
    reassociation — and the (B, T, H, W, C) transpose pair (forward +
    cotangent) never materializes. Params are identical either way; a
    checkpoint moves freely between the two."""

    video_shape: Tuple[int, int, int, int] = (16, 224, 224, 3)
    patch_shape: Tuple[int, int, int] = (1, 4, 4)
    num_output_channels: int = 512
    dtype: jnp.dtype = jnp.float32
    as_patches: bool = False

    @property
    def grid_shape(self) -> Tuple[int, int, int]:
        t, h, w, _ = self.video_shape
        pt, ph, pw = self.patch_shape
        return (
            _check_divisible(t, pt, "video time"),
            _check_divisible(h, ph, "video height"),
            _check_divisible(w, pw, "video width"),
        )

    @property
    def output_shape(self) -> Tuple[int, int]:
        return (math.prod(self.grid_shape), self.num_output_channels)

    @nn.compact
    def __call__(self, x: Array) -> Array:
        b = x.shape[0]
        (gt, gh, gw), (pt, ph, pw) = self.grid_shape, self.patch_shape
        c = self.video_shape[-1]
        w, bias = _LinearParams(
            x.shape[-1], math.prod(self.patch_shape) * c,
            kernel_init=torch_linear_kernel_init,
            bias_init=torch_linear_bias_init(self.num_output_channels),
            name="linear")()
        x = linear_apply(x, w, bias, self.dtype)
        if self.as_patches:
            return x  # (B, N_patches, pt·ph·pw·C)
        x = x.reshape(b, gt, gh, gw, pt, ph, pw, c)
        x = x.transpose(0, 1, 4, 2, 5, 3, 6, 7)
        return x.reshape(b, *self.video_shape)


def patchify_video(target: Array, grid_shape, patch_shape) -> Array:
    """(B, T, H, W, C) → (B, N_patches, pt·ph·pw·C), the exact inverse of
    ``VideoOutputAdapter``'s un-patchify — for patch-space reconstruction
    losses against an ``as_patches=True`` adapter output."""
    b = target.shape[0]
    (gt, gh, gw), (pt, ph, pw) = grid_shape, patch_shape
    c = target.shape[-1]
    x = target.reshape(b, gt, pt, gh, ph, gw, pw, c)
    x = x.transpose(0, 1, 3, 5, 2, 4, 6, 7)
    return x.reshape(b, gt * gh * gw, pt * ph * pw * c)


class MultimodalOutputAdapter(OutputAdapter):
    """Route contiguous decoder-query spans to named sub-adapters.

    ``output_shape = (Σ K_i, C)``; every sub-adapter must produce queries of
    the same channel width C. Returns ``{name: sub_adapter(rows_i)}``.
    """

    adapters: Sequence[Tuple[str, OutputAdapter]] = ()

    @property
    def output_shape(self) -> Tuple[int, int]:
        if not self.adapters:
            raise ValueError("MultimodalOutputAdapter needs at least one adapter")
        shapes = [a.output_shape for _, a in self.adapters]
        widths = {s[1] for s in shapes}
        if len(widths) != 1:
            raise ValueError(
                "all sub-adapters must share one query channel width, got "
                + ", ".join(f"{n}:{s[1]}" for (n, _), s in zip(self.adapters, shapes))
            )
        return (sum(s[0] for s in shapes), widths.pop())

    def __call__(self, x: Array) -> dict:
        out = {}
        start = 0
        for name, adapter in self.adapters:
            k = adapter.output_shape[0]
            out[name] = adapter(x[:, start : start + k, :])
            start += k
        return out


def build_multimodal_autoencoder(
    video_shape: Tuple[int, int, int, int] = (16, 224, 224, 3),
    num_audio_samples: int = 30720,
    samples_per_patch: int = 16,
    num_audio_channels: int = 1,
    num_classes: int = 700,
    latent_shape: Tuple[int, int] = (784, 512),
    video_patch_shape: Tuple[int, int, int] = (1, 4, 4),
    num_layers: int = 1,
    num_self_attention_layers_per_block: int = 8,
    num_cross_attention_heads: int = 1,
    num_self_attention_heads: int = 8,
    num_modality_channels: int = 8,
    video_frequency_bands: int = 32,
    audio_frequency_bands: int = 64,
    dropout: float = 0.0,
    dtype: jnp.dtype = jnp.float32,
    attn_impl: str = "auto",
    remat: bool = False,
    reuse_kv: bool = True,
    video_patch_loss: bool = False,
):
    """PerceiverIO mapping {'video', 'audio'} → {'video', 'audio', 'label'}
    (Kinetics-style multimodal autoencoding + classification; defaults sized
    after the Perceiver IO paper's configuration — shrink everything for
    tests).

    ``video_patch_loss=True`` keeps the video head in patch space
    (``VideoOutputAdapter.as_patches``) for elementwise-loss training —
    exact up to fp reassociation, skips the (B, T, H, W, C) un-patchify
    transpose pair; ``make_multimodal_steps`` patchifies the target to
    match. Params are unaffected — checkpoints move freely."""
    from perceiver_io_tpu.models.perceiver import (
        PerceiverDecoder,
        PerceiverEncoder,
        PerceiverIO,
    )

    c_latent = latent_shape[1]
    input_adapter = MultimodalInputAdapter(
        adapters=(
            (
                "video",
                VideoInputAdapter(
                    video_shape=video_shape,
                    patch_shape=video_patch_shape,
                    num_frequency_bands=video_frequency_bands,
                    dtype=dtype,
                ),
            ),
            (
                "audio",
                AudioInputAdapter(
                    num_samples=num_audio_samples,
                    samples_per_patch=samples_per_patch,
                    num_audio_channels=num_audio_channels,
                    num_frequency_bands=audio_frequency_bands,
                    dtype=dtype,
                ),
            ),
        ),
        num_modality_channels=num_modality_channels,
        dtype=dtype,
    )
    output_adapter = MultimodalOutputAdapter(
        adapters=(
            (
                "video",
                VideoOutputAdapter(
                    video_shape=video_shape,
                    patch_shape=video_patch_shape,
                    num_output_channels=c_latent,
                    dtype=dtype,
                    as_patches=video_patch_loss,
                ),
            ),
            (
                "audio",
                AudioOutputAdapter(
                    num_samples=num_audio_samples,
                    samples_per_patch=samples_per_patch,
                    num_audio_channels=num_audio_channels,
                    num_output_channels=c_latent,
                    dtype=dtype,
                ),
            ),
            (
                "label",
                ClassificationOutputAdapter(
                    num_classes=num_classes,
                    num_outputs=1,
                    num_output_channels=c_latent,
                    dtype=dtype,
                ),
            ),
        )
    )
    return PerceiverIO(
        encoder=PerceiverEncoder(
            input_adapter=input_adapter,
            latent_shape=latent_shape,
            num_layers=num_layers,
            num_cross_attention_heads=num_cross_attention_heads,
            num_self_attention_heads=num_self_attention_heads,
            num_self_attention_layers_per_block=num_self_attention_layers_per_block,
            dropout=dropout,
            dtype=dtype,
            attn_impl=attn_impl,
            remat=remat,
            reuse_kv=reuse_kv,
        ),
        decoder=PerceiverDecoder(
            output_adapter=output_adapter,
            latent_shape=latent_shape,
            num_cross_attention_heads=num_cross_attention_heads,
            dropout=dropout,
            dtype=dtype,
            attn_impl=attn_impl,
        ),
    )


def multimodal_autoencoding_loss(
    outputs: dict,
    batch: dict,
    video_weight: float = 1.0,
    audio_weight: float = 1.0,
    label_weight: float = 1.0,
    video_patch_info: Optional[Tuple[Tuple[int, int, int], Tuple[int, int, int]]] = None,
) -> Tuple[Array, dict]:
    """Weighted MSE(video) + MSE(audio) + CE(label); returns (loss, metrics).

    ``video_patch_info = (grid_shape, patch_shape)``: required when the video
    head runs in patch space (``VideoOutputAdapter.as_patches``)."""
    from perceiver_io_tpu.training.losses import classification_loss_and_accuracy

    video_target = batch["video"]
    video_pred = outputs["video"]
    if video_pred.ndim == 3 and video_target.ndim == 5:
        # patch-space head (VideoOutputAdapter.as_patches): patchify the
        # target instead of un-patchifying the prediction — the MSE sums the
        # same element set, so the loss agrees to fp reassociation while the
        # (B, T, H, W, C) transpose pair never materializes in fwd or bwd.
        # The patch geometry must come from the caller (make_multimodal_steps
        # reads it off the model's VideoOutputAdapter): it is NOT inferable
        # from shapes alone — several factorizations can match, and a wrong
        # one silently pairs predictions with the wrong target elements.
        if video_patch_info is None:
            raise ValueError(
                "patch-space video output needs video_patch_info="
                "(grid_shape, patch_shape)"
            )
        video_target = patchify_video(video_target, *video_patch_info)
    video_loss = jnp.mean(
        jnp.square(video_pred.astype(jnp.float32) - video_target)
    )
    audio_loss = jnp.mean(
        jnp.square(outputs["audio"].astype(jnp.float32) - batch["audio"])
    )
    label_loss, label_acc = classification_loss_and_accuracy(
        outputs["label"], batch["label"]
    )
    loss = (
        video_weight * video_loss
        + audio_weight * audio_loss
        + label_weight * label_loss
    )
    # PSNR over the [0, 1]-scaled video — the paper's reconstruction metric;
    # derived from the already-computed MSE, so it costs nothing extra
    video_psnr = -10.0 * jnp.log10(jnp.maximum(video_loss, 1e-10))
    metrics = {
        "video_loss": video_loss,
        "audio_loss": audio_loss,
        "label_loss": label_loss,
        "video_psnr": video_psnr,
        "acc": label_acc,
    }
    return loss, metrics
