"""Named model presets shared by the benchmarks and the driver entry points.

One definition of the flagship config so ``bench.py``,
``tools/e2e_configs_bench.py`` and ``__graft_entry__.py`` cannot drift apart
(the PERF.md table is sourced from these).
"""

from __future__ import annotations

import jax.numpy as jnp

from perceiver_io_tpu.models.adapters import TextInputAdapter, TextOutputAdapter
from perceiver_io_tpu.models.perceiver import (
    PerceiverDecoder,
    PerceiverEncoder,
    PerceiverMLM,
)
from perceiver_io_tpu.ops.masking import TextMasking


def flagship_mlm(
    vocab_size: int = 10003,
    max_seq_len: int = 512,
    num_latents: int = 256,
    num_channels: int = 64,
    num_layers: int = 3,
    num_self_attention_layers_per_block: int = 6,
    dtype: jnp.dtype = jnp.float32,
    attn_impl: str = "auto",
    remat: bool = False,
) -> PerceiverMLM:
    """The BASELINE.md north-star config: reference train_mlm shapes
    (SURVEY.md §3.1 — 512-token sequences, 256 latents, 3 encoder layers ×
    (cross-attention + 6-layer self-attention block), text in/out adapters)."""
    latent_shape = (num_latents, num_channels)
    return PerceiverMLM(
        encoder=PerceiverEncoder(
            input_adapter=TextInputAdapter(
                vocab_size=vocab_size, max_seq_len=max_seq_len,
                num_channels=num_channels, dtype=dtype,
            ),
            latent_shape=latent_shape,
            num_layers=num_layers,
            num_self_attention_layers_per_block=num_self_attention_layers_per_block,
            dtype=dtype,
            attn_impl=attn_impl,
            remat=remat,
        ),
        decoder=PerceiverDecoder(
            output_adapter=TextOutputAdapter(
                vocab_size=vocab_size, max_seq_len=max_seq_len,
                num_output_channels=num_channels, dtype=dtype,
            ),
            latent_shape=latent_shape,
            dtype=dtype,
            attn_impl=attn_impl,
        ),
        masking=TextMasking(
            vocab_size=vocab_size, unk_token_id=1, mask_token_id=2,
            num_special_tokens=3,
        ),
    )
