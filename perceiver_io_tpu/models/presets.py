"""Named model presets shared by the benchmarks and the driver entry points.

One definition of the flagship config so ``bench.py``,
``tools/e2e_configs_bench.py`` and ``__graft_entry__.py`` cannot drift apart
(the PERF.md table is sourced from these).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from perceiver_io_tpu.models.adapters import TextInputAdapter, TextOutputAdapter
from perceiver_io_tpu.models.perceiver import (
    PerceiverARLM,
    PerceiverDecoder,
    PerceiverEncoder,
    PerceiverMLM,
)
from perceiver_io_tpu.ops.masking import TextMasking


def flagship_tpu_mlm(
    vocab_size: int = 10003,
    max_seq_len: int = 512,
    num_latents: int = 256,
    num_channels: int = 512,
    num_layers: int = 3,
    num_self_attention_layers_per_block: int = 6,
    dtype: jnp.dtype = jnp.bfloat16,
    attn_impl: str = "xla",
    remat: bool = False,
    decoder_attn_impl: Optional[str] = None,
) -> PerceiverMLM:
    """The MLM recipe at TPU-native widths (BASELINE.md north-star, closed
    from the other end).

    ``attn_impl`` defaults to 'xla' rather than 'auto': the area rule would
    route the (64, 4, 256, 512, d128) encoder cross to the fused kernel,
    which wins 1.85x at KERNEL level but measures 43.16 vs 42.08 ms END TO
    END (roofline device trace, r4) — XLA overlaps the logits traffic it
    materializes, the same dilution as PERF.md negative (10b).

    Identical recipe *shape* to the reference ``train_mlm.py:93-106`` — same
    tokenizer, masking, 512-token sequences, 256 latents, 3 encoder layers x
    (cross-attention + 6-layer self-attention block), text in/out adapters —
    but with the channel width raised from the reference's GPU-sized C=64
    (head depth 16, which caps MXU efficiency at ~12.5%; PERF.md's d=16
    structural bound) to C=512 with the default 4 heads, i.e. head depth 128:
    the full MXU contraction depth, the same head geometry that measures
    65.5% MFU on the ImageNet paper config. This is what the MLM task looks
    like when sized for the hardware instead of for 8 GB GPUs."""
    return flagship_mlm(
        vocab_size=vocab_size,
        max_seq_len=max_seq_len,
        num_latents=num_latents,
        num_channels=num_channels,
        num_layers=num_layers,
        num_self_attention_layers_per_block=num_self_attention_layers_per_block,
        dtype=dtype,
        attn_impl=attn_impl,
        remat=remat,
        decoder_attn_impl=decoder_attn_impl,
    )


def tiny_mlm(
    vocab_size: int = 503,
    max_seq_len: int = 64,
    num_latents: int = 16,
    num_channels: int = 32,
    num_layers: int = 2,
    num_self_attention_layers_per_block: int = 1,
    dtype: jnp.dtype = jnp.float32,
    attn_impl: str = "auto",
) -> PerceiverMLM:
    """The CPU-scale twin of the flagship recipe — same code path, minutes
    not hours. One definition shared by the offline (tier-1) modes of the
    serving benches (``tools/inference_bench.py --preset tiny``,
    ``tools/quant_bench.py --cpu``) and the quant parity tests, so the
    "tiny preset" they all quote is the same model."""
    return flagship_mlm(
        vocab_size=vocab_size,
        max_seq_len=max_seq_len,
        num_latents=num_latents,
        num_channels=num_channels,
        num_layers=num_layers,
        num_self_attention_layers_per_block=num_self_attention_layers_per_block,
        dtype=dtype,
        attn_impl=attn_impl,
    )


def flagship_ar(
    vocab_size: int = 10003,
    max_seq_len: int = 512,
    num_latents: int = 256,
    num_channels: int = 512,
    num_layers: int = 3,
    num_self_attention_layers_per_block: int = 6,
    dtype: jnp.dtype = jnp.bfloat16,
    attn_impl: str = "auto",
) -> PerceiverARLM:
    """The generative (Perceiver-AR causal decode) task at the flagship
    TPU-native widths: same encoder recipe shape as ``flagship_tpu_mlm``
    (3 layers × (cross + 6-layer self block), C=512 / head depth 128), with
    the causal latent window covering the last ``num_latents`` positions and
    a causal query decode predicting each successor token.

    ``attn_impl`` stays 'auto', which currently resolves every CAUSAL call
    to XLA — the decode-shape kernel sweep that would set Pallas thresholds
    is queued on the tunnel (PERF.md §Generation); dispatch thresholds only
    move with measurements."""
    return _build_ar(
        vocab_size=vocab_size, max_seq_len=max_seq_len,
        num_latents=num_latents, num_channels=num_channels,
        num_layers=num_layers,
        num_self_attention_layers_per_block=num_self_attention_layers_per_block,
        dtype=dtype, attn_impl=attn_impl,
    )


def tiny_ar(
    vocab_size: int = 503,
    max_seq_len: int = 64,
    num_latents: int = 16,
    num_channels: int = 32,
    num_layers: int = 2,
    num_self_attention_layers_per_block: int = 1,
    dtype: jnp.dtype = jnp.float32,
    attn_impl: str = "auto",
) -> PerceiverARLM:
    """CPU-scale twin of :func:`flagship_ar` — the generation engine /
    serving / chaos tests and the offline modes of the benches all build
    exactly this model (one definition, like :func:`tiny_mlm`)."""
    return _build_ar(
        vocab_size=vocab_size, max_seq_len=max_seq_len,
        num_latents=num_latents, num_channels=num_channels,
        num_layers=num_layers,
        num_self_attention_layers_per_block=num_self_attention_layers_per_block,
        dtype=dtype, attn_impl=attn_impl,
    )


def _build_ar(
    vocab_size: int,
    max_seq_len: int,
    num_latents: int,
    num_channels: int,
    num_layers: int,
    num_self_attention_layers_per_block: int,
    dtype: jnp.dtype,
    attn_impl: str,
) -> PerceiverARLM:
    return PerceiverARLM(
        input_adapter=TextInputAdapter(
            vocab_size=vocab_size, max_seq_len=max_seq_len,
            num_channels=num_channels, dtype=dtype,
        ),
        output_adapter=TextOutputAdapter(
            vocab_size=vocab_size, max_seq_len=max_seq_len,
            num_output_channels=num_channels, dtype=dtype,
        ),
        num_latents=num_latents,
        num_layers=num_layers,
        num_self_attention_layers_per_block=num_self_attention_layers_per_block,
        dtype=dtype,
        attn_impl=attn_impl,
    )


def flagship_mlm(
    vocab_size: int = 10003,
    max_seq_len: int = 512,
    num_latents: int = 256,
    num_channels: int = 64,
    num_layers: int = 3,
    num_self_attention_layers_per_block: int = 6,
    dtype: jnp.dtype = jnp.float32,
    attn_impl: str = "auto",
    remat: bool = False,
    decoder_attn_impl: Optional[str] = None,
) -> PerceiverMLM:
    """The BASELINE.md north-star config: reference train_mlm shapes
    (SURVEY.md §3.1 — 512-token sequences, 256 latents, 3 encoder layers ×
    (cross-attention + 6-layer self-attention block), text in/out adapters).

    ``decoder_attn_impl``: override the DECODER's attention impl separately
    (None = same as ``attn_impl``) — the encoder's long-KV streaming shapes
    and the decoder's many-queries/few-keys gather-decode shape can prefer
    different paths (PERF.md r5 long-context decomposition)."""
    latent_shape = (num_latents, num_channels)
    return PerceiverMLM(
        encoder=PerceiverEncoder(
            input_adapter=TextInputAdapter(
                vocab_size=vocab_size, max_seq_len=max_seq_len,
                num_channels=num_channels, dtype=dtype,
            ),
            latent_shape=latent_shape,
            num_layers=num_layers,
            num_self_attention_layers_per_block=num_self_attention_layers_per_block,
            dtype=dtype,
            attn_impl=attn_impl,
            remat=remat,
        ),
        decoder=PerceiverDecoder(
            output_adapter=TextOutputAdapter(
                vocab_size=vocab_size, max_seq_len=max_seq_len,
                num_output_channels=num_channels, dtype=dtype,
            ),
            latent_shape=latent_shape,
            dtype=dtype,
            attn_impl=decoder_attn_impl or attn_impl,
        ),
        masking=TextMasking(
            vocab_size=vocab_size, unk_token_id=1, mask_token_id=2,
            num_special_tokens=3,
        ),
    )
