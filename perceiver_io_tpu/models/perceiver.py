"""The generic Perceiver IO core: encoder, decoder, and composed models.

Architecture (reference ``perceiver/model.py``): arbitrary-modality inputs are
cross-attended into a small fixed-size latent array — decoupling compute from
input length M (the architectural long-context mechanism: all O(M) work is a
single cross-attention per layer; quadratic self-attention touches only the N
latents) — then decoded by cross-attending task-specific output queries
against the latents.

Key structural semantics preserved:

- encoder layer 1 has unique weights; layers 2..num_layers share ONE weight
  set applied recurrently (reference ``model.py:162-166,185-187``). In flax,
  re-calling the same bound submodule shares parameters, and JAX autodiff
  accumulates gradients across applications exactly like torch autograd.
- learned latent / output-query arrays init ~N(0, 0.02) clamped to ±2
  (reference ``model.py:169-174,222-227``).
- the decoder validates the latent shape (reference ``model.py:232-233``) —
  here at trace time, so the check costs nothing at run time.

TPU-first choices: modules take a ``dtype`` (bfloat16 compute, f32 params),
an ``attn_impl`` switch ('xla' einsum vs. fused Pallas kernel), and an
optional ``remat`` flag that rematerializes each perceiver layer to trade
FLOPs for HBM when the recurrent stack is deep.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from perceiver_io_tpu.ops.attention import CrossAttentionLayer, SelfAttentionBlock
from perceiver_io_tpu.ops.masking import IGNORE_LABEL, TextMasking

Array = jax.Array


def latent_init(std: float = 0.02, clamp: float = 2.0):
    """~N(0, std) clamped to ±clamp (reference ``model.py:169-174``)."""

    def init(key, shape, dtype=jnp.float32):
        return jnp.clip(jax.random.normal(key, shape) * std, -clamp, clamp).astype(dtype)

    return init


class PerceiverLayer(nn.Module):
    """One encoder layer: cross-attention (latent ← input) + self-attention block
    (reference ``model.py:150-160``)."""

    num_latent_channels: int
    num_input_channels: int
    num_cross_attention_heads: int
    num_self_attention_heads: int
    num_self_attention_layers_per_block: int
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "auto"

    @nn.compact
    def __call__(self, x_latent, x_input, pad_mask=None, deterministic=True,
                 kv=None):
        """Always returns ``(x_latent, kv)``: ``kv`` is the cross-attention's
        (k, v) projection of ``x_input`` — computed here when the ``kv``
        argument is None, or the caller's cached tensors passed through
        (the shared-weight recurrence, ``PerceiverEncoder.reuse_kv``). The
        unconditional tuple return keeps the signature remat-safe: no static
        bool crosses the ``nn.remat`` boundary, and ``kv`` is a pytree."""
        x_latent, kv = CrossAttentionLayer(
            num_q_channels=self.num_latent_channels,
            num_kv_channels=self.num_input_channels,
            num_heads=self.num_cross_attention_heads,
            dropout=self.dropout,
            dtype=self.dtype,
            attn_impl=self.attn_impl,
            # this KV stream is the adapted input — the tensor shard_seq=True
            # shards over the mesh's seq axis — so it may route to the
            # sequence-parallel kernel when that regime is active
            seq_shard_kv=True,
            name="cross_attention_layer",
        )(x_latent, x_input, pad_mask=pad_mask, deterministic=deterministic,
          kv=kv, return_kv=True)
        x_latent = SelfAttentionBlock(
            num_layers=self.num_self_attention_layers_per_block,
            num_channels=self.num_latent_channels,
            num_heads=self.num_self_attention_heads,
            dropout=self.dropout,
            dtype=self.dtype,
            attn_impl=self.attn_impl,
            name="self_attention_block",
        )(x_latent, deterministic=deterministic)
        return x_latent, kv


class PerceiverEncoder(nn.Module):
    """Generic Perceiver IO encoder (reference ``model.py:119-189``).

    ``input_adapter`` is injected by the caller (the reference's inversion of
    control, ``model.py:121,145``); its ``num_input_channels`` sizes the
    cross-attention KV stream.
    """

    input_adapter: nn.Module
    latent_shape: Tuple[int, int]
    num_layers: int
    num_cross_attention_heads: int = 4
    num_self_attention_heads: int = 4
    num_self_attention_layers_per_block: int = 2
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "auto"
    remat: bool = False
    # Reuse the shared layer_n cross-attention K/V projections across its
    # recurrent applications: identical weights × identical input ⇒ identical
    # k/v, so the repeat is pure recompute. Exact (the cached tensors are
    # reused, not re-derived); the win is mostly the BACKWARD projection pass
    # autodiff would otherwise emit per application — measured 2.3 ms/step on
    # the 131k-token MLM config (PERF.md r5). Off: recompute per application
    # (marginally less live memory under remat).
    reuse_kv: bool = True

    def _make_layer(self, name: str) -> nn.Module:
        cls = nn.remat(PerceiverLayer) if self.remat else PerceiverLayer
        return cls(
            num_latent_channels=self.latent_shape[1],
            num_input_channels=self.input_adapter.num_input_channels,
            num_cross_attention_heads=self.num_cross_attention_heads,
            num_self_attention_heads=self.num_self_attention_heads,
            num_self_attention_layers_per_block=self.num_self_attention_layers_per_block,
            dropout=self.dropout,
            dtype=self.dtype,
            attn_impl=self.attn_impl,
            name=name,
        )

    @nn.compact
    def __call__(self, x, pad_mask=None, deterministic=True):
        # batch size comes from the adapted (B, M, C) stream, not the raw
        # input — multimodal adapters take a dict of arrays
        x = self.input_adapter(x)
        b = x.shape[0]

        latent = self.param("latent", latent_init(), self.latent_shape)
        x_latent = jnp.broadcast_to(latent.astype(self.dtype), (b, *self.latent_shape))

        x_latent, _ = self._make_layer("layer_1")(
            x_latent, x, pad_mask=pad_mask, deterministic=deterministic
        )
        if self.num_layers > 1:
            # One weight set used recurrently for layers 2..num_layers
            # (reference model.py:162-166,185-187). Its K/V projection of the
            # (unchanging) input is identical across applications — cache and
            # reuse it (reuse_kv above).
            layer_n = self._make_layer("layer_n")
            kv = None
            for _ in range(self.num_layers - 1):
                x_latent, kv_out = layer_n(
                    x_latent, x, pad_mask=pad_mask, deterministic=deterministic,
                    kv=kv,
                )
                if self.reuse_kv:
                    kv = kv_out
        return x_latent


class PerceiverDecoder(nn.Module):
    """Generic Perceiver IO decoder (reference ``model.py:192-237``).

    A learned output-query array of shape ``output_adapter.output_shape``
    cross-attends against the latents, then the injected output adapter maps
    the result to task output.
    """

    output_adapter: nn.Module
    latent_shape: Tuple[int, int]
    num_cross_attention_heads: int = 4
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "auto"

    @nn.compact
    def __call__(self, x, deterministic=True, positions: Optional[Array] = None,
                 return_features: bool = False):
        """``positions``: optional (B, K) int — decode only these rows of the
        learned output-query array. Each output query attends to the latents
        independently (no query-query interaction anywhere in the decoder), so
        decoding a subset is exactly the corresponding rows of the full decode.
        This is the TPU-first answer to the reference's decoder memory hot spot
        (the (B, 512, vocab) logits, SURVEY.md §3.1): callers that only need a
        few positions (e.g. the ~15% masked MLM positions) skip the dominant
        vocab-projection FLOPs for the rest.

        ``return_features=True`` skips the output adapter and returns the
        (B, K, C) decoder stream — for callers that fuse the head into the
        loss (``fused_linear_cross_entropy_with_ignore``).
        """
        b, *d = x.shape
        if tuple(d) != tuple(self.latent_shape):
            raise ValueError(
                f"Latent shape {tuple(d)} different from required shape "
                f"{tuple(self.latent_shape)}"
            )

        output_shape = self.output_adapter.output_shape
        output = self.param("output", latent_init(), tuple(output_shape))
        if positions is not None:
            # (B, K, C): per-batch rows of the learned query array
            x_output = jnp.take(output, positions, axis=0).astype(self.dtype)
        else:
            x_output = jnp.broadcast_to(output.astype(self.dtype), (b, *output_shape))

        x_output = CrossAttentionLayer(
            num_q_channels=output_shape[-1],
            num_kv_channels=self.latent_shape[1],
            num_heads=self.num_cross_attention_heads,
            dropout=self.dropout,
            dtype=self.dtype,
            attn_impl=self.attn_impl,
            name="cross_attention_layer",
        )(x_output, x, deterministic=deterministic)
        if return_features:
            return x_output
        return self.output_adapter(x_output)


class PerceiverIO(nn.Module):
    """encoder → decoder (reference ``model.py:321-325``).

    ``encoder_deterministic`` overrides the dropout mode for the encoder alone —
    the transfer-learning case where a frozen pretrained encoder runs in eval
    mode while the decoder head trains with dropout (the reference's
    ``freeze()`` = requires_grad False + ``.eval()``, ``train/utils.py:5-8``).
    """

    encoder: PerceiverEncoder
    decoder: PerceiverDecoder

    def __call__(self, x, pad_mask=None, deterministic=True,
                 encoder_deterministic: Optional[bool] = None):
        enc_det = deterministic if encoder_deterministic is None else encoder_deterministic
        x_latent = self.encoder(x, pad_mask=pad_mask, deterministic=enc_det)
        return self.decoder(x_latent, deterministic=deterministic)

    def encode(self, x, pad_mask=None, deterministic=True) -> Array:
        """Encoder half only: inputs → (B, N, C) latents.

        The latent array is the model's entire summary of the input —
        Perceiver IO's analogue of a KV cache. Serving callers run this once
        per input and then :meth:`decode` arbitrarily many query sets against
        the cached latents (``model.apply(vars, x, method="encode")``),
        amortizing all O(M) encoder work across decodes.
        """
        return self.encoder(x, pad_mask=pad_mask, deterministic=deterministic)

    def decode(self, x_latent: Array, deterministic=True,
               positions: Optional[Array] = None, return_features: bool = False):
        """Decoder half only: cached latents (+ optional (B, K) query
        ``positions``) → task output. Exactly the fused forward's decoder —
        each output query attends to the latents independently, so
        ``decode(encode(x))`` is the fused ``__call__`` computation."""
        return self.decoder(
            x_latent, deterministic=deterministic, positions=positions,
            return_features=return_features,
        )


class PerceiverARLayer(nn.Module):
    """One causal encoder layer for the Perceiver-AR decode path: causal
    cross-attention (latent window ← full input prefix) + causal latent
    self-attention block.

    Same submodule names as :class:`PerceiverLayer`
    (``cross_attention_layer`` / ``self_attention_block``) so the param tree
    keeps the torch-mirrored leaf names every sharding regex and interop
    mapping matches on. Three call modes share the one weight set:

    - **dense** (training / prefill / the parity oracle): ``causal_offset``
      masks the cross-attention (query i at absolute position offset+i sees
      keys ``<= offset+i``), the self-attention block is square-causal.
      ``return_cache=True`` additionally harvests the tensors an incremental
      decode caches — the cross (k, v) of the input stream and each
      self-attention sub-layer's (k, v) — from the SAME computation.
    - **kv_only**: project one new token's cross (k, v) for the cache ring.
    - **incremental** (``latent_cache``): ``x_latent`` is the (B, 1, C) new
      latent row; cross-attention runs against the caller-updated input ring
      (``kv`` + ``pad_mask`` ring validity), the self-attention block writes
      and attends its per-sub-layer rings at ``latent_index``.
    """

    num_latent_channels: int
    num_input_channels: int
    num_cross_attention_heads: int
    num_self_attention_heads: int
    num_self_attention_layers_per_block: int
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "auto"

    @nn.compact
    def __call__(self, x_latent, x_input, pad_mask=None, deterministic=True,
                 kv=None, causal_offset=None, kv_only=False,
                 return_cache=False, latent_cache=None, latent_index=None,
                 latent_pad=None):
        xlayer = CrossAttentionLayer(
            num_q_channels=self.num_latent_channels,
            num_kv_channels=self.num_input_channels,
            num_heads=self.num_cross_attention_heads,
            dropout=self.dropout,
            dtype=self.dtype,
            attn_impl=self.attn_impl,
            name="cross_attention_layer",
        )
        if kv_only:
            return xlayer(x_latent, x_input, kv_only=True)
        x_latent, kv_out = xlayer(
            x_latent, x_input, pad_mask=pad_mask, deterministic=deterministic,
            kv=kv, return_kv=True, causal_offset=causal_offset,
        )
        block = SelfAttentionBlock(
            num_layers=self.num_self_attention_layers_per_block,
            num_channels=self.num_latent_channels,
            num_heads=self.num_self_attention_heads,
            dropout=self.dropout,
            dtype=self.dtype,
            attn_impl=self.attn_impl,
            name="self_attention_block",
        )
        if latent_cache is not None:
            x_latent, rings = block(
                x_latent, deterministic=deterministic, cache=latent_cache,
                cache_index=latent_index, cache_pad=latent_pad,
            )
            return x_latent, rings
        if return_cache:
            x_latent, self_kvs = block(
                x_latent, deterministic=deterministic, causal_offset=0,
                return_kv=True,
            )
            return x_latent, kv_out, self_kvs
        x_latent = block(x_latent, deterministic=deterministic,
                         causal_offset=0)
        return x_latent, kv_out


class PerceiverARLM(nn.Module):
    """Perceiver-AR causal language model (Hawthorne et al., 2022) on the
    Perceiver IO component set: an arbitrary-length token prefix is
    cross-attended into a small causal latent window covering the LAST N
    positions, a causal latent self-attention stack refines it, and a causal
    query decode predicts each window position's successor token.

    Layout (torch-mirrored leaf names, PARAM_RULES-compatible):

    - ``input_adapter``: token embedding + learned positions — the SAME
      adapter the MLM stack uses, so the long-prefix encode rides the r5
      long-context machinery unchanged (streaming fused cross-attention,
      ``attn_impl='auto'`` KV-block tiers).
    - ``latent``: ONE learned (1, C) latent row added to every window
      query. Per-position identity comes from the (position-stable) input
      embedding — a per-slot learned array would re-assign rows as the
      window advances and break incremental-vs-dense parity.
    - ``layer_1`` / ``layer_n``: the encoder recurrence of
      :class:`PerceiverEncoder` (layer 1 unique, layers 2..num_layers ONE
      shared weight set, cross K/V reused across applications), causal.
    - ``output`` + ``cross_attention_layer`` + ``output_adapter``: the
      decode — learned per-position output queries cross-attend the latent
      window DIAGONALLY-causally (query i sees latents ``<= i``; without
      this, a future latent would leak its token into an earlier
      prediction), then the vocab projection.

    Window rule: a length-L input with ``latent_offset`` o (default
    ``L - min(num_latents, L)``) computes ``n = L - o`` latents for absolute
    positions ``[o, L)``; logits row i predicts token ``o + i + 1``.

    Incremental decode (:meth:`prefill` / :meth:`step`): prefill runs the
    dense forward once over the (padded) prefix and harvests every tensor
    the dense path attends over into fixed-capacity cache rings — input
    cross (k, v) per cross weight set, latent (k, v) per (application,
    sub-layer), final-latent (k, v) for the decode — so step t's single-row
    recompute is attending over EXACTLY the dense forward's tensors. That is
    the correctness spine: token-t logits from the cached step match a dense
    full-prefix forward at 2e-5 on the f32 path (pinned tier-1).
    """

    input_adapter: nn.Module
    output_adapter: nn.Module
    num_latents: int
    num_layers: int
    num_cross_attention_heads: int = 4
    num_self_attention_heads: int = 4
    num_self_attention_layers_per_block: int = 2
    dropout: float = 0.0
    dtype: jnp.dtype = jnp.float32
    attn_impl: str = "auto"

    def setup(self):
        c = self.input_adapter.num_input_channels
        self.latent = self.param("latent", latent_init(), (1, c))
        common = dict(
            num_latent_channels=c,
            num_input_channels=c,
            num_cross_attention_heads=self.num_cross_attention_heads,
            num_self_attention_heads=self.num_self_attention_heads,
            num_self_attention_layers_per_block=(
                self.num_self_attention_layers_per_block),
            dropout=self.dropout,
            dtype=self.dtype,
            attn_impl=self.attn_impl,
        )
        self.layer_1 = PerceiverARLayer(**common)
        if self.num_layers > 1:
            self.layer_n = PerceiverARLayer(**common)
        self.output = self.param(
            "output", latent_init(), tuple(self.output_adapter.output_shape)
        )
        self.cross_attention_layer = CrossAttentionLayer(
            num_q_channels=self.output_adapter.output_shape[-1],
            num_kv_channels=c,
            num_heads=self.num_cross_attention_heads,
            dropout=self.dropout,
            dtype=self.dtype,
            attn_impl=self.attn_impl,
        )

    def _offset(self, l: int, latent_offset: Optional[int]) -> int:
        o = l - min(self.num_latents, l) if latent_offset is None else latent_offset
        if not 0 <= o < l:
            raise ValueError(f"latent_offset {o} outside [0, {l})")
        if l - o > self.num_latents:
            raise ValueError(
                f"latent window {l - o} exceeds num_latents {self.num_latents}"
            )
        return o

    def _encode_window(self, h, pad_mask, o: int, deterministic: bool,
                       return_cache: bool):
        """Shared dense trunk: embedded input → causal latent window."""
        q = h[:, o:] + self.latent.astype(self.dtype)
        caches = []
        if return_cache:
            x, kv1, skvs = self.layer_1(
                q, h, pad_mask=pad_mask, deterministic=deterministic,
                causal_offset=o, return_cache=True)
            caches.append(skvs)
        else:
            x, kv1 = self.layer_1(q, h, pad_mask=pad_mask,
                                  deterministic=deterministic,
                                  causal_offset=o)
        kvn = None
        for _ in range(self.num_layers - 1):
            if return_cache:
                x, kvn, skvs = self.layer_n(
                    x, h, pad_mask=pad_mask, deterministic=deterministic,
                    kv=kvn, causal_offset=o, return_cache=True)
                caches.append(skvs)
            else:
                x, kvn = self.layer_n(x, h, pad_mask=pad_mask,
                                      deterministic=deterministic, kv=kvn,
                                      causal_offset=o)
        return x, kv1, kvn, caches

    def _decode_window(self, x, o: int, n: int, deterministic: bool,
                       return_kv: bool):
        queries = jnp.broadcast_to(
            self.output[o: o + n].astype(self.dtype),
            (x.shape[0], n, self.output.shape[-1]),
        )
        out = self.cross_attention_layer(
            queries, x, deterministic=deterministic, causal_offset=0,
            return_kv=return_kv,
        )
        if return_kv:
            out, final_kv = out
            return self.output_adapter(out), final_kv
        return self.output_adapter(out)

    def __call__(self, token_ids: Array, pad_mask: Optional[Array] = None,
                 deterministic: bool = True,
                 latent_offset: Optional[int] = None) -> Array:
        """Dense causal forward — training and the incremental-parity
        oracle: (B, L) token ids → (B, L - offset, vocab) logits, row i
        predicting token ``offset + i + 1``."""
        h = self.input_adapter(token_ids)
        l = h.shape[1]
        o = self._offset(l, latent_offset)
        x, _, _, _ = self._encode_window(h, pad_mask, o, deterministic, False)
        return self._decode_window(x, o, l - o, deterministic, False)

    def prefill(self, token_ids: Array, pad_mask: Optional[Array] = None,
                length: Optional[Array] = None,
                latent_offset: Optional[int] = None,
                deterministic: bool = True):
        """Dense forward over the (possibly right-padded) prefix + cache
        harvest: returns ``(logits, cache)``. ``length`` (scalar int32
        array) is the REAL token count — slots at positions ``>= length``
        hold pad garbage, are masked by the cache validity rules, and are
        overwritten as generation proceeds. The cache pytree:

        ``len``    scalar int32 — real tokens resident,
        ``cross``  per cross weight set, (k, v) rings (B, W, E) over the
                   input stream (+ the prefix pad mask folded into ``pad``),
        ``pad``    (B, W) bool — True where the ring slot is invalid
                   (beyond ``len``, or a prefix pad token),
        ``latent`` per encoder application, per self-attention sub-layer,
                   (k, v) rings (B, N, E),
        ``final``  (k, v) ring (B, N, E) of decoded latent states.
        """
        h = self.input_adapter(token_ids)
        b, l = token_ids.shape
        o = self._offset(l, latent_offset)
        n = l - o
        if length is None:
            length = jnp.asarray(l, jnp.int32)
        x, kv1, kvn, latent_caches = self._encode_window(
            h, pad_mask, o, deterministic, True)
        logits, final_kv = self._decode_window(x, o, n, deterministic, True)
        invalid = jnp.arange(l, dtype=jnp.int32)[None, :] >= length
        if pad_mask is not None:
            invalid = invalid | pad_mask
        cross = {"layer_1": kv1}
        if self.num_layers > 1:
            cross["layer_n"] = kvn
        cache = {
            "len": jnp.asarray(length, jnp.int32),
            "cross": cross,
            "pad": jnp.broadcast_to(invalid, (b, l)),
            "latent": latent_caches,
            "final": final_kv,
        }
        return logits, cache

    def step(self, cache, token: Array, deterministic: bool = True):
        """One incremental decode step: append ``token`` (B, 1) at position
        ``cache['len']``, recompute ONLY the new latent row against the
        cache rings, and return ``(next_logits (B, vocab), new_cache)`` —
        the logits for position ``len + 1``. Shape-stable in everything but
        the (donatable) cache, so the whole generation loop is one compiled
        program chained by ``lax.fori_loop`` (the tunnel-safe timing
        discipline of PERF.md)."""
        lax = jax.lax
        k1 = cache["cross"]["layer_1"][0]
        b, w, _ = k1.shape
        n_cap = cache["final"][0].shape[1]
        o = w - n_cap
        p = cache["len"]                      # the new token's position
        s = p - o                             # its latent window slot
        zero = jnp.zeros((), jnp.int32)

        pos = jnp.broadcast_to(jnp.reshape(p, (1, 1)), (b, 1))
        h = self.input_adapter(token, positions=pos)

        # append this token's cross k/v per weight set (same projections the
        # dense forward applies — PerceiverARLayer kv_only)
        cross = {}
        layers = {"layer_1": self.layer_1}
        if self.num_layers > 1:
            layers["layer_n"] = self.layer_n
        for name, layer in layers.items():
            k_new, v_new = layer(h, h, kv_only=True)
            k_ring, v_ring = cache["cross"][name]
            cross[name] = (
                lax.dynamic_update_slice(
                    k_ring, k_new.astype(k_ring.dtype), (zero, p, zero)),
                lax.dynamic_update_slice(
                    v_ring, v_new.astype(v_ring.dtype), (zero, p, zero)),
            )
        # ring validity: the new slot becomes live, stale pad slots beyond
        # stay masked (True = masked out)
        live = jnp.arange(w, dtype=jnp.int32)[None, :] == p
        kv_pad = jnp.broadcast_to(
            (cache["pad"] | (jnp.arange(w, dtype=jnp.int32)[None, :] > p))
            & ~live,
            (b, w))
        lat_pad = jnp.broadcast_to(
            jnp.arange(n_cap, dtype=jnp.int32)[None, :] > s, (b, n_cap))

        x = h + self.latent.astype(self.dtype)
        new_latent = []
        apps = [("layer_1", 0)] + [
            ("layer_n", a) for a in range(1, self.num_layers)
        ]
        for name, a in apps:
            x, rings = layers[name](
                x, h, pad_mask=kv_pad, deterministic=deterministic,
                kv=cross[name], latent_cache=cache["latent"][a],
                latent_index=s, latent_pad=lat_pad,
            )
            new_latent.append(rings)

        # decode: append the new final-latent k/v, query = output[p]
        fk, fv = self.cross_attention_layer(x, x, kv_only=True)
        final = (
            lax.dynamic_update_slice(
                cache["final"][0], fk.astype(cache["final"][0].dtype),
                (zero, s, zero)),
            lax.dynamic_update_slice(
                cache["final"][1], fv.astype(cache["final"][1].dtype),
                (zero, s, zero)),
        )
        query = jnp.broadcast_to(
            jnp.take(self.output, jnp.reshape(p, (1,)), axis=0
                     ).astype(self.dtype)[None],
            (b, 1, self.output.shape[-1]),
        )
        dec = self.cross_attention_layer(
            query, x, pad_mask=lat_pad, kv=final,
            deterministic=deterministic,
        )
        logits = self.output_adapter(dec)[:, 0, :]
        new_cache = {
            "len": p + 1,
            "cross": cross,
            "pad": cache["pad"] & ~live,
            "latent": new_latent,
            "final": final,
        }
        return logits, new_cache


class PerceiverMLM(nn.Module):
    """masking → encoder → decoder, logits truncated to input length
    (reference ``model.py:296-318``).

    Masking consumes the ``'masking'`` RNG stream, so a forward with
    ``masking=True`` must be applied with ``rngs={'masking': key}``.
    """

    encoder: PerceiverEncoder
    decoder: PerceiverDecoder
    masking: TextMasking

    def __call__(
        self,
        x_input: Array,
        pad_mask: Optional[Array] = None,
        masking: bool = True,
        deterministic: bool = True,
        loss_gather_capacity: Optional[int] = None,
        return_features: bool = False,
        positions: Optional[Array] = None,
    ) -> Tuple[Array, Optional[Array]]:
        """``loss_gather_capacity``: when set (and ``masking=True``), decode
        only the masked positions — up to that many per row — instead of all L.

        ``positions`` (B, K) int, ``masking=False`` only: decode ONLY these
        positions and return (B, K, vocab) logits (labels None) — the
        inference-side counterpart of the gather decode (each output query
        attends to the latents independently, so this is exactly the
        corresponding rows of the full decode). Long-context fill-mask needs
        this: a full (B, L, vocab) decode at L = 32k+ is a GB-scale tensor
        for a handful of [MASK] positions.

        CE ignores label-(-100) positions entirely, and un-decoded output
        queries receive zero gradient in the full computation too (their logits
        never touch the loss), so loss AND gradients are bit-equivalent to the
        full decode as long as no row has more masked positions than the
        capacity (use ≥ 2·mask_p·L; overflow odds are negligible — at the
        reference config, Binomial(512, 0.15) > 154 is a >13σ event). Skips
        ~(1 − K/L) of the vocab-projection FLOPs, the step's dominant matmul
        (SURVEY.md §3.1 hot spots).
        """
        _, l = x_input.shape

        if positions is not None and masking:
            raise ValueError(
                "positions= is an inference-path argument (masking=False); "
                "training's masked-position gather is loss_gather_capacity="
            )

        if masking:
            key = self.make_rng("masking")
            x_masked, x_labels = self.masking(key, x_input, pad_mask)
        else:
            x_masked = x_input
            x_labels = None

        x_latent = self.encoder(x_masked, pad_mask=pad_mask, deterministic=deterministic)

        if positions is not None:
            x_out = self.decoder(
                x_latent, deterministic=deterministic, positions=positions,
                return_features=return_features,
            )
            return x_out, None

        if masking and loss_gather_capacity is not None:
            # First-K masked indices per row (lax.top_k is index-stable), then
            # earliest unmasked indices; the latter carry label -100 already,
            # so gathered labels mark the padding slots ignored for free.
            # Capacity clamps to the (static) batch width: bucketed-width
            # batches shorter than the configured capacity decode l positions
            # (a permutation of the full decode), never the max_seq_len
            # query count the unclamped full-decode branch would cost.
            capacity = min(loss_gather_capacity, l)
            valid = (x_labels != IGNORE_LABEL).astype(jnp.float32)
            _, gather_positions = jax.lax.top_k(valid, capacity)
            x_out = self.decoder(
                x_latent, deterministic=deterministic,
                positions=gather_positions,
                return_features=return_features,
            )
            return x_out, jnp.take_along_axis(x_labels, gather_positions, axis=1)

        x_out = self.decoder(
            x_latent, deterministic=deterministic,
            return_features=return_features,
        )[:, :l, :]
        return x_out, x_labels

    def encode(self, x_input: Array, pad_mask: Optional[Array] = None,
               deterministic: bool = True) -> Array:
        """Encoder half, inference path (no masking): token ids → latents.

        Encode once, then :meth:`decode` any number of position sets against
        the cached latents — multi-position fill-mask and multi-task decode
        heads pay the encoder cross-attention (all the O(L) work) once.
        Apply with ``model.apply(vars, ids, pad, method="encode")``.
        """
        return self.encoder(x_input, pad_mask=pad_mask, deterministic=deterministic)

    def decode(self, x_latent: Array, deterministic: bool = True,
               positions: Optional[Array] = None,
               return_features: bool = False) -> Array:
        """Decoder half over cached latents: (B, K) ``positions`` → (B, K,
        vocab) logits (None = the full max_seq_len decode — the caller
        truncates to its input length, as ``__call__`` does internally).
        Bit-equivalent to the fused forward's decode: queries never interact,
        so a subset decode is exactly the corresponding rows."""
        return self.decoder(
            x_latent, deterministic=deterministic, positions=positions,
            return_features=return_features,
        )
