"""Task-specific input/output adapters.

The adapter contract is the reference's central extensibility mechanism
(``perceiver/adapter.py:9-32``), preserved here as flax modules satisfying a
shape contract:

- input adapters map task input to ``(B, M, C_in)`` and expose
  ``num_input_channels`` (read by the encoder to size cross-attention KV,
  reference ``model.py:153``);
- output adapters map generic decoder output ``(B, K, C_out)`` to task output
  and expose ``output_shape == (K, C_out)`` (read by the decoder to size its
  learned query array, reference ``model.py:213-222``).

Because flax modules are dataclasses, both properties are derivable from
constructor fields on *unbound* instances — so the encoder/decoder can read
them at construction time exactly like the reference does.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from perceiver_io_tpu.ops.attention import (
    _LinearParams,
    torch_linear_bias_init,
    torch_linear_kernel_init,
)
from perceiver_io_tpu.ops.pallas_matmul import linear_apply
from perceiver_io_tpu.ops.fourier import (
    fourier_position_encodings,
    num_position_encoding_channels,
    spatial_positions,
)

Array = jax.Array


def uniform_init(low: float, high: float):
    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, low, high)

    return init


class InputAdapter(nn.Module):
    """ABC for input adapters (reference ``adapter.py:9-19``)."""

    @property
    def num_input_channels(self) -> int:
        raise NotImplementedError

    def __call__(self, x: Array) -> Array:
        raise NotImplementedError


class OutputAdapter(nn.Module):
    """ABC for output adapters (reference ``adapter.py:22-32``)."""

    @property
    def output_shape(self) -> Tuple[int, int]:
        raise NotImplementedError

    def __call__(self, x: Array) -> Array:
        raise NotImplementedError


class ImageInputAdapter(InputAdapter):
    """Flatten image to (B, H*W, C) and concat Fourier position encodings.

    Reference ``adapter.py:35-109``: coordinates evenly spaced in [-1, 1] per
    spatial dim; ``num_frequency_bands`` linearly spaced frequencies
    1.0 → size/2 with sin+cos plus raw positions; encodings computed once per
    shape and folded into the compiled program as a constant.
    """

    image_shape: Tuple[int, ...] = (28, 28, 1)
    num_frequency_bands: int = 32
    dtype: jnp.dtype = jnp.float32

    @property
    def spatial_shape(self) -> Tuple[int, ...]:
        return self.image_shape[:-1]

    @property
    def num_image_channels(self) -> int:
        return self.image_shape[-1]

    @property
    def num_input_channels(self) -> int:
        return self.num_image_channels + num_position_encoding_channels(
            len(self.spatial_shape), self.num_frequency_bands
        )

    @nn.compact
    def __call__(self, x: Array) -> Array:
        b, *d = x.shape
        if tuple(d) != tuple(self.image_shape):
            raise ValueError(
                f"Input image shape {tuple(d)} different from required shape "
                f"{tuple(self.image_shape)}"
            )

        pos = spatial_positions(self.spatial_shape)
        enc = fourier_position_encodings(pos, self.num_frequency_bands)
        enc = enc.reshape(-1, enc.shape[-1]).astype(self.dtype)  # (M, C_pos)

        x = x.reshape(b, -1, self.num_image_channels).astype(self.dtype)
        enc = jnp.broadcast_to(enc, (b, *enc.shape))
        return jnp.concatenate([x, enc], axis=-1)


class _ScaledEmbed(nn.Embed):
    """``nn.Embed`` whose table is pre-scaled by ``scale`` BEFORE the gather.

    Bit-identical to ``embed(x) * scale`` — each gathered element is the same
    compute-dtype multiply either way — but the multiply streams the
    (vocab, C) table instead of the (B, L, C) output: at seq 131072 the
    output-side mul measures 1.3 ms at 716 GB/s on the device trace
    (hbm_roofline, PERF.md r5) while the table-side mul is noise. Param tree
    unchanged (``{name}/embedding``)."""

    scale: float = 1.0

    def __call__(self, inputs: Array) -> Array:
        if not jnp.issubdtype(inputs.dtype, jnp.integer):
            raise ValueError("Input type must be an integer or unsigned integer.")
        # the free-function spelling (flax.linen.dtypes) — what nn.Embed
        # itself calls; the Module-method spelling doesn't exist on every
        # flax release this runs under
        from flax.linen.dtypes import promote_dtype

        (embedding,) = promote_dtype(
            self.embedding, dtype=self.dtype, inexact=False
        )
        return jnp.take(embedding * self.scale, inputs, axis=0)


class TextInputAdapter(InputAdapter):
    """Token embedding * sqrt(C) + learned position encodings.

    Reference ``adapter.py:112-133``: embedding init U(-0.1, 0.1), position
    encodings (max_seq_len, C) init U(-0.5, 0.5), sliced to actual length.
    """

    vocab_size: int = 10003
    max_seq_len: int = 512
    num_channels: int = 64
    dtype: jnp.dtype = jnp.float32

    @property
    def num_input_channels(self) -> int:
        return self.num_channels

    @nn.compact
    def __call__(self, x: Array, positions: Optional[Array] = None) -> Array:
        """``positions``: optional (B, L) int — the absolute position of each
        token, for callers whose rows do NOT start at position 0 (the AR
        decode step embeds ONE token at its true sequence position). Default
        (None) keeps the contiguous ``[0, L)`` slice — bit-identical to the
        historical behavior, and the gather-free fast path."""
        b, l = x.shape
        if l > self.max_seq_len:
            raise ValueError(f"sequence length {l} exceeds max_seq_len {self.max_seq_len}")

        emb = _ScaledEmbed(
            num_embeddings=self.vocab_size,
            features=self.num_channels,
            embedding_init=uniform_init(-0.1, 0.1),
            dtype=self.dtype,
            scale=math.sqrt(self.num_channels),
            name="text_embedding",
        )(x)
        pos_enc = self.param(
            "pos_encoding",
            uniform_init(-0.5, 0.5),
            (self.max_seq_len, self.num_channels),
        )
        if positions is not None:
            return emb + jnp.take(pos_enc, positions, axis=0).astype(self.dtype)
        return emb + pos_enc[:l].astype(self.dtype)


class ClassificationOutputAdapter(OutputAdapter):
    """Linear head over decoder output; squeezes the query dim when K == 1.

    Reference ``adapter.py:136-149``: output_shape = (num_outputs, C_out) with
    C_out defaulting to num_classes; torch-default Linear init.

    ``pad_classes_to``: round the projection width up to a multiple (e.g. 128
    — one TPU lane tile), emitting logits of that padded width with the extra
    entries pinned to a large negative so softmax/CE/argmax/top-k ignore
    them. This is what makes the vocab projection *tensor-shardable*: the
    reference vocab (10,003) divides no mesh axis, so without padding the
    framework's biggest matmul stays replicated under tp > 1 (the
    ``sharding_for_tree`` divisibility fallback). SURVEY.md §7's
    "vocab-sharded output projection" hard part.
    """

    num_classes: int = 2
    num_outputs: int = 1
    num_output_channels: Optional[int] = None
    dtype: jnp.dtype = jnp.float32
    pad_classes_to: Optional[int] = None

    @property
    def output_shape(self) -> Tuple[int, int]:
        c = self.num_output_channels if self.num_output_channels is not None else self.num_classes
        return (self.num_outputs, c)

    @property
    def padded_num_classes(self) -> int:
        if self.pad_classes_to is None:
            return self.num_classes
        m = self.pad_classes_to
        if m < 1:
            raise ValueError(f"pad_classes_to must be >= 1, got {m}")
        return ((self.num_classes + m - 1) // m) * m

    def masked_head(self, adapter_params) -> Tuple[Array, Array]:
        """(kernel, bias) of the linear head with padded classes masked out
        of the bias — the single source of truth for the ``pad_classes_to``
        scheme when a caller fuses the head into the loss
        (``fused_linear_cross_entropy_with_ignore``) instead of applying this
        adapter. Mirrors the -inf-stand-in masking ``__call__`` applies to
        its logits: padded columns get a large-negative bias, so they vanish
        from any downstream softmax/logsumexp and receive zero gradient."""
        kernel = adapter_params["linear"]["kernel"]
        bias = adapter_params["linear"]["bias"]
        if self.padded_num_classes != self.num_classes:
            col = jnp.arange(bias.shape[-1])
            bias = jnp.where(col < self.num_classes, bias, jnp.asarray(-1e9, bias.dtype))
        return kernel, bias

    @nn.compact
    def __call__(self, x: Array) -> Array:
        c_in = self.output_shape[-1]
        n_out = self.padded_num_classes
        w, b = _LinearParams(
            x.shape[-1], n_out, kernel_init=torch_linear_kernel_init,
            bias_init=torch_linear_bias_init(c_in), name="linear")()
        # the vocab head is the single biggest weight stream in the serving
        # forward — linear_apply routes a quantized tree's kernel through
        # the fused dequant-matmul
        x = linear_apply(x, w, b, self.dtype)
        if n_out != self.num_classes:
            # finite stand-in for -inf: exp() underflows to exactly 0 in the
            # downstream softmax/logsumexp, and no argmax/top-k can pick it
            pad = jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
            x = jnp.where(pad < self.num_classes, x, jnp.asarray(-1e30, x.dtype))
        # Squeeze on the CONFIGURED query count, not the runtime shape: a
        # positions-gathered decode (PerceiverDecoder positions=...) may pass
        # K=1 rows of a multi-query adapter, which must stay (B, 1, C).
        if self.num_outputs == 1 and x.shape[1] == 1:
            x = jnp.squeeze(x, axis=1)
        return x


def TextOutputAdapter(
    vocab_size: int,
    max_seq_len: int,
    num_output_channels: Optional[int] = None,
    dtype: jnp.dtype = jnp.float32,
    pad_classes_to: Optional[int] = None,
) -> ClassificationOutputAdapter:
    """Per-position vocab logits: a classification adapter with one output
    query per sequence position (reference ``adapter.py:152-159``)."""
    return ClassificationOutputAdapter(
        num_classes=vocab_size,
        num_outputs=max_seq_len,
        num_output_channels=num_output_channels,
        dtype=dtype,
        pad_classes_to=pad_classes_to,
    )
