"""Optical-flow adapters: frame-pair input, dense per-pixel output queries.

The reference repo implements no optical flow; these adapters cover the
Perceiver IO paper's flow task (BASELINE.md's Sintel config) and double as the
proof that the injected-adapter contract (reference ``perceiver/adapter.py:9-32``)
generalizes to dense 2D outputs:

- ``OpticalFlowInputAdapter``: a frame pair (B, 2, H, W, C) becomes one token
  per pixel carrying both frames' local patch context (k×k neighborhood,
  extracted with static shifted slices XLA folds into gathers) plus Fourier
  position encodings — the paper's per-pixel patch featurization.
- ``DenseSpatialOutputAdapter``: one decoder query per output pixel,
  ``output_shape = (H·W, C)``; a linear head maps decoder output to
  ``num_output_features`` per pixel, reshaped to (B, H, W, F). For flow,
  F = 2 (dx, dy). Queries are learned arrays, consistent with this
  framework's decoder (reference ``model.py:222``).

Both compose with the unchanged ``PerceiverEncoder``/``PerceiverDecoder``;
``build_optical_flow_model`` assembles the full model.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from perceiver_io_tpu.models.adapters import InputAdapter, OutputAdapter
from perceiver_io_tpu.ops.attention import (
    _LinearParams,
    torch_linear_bias_init,
    torch_linear_kernel_init,
)
from perceiver_io_tpu.ops.pallas_matmul import linear_apply
from perceiver_io_tpu.ops.fourier import (
    fourier_position_encodings,
    num_position_encoding_channels,
    spatial_positions,
)

Array = jax.Array


def extract_patches(x: Array, patch_size: int) -> Array:
    """Per-pixel k×k neighborhoods: (..., H, W, C) → (..., H, W, k*k*C).

    Zero-padded at the borders. Implemented as static shifted slices of one
    padded array — XLA fuses these into cheap strided reads (no gather op).
    """
    if patch_size % 2 != 1:
        raise ValueError(f"patch_size must be odd, got {patch_size}")
    r = patch_size // 2
    *lead, h, w, c = x.shape
    pad = [(0, 0)] * len(lead) + [(r, r), (r, r), (0, 0)]
    xp = jnp.pad(x, pad)
    shifts = [
        xp[..., i : i + h, j : j + w, :]
        for i in range(patch_size)
        for j in range(patch_size)
    ]
    return jnp.concatenate(shifts, axis=-1)


class OpticalFlowInputAdapter(InputAdapter):
    """Frame pair → per-pixel patch features + Fourier position encodings.

    Input: (B, 2, H, W, C) — two frames stacked on axis 1. Output:
    (B, H·W, 2·k²·C + pos_channels).
    """

    image_shape: Tuple[int, int, int] = (368, 496, 3)  # (H, W, C)
    patch_size: int = 3
    num_frequency_bands: int = 64
    dtype: jnp.dtype = jnp.float32

    @property
    def spatial_shape(self) -> Tuple[int, int]:
        return self.image_shape[:2]

    @property
    def num_patch_channels(self) -> int:
        return 2 * self.patch_size**2 * self.image_shape[-1]

    @property
    def num_input_channels(self) -> int:
        return self.num_patch_channels + num_position_encoding_channels(
            2, self.num_frequency_bands
        )

    @nn.compact
    def __call__(self, x: Array) -> Array:
        b, *rest = x.shape
        if tuple(rest) != (2, *self.image_shape):
            raise ValueError(
                f"Input shape {tuple(rest)} != required (2, *{self.image_shape})"
            )
        h, w, _ = self.image_shape

        patches = extract_patches(x.astype(self.dtype), self.patch_size)
        # both frames' patches side by side per pixel: (B, H, W, 2*k²*C)
        patches = jnp.moveaxis(patches, 1, -2).reshape(
            b, h, w, self.num_patch_channels
        )

        pos = spatial_positions((h, w))
        enc = fourier_position_encodings(pos, self.num_frequency_bands)
        enc = jnp.broadcast_to(enc.astype(self.dtype), (b, *enc.shape))
        out = jnp.concatenate([patches, enc], axis=-1)
        return out.reshape(b, h * w, self.num_input_channels)


class DenseSpatialOutputAdapter(OutputAdapter):
    """One decoder query per output pixel; linear head to F features/pixel.

    ``output_shape = (H·W, num_output_channels)`` sizes the decoder's learned
    query array; the head maps to (B, H, W, num_output_features).
    """

    spatial_shape: Tuple[int, int] = (368, 496)
    num_output_features: int = 2  # optical flow: (dx, dy)
    num_output_channels: int = 64
    dtype: jnp.dtype = jnp.float32

    @property
    def output_shape(self) -> Tuple[int, int]:
        h, w = self.spatial_shape
        return (h * w, self.num_output_channels)

    @nn.compact
    def __call__(self, x: Array) -> Array:
        b = x.shape[0]
        wl, bl = _LinearParams(
            x.shape[-1], self.num_output_features,
            kernel_init=torch_linear_kernel_init,
            bias_init=torch_linear_bias_init(self.num_output_channels),
            name="linear")()
        x = linear_apply(x, wl, bl, self.dtype)
        h, w = self.spatial_shape
        return x.reshape(b, h, w, self.num_output_features)


def build_optical_flow_model(
    image_shape: Tuple[int, int, int] = (368, 496, 3),
    latent_shape: Tuple[int, int] = (2048, 512),
    num_layers: int = 1,
    num_self_attention_layers_per_block: int = 24,
    num_cross_attention_heads: int = 1,
    num_self_attention_heads: int = 8,
    patch_size: int = 3,
    num_frequency_bands: int = 64,
    dropout: float = 0.0,
    dtype: jnp.dtype = jnp.float32,
    attn_impl: str = "auto",
    remat: bool = False,
    reuse_kv: bool = True,
):
    """PerceiverIO for optical flow (defaults sized after the Perceiver IO
    paper's flow configuration; shrink everything for tests)."""
    from perceiver_io_tpu.models.perceiver import (
        PerceiverDecoder,
        PerceiverEncoder,
        PerceiverIO,
    )

    h, w, _ = image_shape
    return PerceiverIO(
        encoder=PerceiverEncoder(
            input_adapter=OpticalFlowInputAdapter(
                image_shape=image_shape,
                patch_size=patch_size,
                num_frequency_bands=num_frequency_bands,
                dtype=dtype,
            ),
            latent_shape=latent_shape,
            num_layers=num_layers,
            num_cross_attention_heads=num_cross_attention_heads,
            num_self_attention_heads=num_self_attention_heads,
            num_self_attention_layers_per_block=num_self_attention_layers_per_block,
            dropout=dropout,
            dtype=dtype,
            attn_impl=attn_impl,
            remat=remat,
            reuse_kv=reuse_kv,
        ),
        decoder=PerceiverDecoder(
            output_adapter=DenseSpatialOutputAdapter(
                spatial_shape=(h, w),
                num_output_features=2,
                num_output_channels=latent_shape[1],
                dtype=dtype,
            ),
            latent_shape=latent_shape,
            num_cross_attention_heads=num_cross_attention_heads,
            dropout=dropout,
            dtype=dtype,
            attn_impl=attn_impl,
        ),
    )


def end_point_error(pred: Array, target: Array) -> Array:
    """Mean Euclidean end-point error — the standard optical-flow metric."""
    return jnp.mean(jnp.linalg.norm(pred - target, axis=-1))
