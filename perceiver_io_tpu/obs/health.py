"""Dispatch heartbeats and process health.

The axon-tunneled PJRT backend can wedge (CLAUDE.md): a device call simply
never returns, and a serving loop built on blocking futures hangs silently.
A ``Heartbeat`` turns that failure mode into a *signal*: the dispatch loop
arms it when work goes in flight and beats it on every completion; if no beat
arrives within the deadline, the heartbeat reports stalled — ``/healthz``
flips to 503 — and (once per stall episode) dumps a diagnostic snapshot:
every thread's stack, plus whatever queue/stats context the owner's
``diagnostics`` callback supplies.

Heartbeats self-register in a process-wide set so ``healthz()`` can aggregate
without wiring; ``close()`` (or garbage collection) removes them.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
import weakref
from typing import Any, Callable, Dict, Optional, Tuple

from perceiver_io_tpu.obs import tracing

_HEARTBEATS: "weakref.WeakSet[Heartbeat]" = weakref.WeakSet()
_HEARTBEATS_LOCK = threading.Lock()


def thread_stacks() -> Dict[str, str]:
    """Formatted stack per live thread, keyed by thread name (the core of the
    stall diagnostic: where is everyone stuck?)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    return {
        names.get(ident, f"thread-{ident}"):
            "".join(traceback.format_stack(frame))
        for ident, frame in sys._current_frames().items()
    }


class Heartbeat:
    """Deadline-monitored liveness signal for one dispatch loop.

    - ``arm()`` when work goes in flight (starts the deadline clock);
    - ``beat()`` on every completion (resets it);
    - ``disarm()`` when nothing is in flight (an idle loop is healthy).

    ``deadline_s=None`` disables monitoring (the heartbeat always reports
    healthy and no monitor thread runs). With a deadline, a daemon monitor
    thread watches for a stall and emits the diagnostic dump — detection
    itself (``stalled()``/``healthy()``) is computed on demand, so a health
    probe never depends on the monitor's cadence.
    """

    def __init__(
        self,
        name: str,
        deadline_s: Optional[float] = None,
        diagnostics: Optional[Callable[[], Dict[str, Any]]] = None,
    ):
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be positive, got {deadline_s}")
        self.name = name
        self.deadline_s = deadline_s
        self._diagnostics = diagnostics
        self._lock = threading.Lock()
        self._armed = False
        self._last = time.monotonic()
        self._dumped = False
        self._closed = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        with _HEARTBEATS_LOCK:
            _HEARTBEATS.add(self)
        if deadline_s is not None:
            self._monitor = threading.Thread(
                target=self._watch, name=f"{name}-heartbeat", daemon=True
            )
            self._monitor.start()

    # -- the loop's side -----------------------------------------------------

    def arm(self) -> None:
        with self._lock:
            if not self._armed:
                self._armed = True
                self._last = time.monotonic()

    def beat(self) -> None:
        with self._lock:
            self._last = time.monotonic()
            self._dumped = False

    def disarm(self) -> None:
        with self._lock:
            self._armed = False

    # -- the probe's side ----------------------------------------------------

    def stalled(self) -> bool:
        with self._lock:
            return (
                self._armed
                and self.deadline_s is not None
                and time.monotonic() - self._last > self.deadline_s
            )

    def healthy(self) -> bool:
        return not self.stalled()

    def seconds_since_beat(self) -> float:
        with self._lock:
            return time.monotonic() - self._last

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        self._closed.set()
        self.disarm()
        with _HEARTBEATS_LOCK:
            _HEARTBEATS.discard(self)

    # -- stall monitor -------------------------------------------------------

    def _watch(self) -> None:
        poll = max(0.05, min(self.deadline_s / 4.0, 1.0))
        while not self._closed.wait(poll):
            if not self.stalled():
                continue
            with self._lock:
                if self._dumped:
                    continue
                self._dumped = True
            self._dump()

    def _dump(self) -> None:
        age = self.seconds_since_beat()
        diag: Dict[str, Any] = {}
        if self._diagnostics is not None:
            try:
                diag = self._diagnostics()
            except Exception as e:  # a broken callback must not kill the dump
                diag = {"diagnostics_error": f"{type(e).__name__}: {e}"}
        stacks = thread_stacks()
        print(
            f"[obs] heartbeat {self.name!r} STALLED: no dispatch completion "
            f"for {age:.1f}s (deadline {self.deadline_s}s) — diagnostic "
            f"snapshot follows",
            file=sys.stderr,
        )
        for key, val in diag.items():
            print(f"[obs]   {key}: {val}", file=sys.stderr)
        for tname, stack in stacks.items():
            print(f"[obs]   -- thread {tname} --\n{stack}",
                  file=sys.stderr, end="")
        sys.stderr.flush()
        tracing.event(
            "heartbeat_stall", heartbeat=self.name,
            seconds_since_beat=round(age, 3), deadline_s=self.deadline_s,
            diagnostics=diag, threads=sorted(stacks),
        )


def healthz() -> Tuple[bool, Dict[str, Any]]:
    """Aggregate health over every live heartbeat: ``(ok, detail)``.

    A process with no heartbeats is healthy (nothing claims to be
    dispatching); any stalled heartbeat makes it unhealthy.
    """
    with _HEARTBEATS_LOCK:
        beats = list(_HEARTBEATS)
    detail: Dict[str, Any] = {}
    ok = True
    for hb in sorted(beats, key=lambda h: h.name):
        stalled = hb.stalled()
        detail[hb.name] = {
            "stalled": stalled,
            "seconds_since_beat": round(hb.seconds_since_beat(), 3),
            "deadline_s": hb.deadline_s,
        }
        ok = ok and not stalled
    return ok, {"status": "ok" if ok else "stalled", "heartbeats": detail}
