"""Dispatch heartbeats and process health.

The axon-tunneled PJRT backend can wedge (CLAUDE.md): a device call simply
never returns, and a serving loop built on blocking futures hangs silently.
A ``Heartbeat`` turns that failure mode into a *signal*: the dispatch loop
arms it when work goes in flight and beats it on every completion; if no beat
arrives within the deadline, the heartbeat reports stalled — ``/healthz``
flips to 503 — and (once per stall episode) dumps a diagnostic snapshot:
every thread's stack, plus whatever queue/stats context the owner's
``diagnostics`` callback supplies.

Heartbeats self-register in a process-wide set so ``healthz()`` can aggregate
without wiring; ``close()`` (or garbage collection) removes them.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
import weakref
from typing import Any, Callable, Dict, Optional, Tuple

from perceiver_io_tpu.obs import tracing

_HEARTBEATS: "weakref.WeakSet[Heartbeat]" = weakref.WeakSet()
_HEARTBEATS_LOCK = threading.Lock()

# Non-heartbeat health contributors (circuit breakers, future sources):
# anything exposing health_status() -> (name, ok, detail). Registered by the
# resilience layer; obs stays free of upward imports.
_SOURCES: "weakref.WeakSet" = weakref.WeakSet()
_SOURCES_LOCK = threading.Lock()


def register_health_source(source) -> None:
    """Add a ``health_status() -> (name, ok, detail)`` contributor to
    ``healthz()`` aggregation (weakly referenced; GC removes it)."""
    with _SOURCES_LOCK:
        _SOURCES.add(source)


def unregister_health_source(source) -> None:
    with _SOURCES_LOCK:
        _SOURCES.discard(source)


def thread_stacks() -> Dict[str, str]:
    """Formatted stack per live thread, keyed by thread name (the core of the
    stall diagnostic: where is everyone stuck?)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    return {
        names.get(ident, f"thread-{ident}"):
            "".join(traceback.format_stack(frame))
        for ident, frame in sys._current_frames().items()
    }


class Heartbeat:
    """Deadline-monitored liveness signal for one dispatch loop.

    - ``arm()`` when work goes in flight (starts the deadline clock);
    - ``beat()`` on every completion (resets it);
    - ``disarm()`` when nothing is in flight (an idle loop is healthy).

    ``deadline_s=None`` disables monitoring (the heartbeat always reports
    healthy and no monitor thread runs). With a deadline, a daemon monitor
    thread watches for a stall and emits the diagnostic dump — detection
    itself (``stalled()``/``healthy()``) is computed on demand, so a health
    probe never depends on the monitor's cadence.

    ``on_stall`` (optional) is invoked once per stall episode from the
    monitor thread, right before the diagnostic dump — the actuation hook
    (e.g. tripping a circuit breaker open: a wedged dispatch never *fails*,
    so only the stall monitor can observe it).
    """

    def __init__(
        self,
        name: str,
        deadline_s: Optional[float] = None,
        diagnostics: Optional[Callable[[], Dict[str, Any]]] = None,
        on_stall: Optional[Callable[[], None]] = None,
    ):
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be positive, got {deadline_s}")
        self.name = name
        self.deadline_s = deadline_s
        self._diagnostics = diagnostics
        self._on_stall = on_stall
        self._lock = threading.Lock()
        self._armed = False
        self._last = time.monotonic()
        self._dumped = False
        self._closed = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        with _HEARTBEATS_LOCK:
            _HEARTBEATS.add(self)
        if deadline_s is not None:
            self._monitor = threading.Thread(
                target=self._watch, name=f"{name}-heartbeat", daemon=True
            )
            self._monitor.start()

    # -- the loop's side -----------------------------------------------------

    def arm(self) -> None:
        with self._lock:
            if not self._armed:
                self._armed = True
                self._last = time.monotonic()

    def beat(self) -> None:
        with self._lock:
            self._last = time.monotonic()
            self._dumped = False

    def disarm(self) -> None:
        with self._lock:
            self._armed = False

    # -- the probe's side ----------------------------------------------------

    def stalled(self) -> bool:
        with self._lock:
            return (
                self._armed
                and self.deadline_s is not None
                and time.monotonic() - self._last > self.deadline_s
            )

    def healthy(self) -> bool:
        return not self.stalled()

    def seconds_since_beat(self) -> float:
        with self._lock:
            return time.monotonic() - self._last

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        self._closed.set()
        self.disarm()
        with _HEARTBEATS_LOCK:
            _HEARTBEATS.discard(self)

    # -- stall monitor -------------------------------------------------------

    def _watch(self) -> None:
        poll = max(0.05, min(self.deadline_s / 4.0, 1.0))
        while not self._closed.wait(poll):
            if not self.stalled():
                continue
            if self._on_stall is not None:
                # EVERY poll while stalled, not once per episode: the hook
                # must keep re-asserting for as long as the stall persists
                # (a tripped breaker's cooldown can elapse mid-stall — the
                # re-trip is what keeps it from parking half-open and
                # admitting traffic into a still-wedged dispatch loop)
                try:
                    self._on_stall()
                except Exception as e:  # actuation must not kill the monitor
                    print(f"[obs] heartbeat {self.name!r} on_stall hook "
                          f"failed: {type(e).__name__}: {e}", file=sys.stderr)
            with self._lock:
                if self._dumped:
                    continue
                self._dumped = True
            self._dump()

    def _dump(self) -> None:
        age = self.seconds_since_beat()
        diag: Dict[str, Any] = {}
        if self._diagnostics is not None:
            try:
                diag = self._diagnostics()
            except Exception as e:  # a broken callback must not kill the dump
                diag = {"diagnostics_error": f"{type(e).__name__}: {e}"}
        stacks = thread_stacks()
        print(
            f"[obs] heartbeat {self.name!r} STALLED: no dispatch completion "
            f"for {age:.1f}s (deadline {self.deadline_s}s) — diagnostic "
            f"snapshot follows",
            file=sys.stderr,
        )
        for key, val in diag.items():
            print(f"[obs]   {key}: {val}", file=sys.stderr)
        for tname, stack in stacks.items():
            print(f"[obs]   -- thread {tname} --\n{stack}",
                  file=sys.stderr, end="")
        sys.stderr.flush()
        tracing.event(
            "heartbeat_stall", heartbeat=self.name,
            seconds_since_beat=round(age, 3), deadline_s=self.deadline_s,
            diagnostics=diag, threads=sorted(stacks),
        )


def healthz() -> Tuple[bool, Dict[str, Any]]:
    """Aggregate health over every live heartbeat and registered health
    source (circuit breakers): ``(ok, detail)``.

    A process with no heartbeats or sources is healthy (nothing claims to be
    dispatching); any stalled heartbeat or unhealthy source (an OPEN breaker)
    makes it unhealthy.
    """
    with _HEARTBEATS_LOCK:
        beats = list(_HEARTBEATS)
    detail: Dict[str, Any] = {}
    ok = True
    for hb in sorted(beats, key=lambda h: h.name):
        stalled = hb.stalled()
        detail[hb.name] = {
            "stalled": stalled,
            "seconds_since_beat": round(hb.seconds_since_beat(), 3),
            "deadline_s": hb.deadline_s,
        }
        ok = ok and not stalled
    with _SOURCES_LOCK:
        sources = list(_SOURCES)
    source_detail: Dict[str, Any] = {}
    for src in sources:
        try:
            name, src_ok, src_info = src.health_status()
        except Exception as e:  # a broken source must not break the probe
            name, src_ok, src_info = (
                f"{type(src).__name__}", False,
                {"error": f"{type(e).__name__}: {e}"},
            )
        source_detail[name] = src_info
        ok = ok and src_ok
    body: Dict[str, Any] = {
        "status": "ok" if ok else "degraded", "heartbeats": detail,
    }
    if source_detail:
        body["sources"] = dict(sorted(source_detail.items()))
    return ok, body
