"""Process self-metrics: RSS, uptime, thread count, GC activity.

Host-pressure context for the serving metrics: when an offered-load sweep
saturates, these gauges tell whether the knee is the model (device/compute
bound, RSS flat) or the host (memory growth, thread pile-up, GC churn). No
psutil in this container — everything reads ``/proc`` with stdlib fallbacks,
and every value refreshes at scrape time via the registry's collector hook,
so ``/metrics`` and ``/statz`` always report the current process, not the
last producer write.
"""

from __future__ import annotations

import gc
import os
import threading
import time
from typing import Optional

from perceiver_io_tpu.obs.registry import MetricsRegistry, get_registry

__all__ = ["install_process_metrics", "process_age_s", "process_rss_bytes",
           "process_start_time"]

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def process_rss_bytes() -> Optional[float]:
    """Resident set size in bytes (``/proc/self/statm``; falls back to
    ``resource`` peak-RSS — still useful for trend-free platforms); None when
    neither source exists."""
    try:
        with open("/proc/self/statm") as f:
            return float(f.read().split()[1]) * _PAGE_SIZE
    except (OSError, IndexError, ValueError):
        pass
    try:
        import resource

        # ru_maxrss is KiB on Linux (peak, not current — documented caveat)
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024.0
    except Exception:
        return None


def _boot_relative_start() -> tuple:
    """``(uptime_s, start_s)`` since boot, both from ``/proc`` — one clock,
    no wall time involved; raises when ``/proc`` is unreadable."""
    with open("/proc/self/stat") as f:
        # field 22 (1-indexed) is starttime in clock ticks since boot;
        # split after the parenthesized comm, which can contain spaces
        stat = f.read()
    start_ticks = float(stat.rsplit(")", 1)[1].split()[19])
    with open("/proc/uptime") as f:
        uptime_s = float(f.read().split()[0])
    return uptime_s, start_ticks / os.sysconf("SC_CLK_TCK")


def process_start_time() -> float:
    """Epoch seconds this process started (``/proc`` btime + starttime
    ticks; falls back to this module's import time, which is within the
    interpreter's first imports for every entry point here)."""
    try:
        uptime_s, start_s = _boot_relative_start()
        # epoch arithmetic, not a duration: converting a boot-relative stamp
        # to wall time is the one computation that NEEDS the wall clock
        return time.time() - uptime_s + start_s  # pitlint: ignore[PIT-CLOCK] produces a wall-clock timestamp, not a duration
    except (OSError, IndexError, ValueError):
        return _IMPORT_TIME


def process_age_s() -> float:
    """Seconds this process has been alive, wall-clock-free: both operands
    come from ``/proc``'s boot-relative clock (an NTP step cannot bend the
    uptime gauge). Falls back to a monotonic delta from module import."""
    try:
        uptime_s, start_s = _boot_relative_start()
        return uptime_s - start_s
    except (OSError, IndexError, ValueError):
        return time.monotonic() - _IMPORT_MONOTONIC


_IMPORT_TIME = time.time()
_IMPORT_MONOTONIC = time.monotonic()
_INSTALL_LOCK = threading.Lock()


def install_process_metrics(registry: Optional[MetricsRegistry] = None):
    """Register the process self-metric gauges on ``registry`` (default: the
    process-wide one) and the collector that refreshes them at every export:

    - ``process_rss_bytes`` — current resident set size;
    - ``process_uptime_seconds`` — seconds since process start;
    - ``process_threads`` — live Python threads;
    - ``process_gc_collections`` — cumulative GC collections (all
      generations; a gauge resampled at scrape, so no ``_total`` suffix —
      that suffix is reserved for Counter semantics) — churn here during a
      load sweep is host pressure, not device time;
    - ``process_open_fds`` — open file descriptors (0 when unreadable).

    Idempotent per registry; returns the collector for direct invocation in
    tests."""
    reg = registry if registry is not None else get_registry()
    g_rss = reg.gauge("process_rss_bytes",
                      "resident set size of this process")
    g_up = reg.gauge("process_uptime_seconds", "seconds since process start")
    g_thr = reg.gauge("process_threads", "live Python threads")
    g_gc = reg.gauge("process_gc_collections",
                     "cumulative garbage collections across generations "
                     "(resampled at scrape)")
    g_fds = reg.gauge("process_open_fds", "open file descriptors")

    def collect() -> None:
        rss = process_rss_bytes()
        if rss is not None:
            g_rss.set(rss)
        # duration, so duration clock: wall-clock subtraction here drifted
        # the uptime gauge under NTP steps (pitlint PIT-CLOCK)
        g_up.set(process_age_s())
        g_thr.set(threading.active_count())
        g_gc.set(sum(s.get("collections", 0) for s in gc.get_stats()))
        try:
            g_fds.set(len(os.listdir("/proc/self/fd")))
        except OSError:
            g_fds.set(0)

    with _INSTALL_LOCK:
        # marker on the registry itself (not an id() set — reused addresses
        # after GC would make a fresh registry look already-installed)
        if getattr(reg, "_process_metrics_installed", False):
            return collect
        reg._process_metrics_installed = True
    collect()
    reg.register_collector(collect)
    return collect
