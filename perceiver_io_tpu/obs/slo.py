"""Declarative serving SLOs: objectives, error-budget burn rate, and the
measured capacity model.

Three pieces, composing the r7 registry and r9 health aggregation into
SLO-grade evidence:

- :class:`SLO` — a declarative objective: a latency target (a request
  answered within ``latency_target_s`` is *good*) and an availability target
  (the fraction of requests that must be good). The error budget is
  ``1 - availability_target``.
- :class:`SLOTracker` — per-request accounting against an SLO over a bounded
  window: ``slo_good_fraction`` and ``slo_error_budget_burn_rate`` gauges
  (burn rate = observed bad fraction / error budget — 1.0 means spending the
  budget exactly as fast as it accrues, >1 means burning it down), breach
  counters by reason, and a ``healthz()`` source that degrades the process
  when the burn rate crosses ``burn_alert`` (the same aggregation path as a
  stalled heartbeat or an open breaker, so ``/healthz`` 503s on a burning
  SLO too).
- :func:`fit_capacity` — the capacity model over an offered-load sweep
  (``tools/load_bench.py``): the service-time floor from the light-load
  points, the knee where p99 departs that floor (or shedding begins, or
  achieved throughput stops tracking offered), the achieved-throughput
  plateau as the capacity estimate, and the max offered rate that still
  meets a given SLO.

Pure host-side python over the registry — importable before jax initializes
a backend, provable on CPU while the tunnel is dark.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

from perceiver_io_tpu.obs import health as _health
from perceiver_io_tpu.obs.registry import MetricsRegistry, get_registry

__all__ = ["SLO", "SLOTracker", "fit_capacity"]


@dataclasses.dataclass(frozen=True)
class SLO:
    """One serving objective.

    ``latency_target_s``: a request is *good* when it completes successfully
    within this many seconds (shed/failed requests are always bad).
    ``availability_target``: the fraction of requests that must be good —
    the error budget is its complement. ``burn_alert``: burn rate above
    which the tracker reports unhealthy (None disables the health wire).
    ``min_samples``: the health wire stays quiet below this many recorded
    requests — one bad first request must not 503 a fresh process.

    ``ttft_target_s`` / ``itl_target_s`` (optional) are the STREAM-shaped
    objectives (r21): a decode stream is good against each set target when
    its time-to-first-token / mean inter-token latency lands inside it.
    Request latency is the wrong signal for a token stream — a stream can
    meet a whole-request deadline while every token arrives in stalls —
    so each stream signal gets its own window and burn rate
    (``slo_stream_burn_rate{signal=}``), sharing this SLO's availability
    target, burn alert, and min-samples guard.
    """

    latency_target_s: float
    availability_target: float = 0.999
    name: str = "serving"
    burn_alert: Optional[float] = 2.0
    min_samples: int = 20
    ttft_target_s: Optional[float] = None
    itl_target_s: Optional[float] = None

    def __post_init__(self):
        if self.latency_target_s <= 0:
            raise ValueError(
                f"latency_target_s must be positive, got {self.latency_target_s}"
            )
        if not 0.0 < self.availability_target < 1.0:
            raise ValueError(
                "availability_target must lie in (0, 1) — a 1.0 target has "
                f"zero error budget, got {self.availability_target}"
            )
        for field in ("ttft_target_s", "itl_target_s"):
            v = getattr(self, field)
            if v is not None and v <= 0:
                raise ValueError(f"{field} must be positive, got {v}")

    @property
    def stream_signals(self) -> Dict[str, float]:
        """The configured stream objectives: ``{signal: target_s}`` over
        ``ttft``/``itl`` (empty when this SLO is request-only)."""
        out = {}
        if self.ttft_target_s is not None:
            out["ttft"] = self.ttft_target_s
        if self.itl_target_s is not None:
            out["itl"] = self.itl_target_s
        return out

    @property
    def error_budget(self) -> float:
        return 1.0 - self.availability_target


class SLOTracker:
    """Per-request accounting against one :class:`SLO` over a bounded window.

    ``record(latency_s=..., ok=...)`` classifies each request: good when it
    completed (``ok=True``) within the latency target; bad otherwise, with
    the breach reason counted (``latency`` vs ``error`` — shed requests ride
    the error reason). The window is bounded (an engine serves indefinitely)
    and all derived numbers — good fraction, burn rate — are over that
    window, which is what a burn-rate alert wants: recent behavior, not the
    lifetime average.

    Thread-safe; registers as a ``healthz()`` source when the SLO carries a
    ``burn_alert`` (``close()`` unregisters).
    """

    def __init__(self, slo: SLO, registry: Optional[MetricsRegistry] = None,
                 labels: Optional[Dict[str, str]] = None, window: int = 4096):
        self.slo = slo
        reg = registry if registry is not None else get_registry()
        self._lock = threading.Lock()
        self._window: deque = deque(maxlen=window)  # True = good
        self._good_in_window = 0
        base = {"slo": slo.name, **(labels or {})}
        self._m_target = reg.gauge(
            "slo_latency_target_seconds",
            "latency bound under which a served request counts good", base)
        self._m_avail = reg.gauge(
            "slo_availability_target",
            "fraction of requests that must be good", base)
        self._m_target.set(slo.latency_target_s)
        self._m_avail.set(slo.availability_target)
        self._m_requests = reg.counter(
            "slo_requests_total", "requests classified against the SLO", base)
        self._m_breaches = {
            reason: reg.counter(
                "slo_breaches_total", "bad requests by breach reason",
                {**base, "reason": reason})
            for reason in ("latency", "error")
        }
        self._m_good = reg.gauge(
            "slo_good_fraction", "good requests / all, over the window", base)
        self._m_burn = reg.gauge(
            "slo_error_budget_burn_rate",
            "bad fraction / error budget over the window (1.0 = spending "
            "the budget exactly as it accrues; >1 = burning it down)", base)
        # -- the stream signals (r21): one window + burn gauge per
        # configured target, same availability budget/alert as requests
        self._stream_windows: Dict[str, deque] = {}
        self._stream_good: Dict[str, int] = {}
        self._m_stream_burn: Dict[str, Any] = {}
        self._m_stream_breaches: Dict[str, Any] = {}
        if slo.stream_signals:
            self._m_ttft_target = reg.gauge(
                "slo_ttft_target_seconds",
                "TTFT bound under which a decode stream counts good", base)
            self._m_itl_target = reg.gauge(
                "slo_itl_target_seconds",
                "mean inter-token-latency bound under which a decode "
                "stream counts good", base)
            if slo.ttft_target_s is not None:
                self._m_ttft_target.set(slo.ttft_target_s)
            if slo.itl_target_s is not None:
                self._m_itl_target.set(slo.itl_target_s)
        for signal in slo.stream_signals:
            self._stream_windows[signal] = deque(maxlen=window)
            self._stream_good[signal] = 0
            sig_labels = {**base, "signal": signal}
            self._m_stream_burn[signal] = reg.gauge(
                "slo_stream_burn_rate",
                "bad stream fraction / error budget over the window, per "
                "token-latency signal (ttft|itl)", sig_labels)
            self._m_stream_breaches[signal] = reg.counter(
                "slo_stream_breaches_total",
                "decode streams missing a token-latency target, by signal",
                sig_labels)
        self._name = ":".join(["slo", slo.name]
                              + [v for _, v in sorted((labels or {}).items())])
        self._registered = slo.burn_alert is not None
        if self._registered:
            _health.register_health_source(self)

    def record(self, latency_s: Optional[float] = None, ok: bool = True) -> None:
        """Classify one finished (or shed/failed) request."""
        good = bool(ok) and (
            latency_s is None or latency_s <= self.slo.latency_target_s
        )
        with self._lock:
            if len(self._window) == self._window.maxlen and self._window[0]:
                self._good_in_window -= 1
            self._window.append(good)
            if good:
                self._good_in_window += 1
            n, g = len(self._window), self._good_in_window
        self._m_requests.inc()
        if not good:
            self._m_breaches["latency" if ok else "error"].inc()
        frac = g / n
        self._m_good.set(frac)
        self._m_burn.set((1.0 - frac) / self.slo.error_budget)

    def record_stream(self, ttft_s: Optional[float] = None,
                      itl_s: Optional[float] = None,
                      ok: bool = True) -> None:
        """Classify one finished decode stream against the configured
        stream signals: ``ttft_s`` (enqueue -> first token) and ``itl_s``
        (mean inter-token latency) each against their own target. A stream
        that died (``ok=False``) is bad on every configured signal — a
        killed stream never met its token deadline. No-op on a
        request-only SLO."""
        for signal, target in self.slo.stream_signals.items():
            v = ttft_s if signal == "ttft" else itl_s
            if ok and v is None:
                continue  # signal unmeasured this stream (e.g. 0 tokens)
            good = bool(ok) and v is not None and v <= target
            with self._lock:
                w = self._stream_windows[signal]
                if len(w) == w.maxlen and w[0]:
                    self._stream_good[signal] -= 1
                w.append(good)
                if good:
                    self._stream_good[signal] += 1
                n, g = len(w), self._stream_good[signal]
            if not good:
                self._m_stream_breaches[signal].inc()
            self._m_stream_burn[signal].set(
                (1.0 - g / n) / self.slo.error_budget)

    def good_fraction(self) -> float:
        with self._lock:
            return (self._good_in_window / len(self._window)
                    if self._window else 1.0)

    def burn_rate(self) -> float:
        return (1.0 - self.good_fraction()) / self.slo.error_budget

    def stream_burn_rate(self, signal: Optional[str] = None) -> float:
        """The windowed stream burn rate — one signal, or the max across
        the configured ones (the scrape's single per-replica number).
        0.0 on a request-only SLO or an empty window."""
        signals = ([signal] if signal is not None
                   else list(self._stream_windows))
        worst = 0.0
        with self._lock:
            for s in signals:
                w = self._stream_windows.get(s)
                if not w:
                    continue
                frac = self._stream_good[s] / len(w)
                worst = max(worst, (1.0 - frac) / self.slo.error_budget)
        return worst

    def stream_sample_count(self, signal: str) -> int:
        with self._lock:
            w = self._stream_windows.get(signal)
            return len(w) if w is not None else 0

    def sample_count(self) -> int:
        with self._lock:
            return len(self._window)

    # -- healthz() source ----------------------------------------------------

    def health_status(self):
        burn = self.burn_rate()
        n = self.sample_count()
        alert = self.slo.burn_alert
        ok = (alert is None or n < self.slo.min_samples or burn <= alert)
        detail = {
            "burn_rate": round(burn, 4),
            "good_fraction": round(self.good_fraction(), 4),
            "samples": n,
            "burn_alert": alert,
        }
        # a burning stream signal degrades like a burning request signal
        # (same alert threshold, same per-signal min-samples guard)
        for signal in self._stream_windows:
            s_burn = self.stream_burn_rate(signal)
            s_n = self.stream_sample_count(signal)
            detail[f"stream_{signal}_burn_rate"] = round(s_burn, 4)
            detail[f"stream_{signal}_samples"] = s_n
            if (alert is not None and s_n >= self.slo.min_samples
                    and s_burn > alert):
                ok = False
        return self._name, ok, detail

    def close(self) -> None:
        if self._registered:
            _health.unregister_health_source(self)
            self._registered = False


def fit_capacity(
    points: Sequence[Dict[str, Any]],
    slo: Optional[SLO] = None,
    p99_departure_factor: float = 3.0,
    sustain_fraction: float = 0.9,
    shed_tolerance: float = 1e-3,
) -> Dict[str, Any]:
    """Fit the capacity model from an offered-load sweep.

    ``points``: one dict per offered rate, carrying ``offered_rps``,
    ``achieved_rps``, ``p50_s``, ``p99_s``, ``shed_rate`` (as
    ``tools/load_bench.py`` measures them). Returns:

    - ``service_floor_s`` / ``p99_floor_s``: the light-load latency floor
      (min p50 / min p99 across the sweep) — the service time itself;
    - ``knee_rps``: the highest offered rate the system still *sustains*
      (achieved ≥ ``sustain_fraction`` × offered, shedding within
      ``shed_tolerance`` — an exact-zero bar would let one transient blip
      in a thousand-request point collapse the knee to 0 — and p99 within
      ``p99_departure_factor`` × the p99 floor) — where p99 departs the
      service-time floor;
    - ``capacity_rps``: the achieved-throughput plateau (max achieved across
      the sweep) — what the system actually serves under overload;
    - ``slo_sustainable_rps``: the highest offered rate meeting ``slo``
      (p99 within the latency target, shed rate within the error budget),
      present only when an SLO is given.

    0.0 knee/sustainable values mean no point qualified (the sweep started
    past saturation).
    """
    pts = sorted(points, key=lambda p: float(p["offered_rps"]))
    if not pts:
        raise ValueError("fit_capacity needs at least one sweep point")
    p50s = [float(p["p50_s"]) for p in pts]
    p99s = [float(p["p99_s"]) for p in pts]
    floor_p50 = min(p50s)
    floor_p99 = min(p99s)

    def sustains(p) -> bool:
        return (
            float(p["achieved_rps"])
            >= sustain_fraction * float(p["offered_rps"])
            and float(p["shed_rate"]) <= shed_tolerance
            and float(p["p99_s"]) <= p99_departure_factor * floor_p99
        )

    knee = 0.0
    for p in pts:
        if sustains(p):
            knee = float(p["offered_rps"])
        else:
            break  # the knee is where sustained operation ENDS
    out: Dict[str, Any] = {
        "service_floor_s": floor_p50,
        "p99_floor_s": floor_p99,
        "knee_rps": knee,
        "capacity_rps": max(float(p["achieved_rps"]) for p in pts),
        "points": len(pts),
    }
    if slo is not None:
        ok_rates = [
            float(p["offered_rps"]) for p in pts
            if float(p["p99_s"]) <= slo.latency_target_s
            and float(p["shed_rate"]) <= slo.error_budget
        ]
        out["slo_sustainable_rps"] = max(ok_rates) if ok_rates else 0.0
        out["slo"] = {
            "name": slo.name,
            "latency_target_s": slo.latency_target_s,
            "availability_target": slo.availability_target,
        }
    return out
