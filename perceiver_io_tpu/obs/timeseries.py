"""Windowed metrics time-series: the historical half of observability.

Every exporter so far (``/metrics``, ``/statz``) answers "what is the value
NOW"; nothing answers "what has it been doing for the last two minutes" —
yet that is the question autoscaling policies, rollout bakes, and alert
rules actually ask. This module adds it without unbounding memory:

- :class:`SeriesStore` — a bounded ring-buffer store of samples per series
  key (``name{labels}`` exactly as the registry's ``snapshot()`` keys them,
  plus a ``:p50``/``:p99``/``:count`` field suffix for histogram-derived
  series). Fixed memory by construction: ``max_samples`` per series,
  ``max_series`` keys total (overflow counted, never grown). Queries are
  windowed: ``last``/``points``/``window_agg`` for gauges, counter-reset-
  aware ``delta``/``rate`` for counters, ``age_s`` for absence detection.
- :class:`Sampler` — snapshots every registry instrument at a configurable
  cadence through the registry's collector hook (``snapshot()`` runs
  collectors first, so sampled values — RSS, eventlog queue depth — are
  fresh): counters as cumulative values (the store derives deltas/rates),
  gauges as values, histograms as their windowed p50/p95/p99 + count.
  Optionally persists one ``series_sample`` JSONL record per sweep through
  a dedicated :class:`~perceiver_io_tpu.obs.tracing.EventLog` (size-capped
  rotation, async writer, drop-not-block — the same bounded-telemetry
  contract as the event log it sits alongside).
- **fleet ingestion** (:meth:`SeriesStore.ingest_scrape`) — the Router's
  scrape loop feeds per-replica scrape bodies into one fleet store under
  ``replica=`` labels, so rollout bakes and placement judge against a
  *history* instead of a point read.

Series keys are built with :func:`series_key`; pitlint's PIT-METRIC rule
statically resolves its (and ``AlertRule``'s) metric-name literals against
the registry's known instrument names, so a typo'd key fails lint instead
of silently never matching.

Dual clock stamps per sample (PIT-CLOCK): ``t`` (wall — display, JSONL
correlation) and ``mono`` (monotonic — the only clock windows are computed
from). Importable before jax initializes a backend, like the rest of
``obs``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from perceiver_io_tpu.obs.registry import (
    MetricsRegistry,
    _escape_label,
    _label_suffix,
    get_registry,
    sanitize_metric_name,
)

__all__ = [
    "Sampler",
    "SeriesStore",
    "get_series_store",
    "install_series_store",
    "series_key",
    "split_series_key",
]

# histogram-derived per-series fields (the ``:FIELD`` key suffix); count is
# counter-kind (rate-able), the percentiles are gauge-kind
HISTOGRAM_FIELDS = ("p50", "p95", "p99", "count")

# a sample rate/delta needs two points at least this far apart to divide by
_MIN_SPAN_S = 1e-6


def series_key(name: str, labels: Optional[Dict[str, str]] = None,
               field: Optional[str] = None) -> str:
    """The canonical series key for one instrument (+ optional histogram
    field): ``name{k="v",...}:field`` — byte-identical to the registry
    ``snapshot()`` key so sampled series and hand-built queries meet.

    The ``name`` literal at call sites is statically checked against the
    registry's known instrument names (pitlint PIT-METRIC)."""
    key = sanitize_metric_name(name) + _label_suffix(
        tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items())))
    return f"{key}:{field}" if field else key


def split_series_key(key: str) -> Tuple[str, str, str]:
    """``(base_name, label_suffix, field)`` — the inverse of
    :func:`series_key` (field may be empty)."""
    field = ""
    base = key
    if ":" in key.rsplit("}", 1)[-1]:
        base, field = key.rsplit(":", 1)
        if field not in HISTOGRAM_FIELDS:
            base, field = key, ""
    name, sep, rest = base.partition("{")
    return name, (sep + rest if sep else ""), field


class _Series:
    __slots__ = ("kind", "points")

    def __init__(self, kind: str, max_samples: int):
        self.kind = kind
        # (t_wall, mono, value) rings; maxlen bounds memory per series
        self.points: deque = deque(maxlen=max_samples)


class SeriesStore:
    """Bounded in-memory time-series over ``(key -> ring of samples)``.

    Thread-safe; writers (``record``/``ingest_scrape``) and readers (the
    query surface, ``/seriesz``) may race freely. Memory is fixed by
    construction — ``max_samples`` per series, ``max_series`` series; a
    sample for a key past the cap is DROPPED (counted on
    :attr:`dropped_series`), never grown into."""

    # pitlint PIT-LOCK: the series table is hit from the sampler thread,
    # the router scrape loop, and every query — only under _lock
    _guarded_by = {"_series": "_lock"}

    def __init__(self, max_samples: int = 512, max_series: int = 4096):
        if max_samples < 2:
            raise ValueError(f"max_samples must be >= 2, got {max_samples}")
        if max_series < 1:
            raise ValueError(f"max_series must be >= 1, got {max_series}")
        self.max_samples = max_samples
        self.max_series = max_series
        self.dropped_series = 0  # keys refused at the max_series cap
        self._lock = threading.Lock()
        self._series: Dict[str, _Series] = {}

    # -- writing -------------------------------------------------------------

    def record(self, key: str, value: float, kind: str = "gauge",
               t: Optional[float] = None,
               mono: Optional[float] = None) -> bool:
        """Append one sample; returns False when the key was refused at the
        ``max_series`` cap. Explicit ``t``/``mono`` stamps are for tests and
        replayed ingestion — live producers omit them."""
        t = time.time() if t is None else t
        mono = time.monotonic() if mono is None else mono
        with self._lock:
            s = self._series.get(key)
            if s is None:
                if len(self._series) >= self.max_series:
                    self.dropped_series += 1
                    return False
                s = self._series[key] = _Series(kind, self.max_samples)
            s.points.append((t, mono, float(value)))
        return True

    # the scrape fields the fleet history keeps, and the instrument name
    # each lands under (the same names ReplicaGauges publishes, so the
    # sampled router registry and the directly-ingested store agree)
    _SCRAPE_FIELDS = (
        ("up", "fleet_replica_up", "gauge"),
        ("ready", "fleet_replica_ready", "gauge"),
        ("queue_depth", "fleet_replica_queue_depth", "gauge"),
        ("inflight", "fleet_replica_inflight", "gauge"),
        ("breaker_open", "fleet_replica_breaker_open", "gauge"),
        ("slo_burn", "fleet_replica_slo_burn", "gauge"),
        ("stream_burn", "fleet_replica_stream_burn", "gauge"),
        ("requests_total", "fleet_replica_requests_total", "counter"),
    )

    def ingest_scrape(self, fleet: str, replica: str,
                      scrape: Dict[str, Any],
                      scrape_age_s: Optional[float] = None) -> None:
        """One replica scrape body → per-replica labeled series (the fleet
        aggregation hook the router's scrape loop calls)."""
        labels = {"fleet": fleet, "replica": replica}
        for field, name, kind in self._SCRAPE_FIELDS:
            v = scrape.get(field)
            if v is None and field != "up":
                continue
            self.record(series_key(name, labels),
                        float(bool(v)) if isinstance(v, bool) or v is None
                        else float(v), kind)
        if scrape_age_s is not None:
            self.record(series_key("fleet_scrape_age_s", labels),
                        float(scrape_age_s), "gauge")

    def forget(self, labels: Dict[str, str]) -> int:
        """Drop every series whose key carries ALL the given label pairs;
        returns how many were dropped. The scale-down path: a drained-and-
        retired replica's history must leave the fleet store — the
        autoscaler and the rollout bake query by bare instrument name, and
        a ghost replica's frozen series would keep matching forever."""
        frags = ['%s="%s"' % (str(k), _escape_label(str(v)))
                 for k, v in labels.items()]
        with self._lock:
            doomed = [key for key in self._series
                      if all(f in key for f in frags)]
            for key in doomed:
                del self._series[key]
        return len(doomed)

    # -- reading -------------------------------------------------------------

    def keys(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def n_series(self) -> int:
        with self._lock:
            return len(self._series)

    def kind(self, key: str) -> Optional[str]:
        with self._lock:
            s = self._series.get(key)
            return s.kind if s is not None else None

    def match(self, metric: str) -> List[str]:
        """Keys a rule's ``metric`` selects: an exact key (or anything
        carrying a ``{`` label suffix) matches itself; a bare
        ``name``/``name:field`` matches every label set of that
        instrument."""
        with self._lock:
            if "{" in metric or metric in self._series:
                return [metric] if metric in self._series else []
            want_name, _, want_field = split_series_key(metric)
            out = []
            for key in self._series:
                name, _, field = split_series_key(key)
                if name == want_name and field == want_field:
                    out.append(key)
            return sorted(out)

    def points(self, key: str, window_s: Optional[float] = None,
               now: Optional[float] = None) -> List[Tuple[float, float]]:
        """``(t_wall, value)`` samples within the window (all when None)."""
        with self._lock:
            s = self._series.get(key)
            pts = list(s.points) if s is not None else []
        if window_s is not None:
            now = time.monotonic() if now is None else now
            cutoff = now - window_s
            pts = [p for p in pts if p[1] >= cutoff]
        return [(t, v) for t, _, v in pts]

    def last(self, key: str, window_s: Optional[float] = None,
             now: Optional[float] = None) -> Optional[float]:
        pts = self.points(key, window_s, now)
        return pts[-1][1] if pts else None

    def age_s(self, key: str, now: Optional[float] = None) -> Optional[float]:
        """Seconds since the newest sample (None = never seen) — the
        absence-detection primitive."""
        with self._lock:
            s = self._series.get(key)
            if s is None or not s.points:
                return None
            last_mono = s.points[-1][1]
        return (time.monotonic() if now is None else now) - last_mono

    def _window(self, key: str, window_s: float,
                now: Optional[float]) -> List[Tuple[float, float, float]]:
        now = time.monotonic() if now is None else now
        cutoff = now - window_s
        with self._lock:
            s = self._series.get(key)
            pts = list(s.points) if s is not None else []
        return [p for p in pts if p[1] >= cutoff]

    @staticmethod
    def _delta_of(pts, kind: str) -> float:
        """Change over one in-window point list: reset-aware increment sum
        for counters (a restarted process re-publishing from zero starts a
        new segment instead of going negative), last − first for gauges."""
        if kind == "gauge":
            return pts[-1][2] - pts[0][2]
        total = 0.0
        for (_, _, a), (_, _, b) in zip(pts, pts[1:]):
            if b >= a:
                total += b - a
        return total

    def delta(self, key: str, window_s: float,
              now: Optional[float] = None) -> Optional[float]:
        """Counter increase (gauge change) over the window; None below two
        in-window samples."""
        pts = self._window(key, window_s, now)
        if len(pts) < 2:
            return None
        return self._delta_of(pts, self.kind(key))

    def rate(self, key: str, window_s: float,
             now: Optional[float] = None) -> Optional[float]:
        """Per-second rate of change over the window (delta / observed
        span), computed from ONE ring read — a concurrent append between
        two reads would pair a delta with a mismatched span. None below
        two in-window samples."""
        pts = self._window(key, window_s, now)
        if len(pts) < 2:
            return None
        span = pts[-1][1] - pts[0][1]
        if span < _MIN_SPAN_S:
            return None
        return self._delta_of(pts, self.kind(key)) / span

    def window_agg(self, key: str, window_s: float, agg: str = "last",
                   now: Optional[float] = None) -> Optional[float]:
        """``last``/``mean``/``max``/``min`` over the in-window samples
        (None when the window is empty)."""
        pts = self._window(key, window_s, now)
        if not pts:
            return None
        vals = [v for _, _, v in pts]
        if agg == "last":
            return vals[-1]
        if agg == "mean":
            return sum(vals) / len(vals)
        if agg == "max":
            return max(vals)
        if agg == "min":
            return min(vals)
        raise ValueError(f"unknown agg {agg!r} (last|mean|max|min)")

    def snapshot(self, window_s: Optional[float] = None,
                 points: bool = True) -> Dict[str, Any]:
        """JSON-able view (the ``/seriesz`` body): per key its kind, sample
        count, latest value, and — with ``points`` — the windowed
        ``[t_wall, value]`` pairs.

        The lock is taken per ring, never across the whole table: a full
        snapshot of a mature store (thousands of rings) must not stall the
        scrape loop and the sampler tick behind one observability read."""
        cutoff = None
        if window_s is not None:
            cutoff = time.monotonic() - window_s
        series: Dict[str, Any] = {}
        keys = self.keys()
        for key in keys:
            with self._lock:
                s = self._series.get(key)
                if s is None:
                    continue  # removed between the key list and now
                kind, pts = s.kind, list(s.points)
            if cutoff is not None:
                pts = [p for p in pts if p[1] >= cutoff]
            entry: Dict[str, Any] = {
                "kind": kind, "n": len(pts),
                "last": pts[-1][2] if pts else None,
            }
            if points:
                entry["points"] = [[round(t, 3), v] for t, _, v in pts]
            series[key] = entry
        return {
            "series": series,
            "series_total": len(keys),
            "dropped_series": self.dropped_series,
            "window_s": window_s,
        }


class Sampler:
    """Cadenced registry → :class:`SeriesStore` snapshotter with optional
    rotating-JSONL persistence.

    ``sample_once()`` is the deterministic unit tests and tools drive
    directly; ``start()`` runs it on a daemon thread every ``interval_s``.
    One registry ``snapshot()`` per tick (collectors run — sampled values
    are fresh), every instrument recorded: counters cumulative (query with
    ``rate``/``delta``), gauges as-is, histograms as ``:p50``/``:p95``/
    ``:p99`` gauges + a ``:count`` counter over the instrument's bounded
    observation window as of the tick."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 store: Optional[SeriesStore] = None,
                 interval_s: float = 1.0,
                 jsonl_path: Optional[str] = None,
                 jsonl_max_bytes: Optional[int] = 16 * 1024 * 1024,
                 jsonl_backups: int = 3,
                 name: str = "sampler"):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be positive, got {interval_s}")
        self.registry = registry if registry is not None else get_registry()
        self.store = store if store is not None else SeriesStore()
        self.interval_s = interval_s
        self.name = name
        self._log = None
        if jsonl_path:
            from perceiver_io_tpu.obs.tracing import EventLog

            # the log's drop/queue instruments land in THIS registry, so
            # the sampler's own sweeps see its persistence losses
            self._log = EventLog(jsonl_path, max_bytes=jsonl_max_bytes,
                                 backups=jsonl_backups,
                                 registry=self.registry)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # self-observability (and the PIT-METRIC known-name registrations)
        self._m_sweeps = self.registry.counter(
            "series_sweeps_total", "sampler sweeps performed",
            {"sampler": name})
        self._m_series = self.registry.gauge(
            "series_count", "distinct series keys in the store",
            {"sampler": name})

    def sample_once(self) -> int:
        """One sweep over every registry instrument; returns the number of
        series keys written."""
        snap = self.registry.snapshot()
        flat: Dict[str, float] = {}
        for key, v in snap["counters"].items():
            flat[key] = float(v)
            self.store.record(key, v, "counter")
        for key, v in snap["gauges"].items():
            flat[key] = float(v)
            self.store.record(key, v, "gauge")
        for key, entry in snap["histograms"].items():
            for field in HISTOGRAM_FIELDS:
                v = entry.get(field)
                if v is None:
                    continue
                fkey = f"{key}:{field}"
                flat[fkey] = float(v)
                self.store.record(
                    fkey, v, "counter" if field == "count" else "gauge")
        self._m_sweeps.inc()
        self._m_series.set(self.store.n_series())
        if self._log is not None:
            self._log.write(
                {"event": "series_sample", "sampler": self.name,
                 "n": len(flat), "series": flat})
        return len(flat)

    @property
    def sweeps(self) -> int:
        return int(self._m_sweeps.value)

    def start(self) -> "Sampler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name=f"{self.name}-series", daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:
                pass  # telemetry must never kill its own thread

    def close(self) -> None:
        """Stop the cadence thread and drain the JSONL sink (every sample
        accepted before close lands on disk)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._log is not None:
            self._log.close()

    def __enter__(self) -> "Sampler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- the process-default store (what /seriesz serves) -------------------------

_DEFAULT_STORE: Optional[SeriesStore] = None
_DEFAULT_LOCK = threading.Lock()


def install_series_store(store: Optional[SeriesStore]) -> Optional[SeriesStore]:
    """Install (or with None remove) the process-default series store —
    the one ``ObsServer``'s ``/seriesz`` endpoint serves."""
    global _DEFAULT_STORE
    with _DEFAULT_LOCK:
        _DEFAULT_STORE = store
        return store


def get_series_store() -> Optional[SeriesStore]:
    return _DEFAULT_STORE
