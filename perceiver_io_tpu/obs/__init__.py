"""Unified runtime telemetry for the framework.

One subsystem, four pieces, every layer wired through it:

- :mod:`registry` — the process-wide thread-safe metrics registry (counters,
  gauges, bounded histograms with p50/p95/p99); the single source of truth
  the serving engine, the Trainer/``MetricsLogger``, and the watchdog all
  publish to.
- :mod:`tracing` — span/event tracing to JSONL (compiles, warmups, stalls),
  every record dual-stamped (wall + monotonic) and pid-labeled.
- :mod:`reqtrace` — distributed request tracing: ``TraceContext``
  propagation across router → RPC → replica → engine, span records, and
  cross-process trace assembly with clock alignment and tail sampling.
- :mod:`health` — dispatch heartbeats with stall detection + diagnostic
  thread-stack dumps, aggregated by ``healthz()``.
- :mod:`watchdog` — the in-loop self-profiler: periodic short device traces
  analyzed in-process (``utils/xplane.py`` lower-quartile discipline) into
  live device-step-time / MFU / recompile gauges.
- :mod:`http` — the localhost sidecar serving ``/metrics`` (Prometheus text),
  ``/healthz``, and ``/statz``.
- :mod:`slo` — declarative serving objectives: per-request accounting into
  error-budget burn-rate gauges (wired into ``healthz()``), and the capacity
  model fitted from an offered-load sweep (``tools/load_bench.py``).
- :mod:`process` — process self-metrics (RSS, uptime, threads, GC) refreshed
  at scrape time via the registry's collector hook.
- :mod:`fleet` — multi-replica aggregation: the fleet-aware ``healthz()``
  source (one replica's open breaker degrades that replica's label, never
  the router's status code while other replicas serve) and the per-replica
  labeled gauges the router publishes from its scrape loop.
- :mod:`timeseries` — the historical half: a bounded ring-buffer
  ``SeriesStore`` with windowed ``last``/``rate``/``delta`` queries, a
  cadenced ``Sampler`` over every registry instrument (rotating-JSONL
  persistence, served live as ``/seriesz``), and per-replica fleet
  ingestion from the router's scrape loop.
- :mod:`alerts` — declarative alerting over the series store:
  ``AlertRule`` (threshold / rate-of-change / absence over a window, with
  ``for_s`` hold-down and hysteresis), evaluated into EventLog
  firing/resolved events (exemplar trace-linked), ``alert_state{rule=}``
  gauges, and a ``healthz()`` source — a firing page-class alert degrades
  ``/healthz`` like a stall, a breaker, or SLO burn.

Importing this package never initializes a jax backend — entry points stay
free to pick their platform (``ensure_cpu_only``) first.
"""

from perceiver_io_tpu.obs.health import (
    Heartbeat,
    healthz,
    register_health_source,
    thread_stacks,
    unregister_health_source,
)
from perceiver_io_tpu.obs.fleet import FleetHealth, ReplicaGauges
from perceiver_io_tpu.obs.http import ObsServer
from perceiver_io_tpu.obs.process import install_process_metrics
from perceiver_io_tpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    is_export_process,
    sanitize_metric_name,
)
from perceiver_io_tpu.obs.reqtrace import (
    SPAN_NAMES,
    TraceBuffer,
    TraceContext,
    assemble_traces,
    maybe_trace,
    new_span_id,
    record_span,
    tail_sample,
)
from perceiver_io_tpu.obs.alerts import (
    AlertEngine,
    AlertRule,
    load_rules as load_alert_rules,
)
from perceiver_io_tpu.obs.slo import SLO, SLOTracker, fit_capacity
from perceiver_io_tpu.obs.timeseries import (
    Sampler,
    SeriesStore,
    get_series_store,
    install_series_store,
    series_key,
)
from perceiver_io_tpu.obs.tracing import (
    EventLog,
    configure_event_log,
    event,
    get_event_log,
    span,
)
from perceiver_io_tpu.obs.watchdog import SelfProfiler, install_compile_counter

__all__ = [
    "AlertEngine",
    "AlertRule",
    "Counter",
    "EventLog",
    "FleetHealth",
    "Gauge",
    "Heartbeat",
    "Histogram",
    "MetricsRegistry",
    "ObsServer",
    "ReplicaGauges",
    "SLO",
    "SLOTracker",
    "SPAN_NAMES",
    "Sampler",
    "SelfProfiler",
    "SeriesStore",
    "TraceBuffer",
    "TraceContext",
    "assemble_traces",
    "configure_event_log",
    "event",
    "fit_capacity",
    "get_event_log",
    "get_registry",
    "get_series_store",
    "healthz",
    "install_series_store",
    "load_alert_rules",
    "series_key",
    "install_compile_counter",
    "install_process_metrics",
    "is_export_process",
    "maybe_trace",
    "new_span_id",
    "record_span",
    "register_health_source",
    "sanitize_metric_name",
    "span",
    "tail_sample",
    "thread_stacks",
    "unregister_health_source",
]
