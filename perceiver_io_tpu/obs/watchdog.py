"""In-loop self-profiling: live device-clock step time, MFU, and recompiles.

``tools/hbm_roofline.py`` proved the methodology offline: capture a short
``jax.profiler`` trace, read the DEVICE-recorded per-step windows from the
xplane, take the lower quartile — the one clock the tunnel cannot distort
(PERF.md measurement discipline). ``SelfProfiler`` runs exactly that analysis
*in-process, periodically, during the loop it is measuring*: every
``every_n`` ticks it captures ``trace_steps`` dispatches, analyzes the trace,
and publishes gauges through the metrics registry — device step time when a
TPU plane is present, host step time always (the honest fallback off-TPU or
when the xplane read fails), MFU when a FLOP count is known, and the
process-lifetime jax compilation count (steady state should hold it flat; a
climbing count during serving is the recompile bug the bucket programs
exist to prevent).

Trace start/stop run under a deadline (``utils.profiling.call_with_deadline``)
so a wedged tunnel degrades this to host timing with a warning instead of
freezing the loop it watches.

jax is imported lazily — constructing a profiler must not initialize a
backend before the entry point has chosen one.
"""

from __future__ import annotations

import shutil
import sys
import tempfile
import threading
import time
from typing import Any, Callable, Dict, Optional, Union

from perceiver_io_tpu.obs import registry as _registry_mod
from perceiver_io_tpu.obs import tracing

# jax.profiler supports ONE active trace per process; concurrent profilers
# (engine + trainer, or three engines) take turns instead of erroring
_TRACE_SLOT = threading.Lock()

# weakrefs: every live registry's counter gets each event; a registry no
# longer referenced anywhere (tests build private ones) must stay
# collectable — the process-lifetime listener must not pin it
_COMPILE_COUNTERS: list = []  # list of weakref.ref[Counter]
_COMPILE_LISTENER_INSTALLED = False
_COMPILE_LOCK = threading.Lock()


def install_compile_counter(registry=None):
    """Count every XLA backend compilation into the
    ``jax_compilations_total`` counter of ``registry`` (idempotent per
    registry; returns the counter).

    Rides ``jax.monitoring``'s duration events — ``backend_compile`` fires
    once per real compilation and never for cache hits, which makes the
    counter a live recompile detector. One process-wide listener fans out to
    every registry that asked (tests use private registries; production uses
    the default one).
    """
    global _COMPILE_LISTENER_INSTALLED
    registry = registry or _registry_mod.get_registry()
    counter = registry.counter(
        "jax_compilations_total",
        "XLA backend compilations observed in this process",
    )
    import weakref

    with _COMPILE_LOCK:
        if not any(r() is counter for r in _COMPILE_COUNTERS):
            _COMPILE_COUNTERS.append(weakref.ref(counter))
        if not _COMPILE_LISTENER_INSTALLED:
            try:
                import jax.monitoring

                def _listener(name: str, duration: float, **kwargs) -> None:
                    if not name.endswith("backend_compile_duration"):
                        return
                    dead = False
                    for r in list(_COMPILE_COUNTERS):
                        c = r()
                        if c is None:
                            dead = True
                        else:
                            c.inc()
                    if dead:
                        with _COMPILE_LOCK:
                            _COMPILE_COUNTERS[:] = [
                                r for r in _COMPILE_COUNTERS
                                if r() is not None
                            ]

                jax.monitoring.register_event_duration_secs_listener(_listener)
                _COMPILE_LISTENER_INSTALLED = True
            except Exception as e:  # monitoring API moved: degrade, not crash
                print(f"[obs] compile counter unavailable: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
    return counter


class SelfProfiler:
    """Periodic in-loop trace capture + xplane analysis + gauge publication.

    The owning loop calls ``tick(steps)`` once per DISPATCH (``steps`` =
    optimizer steps / batches that dispatch carried — 1 for the engine, K
    under the Trainer's ``steps_per_dispatch``). ``every_n`` counts steps
    between windows; a window spans ``trace_steps`` dispatches (each dispatch
    is one ``StepTraceAnnotation`` window in the trace). All published
    numbers are normalized PER STEP: the xplane windows are per-dispatch, so
    device time divides by the window's mean dispatch width — without this a
    K-step dispatch reported K× step time and K×-understated MFU (the r4
    in-loop-MFU bug; see ``trainer._maybe_compute_flops``). When a window
    closes, the published metrics are also returned as a dict so the caller
    can forward them to its own logger (the Trainer writes them into
    ``metrics.jsonl`` — same numbers, every sink).

    Published gauges (``<prefix>_…``):
      - ``selfprofile_device_step_ms`` — lower-quartile device step time
        (only when the trace carries a TPU plane);
      - ``selfprofile_host_step_ms`` — host wall-clock per step over the
        window (always; the tunnel-exposed number, kept for contrast);
      - ``selfprofile_mfu`` — from device step time when available, else host
        (requires ``flops_per_step`` and a known device peak);
      - ``selfprofile_windows_total`` / ``selfprofile_failures_total``
        counters, and the process-wide ``jax_compilations_total``.
    """

    def __init__(
        self,
        every_n: int,
        trace_steps: int = 4,
        prefix: str = "train",
        registry=None,
        flops_per_step: Union[None, float, Callable[[], Optional[float]]] = None,
        num_devices: int = 1,
        deadline_s: Optional[float] = 30.0,
        keep_trace_dirs: bool = False,
    ):
        if every_n <= 0:
            raise ValueError(f"every_n must be positive, got {every_n}")
        self.every_n = every_n
        self.trace_steps = max(1, int(trace_steps))
        self.prefix = prefix
        self.deadline_s = deadline_s
        self.keep_trace_dirs = keep_trace_dirs
        self._flops_per_step = flops_per_step
        self._num_devices = num_devices
        reg = registry or _registry_mod.get_registry()
        self._registry = reg
        labels = {"loop": prefix}
        self._g_device_ms = reg.gauge(
            "selfprofile_device_step_ms",
            "lower-quartile device step time from the in-loop trace", labels)
        self._g_host_ms = reg.gauge(
            "selfprofile_host_step_ms",
            "host wall-clock per step over the in-loop trace window", labels)
        self._g_mfu = reg.gauge(
            "selfprofile_mfu",
            "model FLOPs utilization from the in-loop trace", labels)
        self._c_windows = reg.counter(
            "selfprofile_windows_total",
            "in-loop trace windows analyzed", labels)
        self._c_failures = reg.counter(
            "selfprofile_failures_total",
            "in-loop trace windows that degraded (no device plane, deadline, "
            "or capture error)", labels)
        self._c_compiles = install_compile_counter(reg)

        self._since_window = 0
        self._window_dispatches = 0
        self._window_steps = 0
        self._tracing = False
        self._trace_dir: Optional[str] = None
        self._t0 = 0.0
        # guards the _tracing transition between the loop's tick() thread
        # and close() from another thread (engine shutdown with the worker
        # mid-capture): exactly one side may tear the window down and
        # release the trace slot
        self._state_lock = threading.Lock()

    def _flops(self) -> Optional[float]:
        f = self._flops_per_step
        return f() if callable(f) else f

    def _claim_end(self) -> bool:
        """Atomically claim the open capture window for teardown; False when
        there is none (or another thread already claimed it)."""
        with self._state_lock:
            if not self._tracing:
                return False
            self._tracing = False
            return True

    def tick(self, steps: int = 1,
             sync: Optional[Callable[[], Any]] = None) -> Optional[Dict[str, float]]:
        """Advance by one dispatch carrying ``steps`` optimizer steps;
        returns published metrics when a capture window just closed, else
        None. ``sync`` (e.g. block_until_ready on the step output) runs
        before the trace stops so the captured windows are complete."""
        if self._tracing:
            self._window_dispatches += 1
            self._window_steps += steps
            if self._window_dispatches >= self.trace_steps:
                return self._finish(sync)
            return None
        self._since_window += steps
        if self._since_window >= self.every_n:
            self._since_window = 0
            self._start()
        return None

    def _start(self) -> None:
        from perceiver_io_tpu.utils import profiling

        if not _TRACE_SLOT.acquire(blocking=False):
            return  # someone else (trainer profile capture, another engine)
        trace_dir = tempfile.mkdtemp(prefix=f"selfprofile_{self.prefix}_")
        try:
            import jax

            ok, _ = profiling.call_with_deadline(
                lambda: jax.profiler.start_trace(trace_dir),
                self.deadline_s, "start_trace",
            )
        except Exception as e:
            ok = False
            print(f"[obs] selfprofile start_trace failed: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
        if not ok:
            self._c_failures.inc()
            shutil.rmtree(trace_dir, ignore_errors=True)
            _TRACE_SLOT.release()
            return
        with self._state_lock:
            self._trace_dir = trace_dir
            self._tracing = True
        self._window_dispatches = 0
        self._window_steps = 0
        self._t0 = time.perf_counter()

    def _finish(self, sync) -> Optional[Dict[str, float]]:
        from perceiver_io_tpu.utils import profiling

        if not self._claim_end():  # close() got there first
            return None
        host_elapsed = 0.0
        try:
            if sync is not None:
                try:
                    sync()
                except Exception:
                    pass
            # the window ends when the synced work does — stop_trace's own
            # export time (file writes) must not inflate the host number
            host_elapsed = time.perf_counter() - self._t0
            import jax

            ok, _ = profiling.call_with_deadline(
                jax.profiler.stop_trace, self.deadline_s, "stop_trace")
        except Exception as e:
            # a telemetry failure must never crash the loop it watches —
            # stop_trace errors (disk full, proto issues, profiler state)
            # degrade this window, they don't kill the engine/Trainer
            ok = None
            if not host_elapsed:
                host_elapsed = time.perf_counter() - self._t0
            print(f"[obs] selfprofile stop_trace failed: "
                  f"{type(e).__name__}: {e} — publishing host timing only",
                  file=sys.stderr)
        finally:
            _TRACE_SLOT.release()
        trace_dir, self._trace_dir = self._trace_dir, None
        metrics: Dict[str, float] = {}
        steps = max(self._window_steps, 1)
        dispatches = max(self._window_dispatches, 1)
        host_ms = host_elapsed / steps * 1e3
        self._g_host_ms.set(host_ms)
        metrics["selfprofile_host_step_ms"] = host_ms
        step_s = host_elapsed / steps
        if not ok:
            self._c_failures.inc()
            if ok is False:  # deadline (None = already-reported error)
                print(f"[obs] selfprofile stop_trace exceeded the "
                      f"{self.deadline_s}s deadline — publishing host timing "
                      f"only (wedged tunnel?)", file=sys.stderr)
        else:
            try:
                from perceiver_io_tpu.utils import xplane

                # the trace's step windows are per-DISPATCH; normalize by
                # the window's mean dispatch width (K under the Trainer's
                # steps_per_dispatch, 1 for the engine)
                dev_dispatch_s, _ = xplane.device_step_seconds(
                    trace_dir, skip_first=1)
                dev_s = dev_dispatch_s * dispatches / steps
                self._g_device_ms.set(dev_s * 1e3)
                metrics["selfprofile_device_step_ms"] = dev_s * 1e3
                step_s = dev_s
            except Exception:
                # no TPU plane (CPU), proto import missing, empty trace:
                # the host number above is the honest fallback
                self._c_failures.inc()
        flops = self._flops()
        if flops:
            from perceiver_io_tpu.utils import profiling as _p

            u = _p.mfu(flops, step_s, num_devices=self._num_devices)
            if u is not None:
                self._g_mfu.set(u)
                metrics["selfprofile_mfu"] = u
        self._c_windows.inc()
        # snapshot of the process-lifetime counter, gauge-named so callers
        # can forward the dict to MetricsLogger without a kind conflict
        metrics["selfprofile_jax_compilations"] = self._c_compiles.value
        tracing.event("selfprofile_window", loop=self.prefix,
                      **{k: round(v, 6) for k, v in metrics.items()})
        if not self.keep_trace_dirs and trace_dir:
            shutil.rmtree(trace_dir, ignore_errors=True)
        elif trace_dir:
            print(f"[obs] selfprofile trace kept at {trace_dir}",
                  file=sys.stderr)
        return metrics

    def close(self) -> None:
        """Abort an open capture window (error/shutdown paths)."""
        if not self._claim_end():  # no window, or tick()'s _finish owns it
            return
        try:
            import jax

            from perceiver_io_tpu.utils import profiling

            profiling.call_with_deadline(
                jax.profiler.stop_trace, self.deadline_s, "stop_trace")
        except Exception:
            pass
        finally:
            _TRACE_SLOT.release()
            if self._trace_dir:
                shutil.rmtree(self._trace_dir, ignore_errors=True)
                self._trace_dir = None
