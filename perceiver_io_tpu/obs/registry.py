"""Process-wide, thread-safe metrics registry: counters, gauges, and bounded
histograms with on-demand percentiles.

The single source of truth for runtime telemetry: the serving engine, the
Trainer/``MetricsLogger``, and the self-profiling watchdog all publish here,
and every exporter (``/metrics`` Prometheus text, ``/statz`` JSON, the
``metrics.jsonl`` stream) reads the same instruments. Instruments are keyed by
``(name, labels)`` — asking twice returns the same object, so producers in
different modules aggregate naturally.

Deliberately importable before jax initializes any backend (no jax import at
module scope): the CLI entry points parse flags and set up observability
before the first device touch, and ``ensure_cpu_only`` must stay effective.
Multi-host awareness lives at the export edge: every process records locally
(cheap, lock-per-instrument), but ``is_export_process()`` gates the HTTP
sidecar / text exposition to process 0.
"""

from __future__ import annotations

import json
import math
import re
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_ESCAPES = {"\\": "\\\\", '"': '\\"', "\n": "\\n"}


def sanitize_metric_name(name: str) -> str:
    """Coerce an arbitrary scalar key (``val_loss``, ``bucket64.p95``) into a
    valid Prometheus metric name."""
    name = re.sub(r"[^a-zA-Z0-9_:]", "_", str(name))
    if not name or not _NAME_OK.match(name):
        name = "_" + name
    return name


def _escape_label(value: str) -> str:
    return "".join(_LABEL_ESCAPES.get(c, c) for c in str(value))


def _label_suffix(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in labels)
    return "{" + inner + "}"


class _Instrument:
    kind = "untyped"

    def __init__(self, name: str, help: str,
                 labels: Tuple[Tuple[str, str], ...]):
        self.name = name
        self.help = help
        self.labels = labels
        self._lock = threading.Lock()

    @property
    def label_dict(self) -> Dict[str, str]:
        return dict(self.labels)


class Counter(_Instrument):
    """Monotonically increasing count."""

    kind = "counter"

    def __init__(self, name, help, labels):
        super().__init__(name, help, labels)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (inc {n})")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(_Instrument):
    """Last-written value (may go up or down)."""

    kind = "gauge"

    def __init__(self, name, help, labels):
        super().__init__(name, help, labels)
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram(_Instrument):
    """Bounded observation window with exact count/sum, on-demand
    percentiles over the window, and optional *exemplars*.

    An engine serves indefinitely — unbounded per-observation lists would grow
    without limit; a 4096-observation window is plenty for p50/p95/p99
    reporting while keeping memory flat. ``count``/``sum`` stay exact over the
    instrument's whole lifetime (they feed Prometheus summary semantics).

    Exemplars (OpenMetrics-style, carried on ``snapshot()``/``/statz``
    rather than the 0.0.4 text exposition, which predates them): an
    ``observe(v, exemplar=trace_id)`` attaches a concrete trace id to the
    observation, and the histogram keeps a small ring of RECENT exemplars
    plus one sticky slot for the SLOWEST exemplar'd observation ever — so
    "p99 is high" links directly to an assembled trace even after the slow
    request scrolls out of the recency ring.
    """

    kind = "histogram"

    def __init__(self, name, help, labels, window: int = 4096):
        super().__init__(name, help, labels)
        self._window: deque = deque(maxlen=window)
        self._count = 0
        self._sum = 0.0
        self._exemplars: deque = deque(maxlen=8)
        self._slowest_exemplar: Optional[Dict[str, Any]] = None

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        v = float(v)
        with self._lock:
            self._window.append(v)
            self._count += 1
            self._sum += v
            if exemplar is not None:
                entry = {"value": v, "trace": str(exemplar)}
                self._exemplars.append(entry)
                if (self._slowest_exemplar is None
                        or v >= self._slowest_exemplar["value"]):
                    self._slowest_exemplar = entry

    def exemplars(self) -> List[Dict[str, Any]]:
        """(value, trace) exemplars, slowest first: the sticky slowest-ever
        slot plus the recency ring (deduped) — the p99→trace link
        ``tools/trace_assemble.py`` resolves."""
        with self._lock:
            ex = list(self._exemplars)
            slowest = self._slowest_exemplar
        if slowest is not None and slowest not in ex:
            ex.append(slowest)
        return sorted(ex, key=lambda e: -e["value"])

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def values(self) -> List[float]:
        """Copy of the current observation window."""
        with self._lock:
            return list(self._window)

    def percentiles(self, qs=(0.5, 0.95, 0.99)) -> Dict[float, float]:
        """Window percentiles; empty dict when nothing was observed."""
        with self._lock:
            v = sorted(self._window)
        if not v:
            return {}
        return {q: v[min(len(v) - 1, int(q * len(v)))] for q in qs}


class MetricsRegistry:
    """Thread-safe instrument factory + exporter.

    ``counter``/``gauge``/``histogram`` return THE instrument for
    ``(name, labels)`` — creating on first ask, reusing afterwards. Asking for
    an existing name with a different instrument type raises (one name, one
    TYPE line in the exposition).
    """

    # pitlint PIT-LOCK: the instrument table and collector list are hit from
    # every producer thread and every exporter scrape — only under _lock
    _guarded_by = {
        "_instruments": "_lock",
        "_kinds": "_lock",
        "_collectors": "_lock",
    }

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                                _Instrument] = {}
        self._kinds: Dict[str, str] = {}
        self._collectors: List = []

    def register_collector(self, fn) -> None:
        """Add a zero-arg callable invoked at every export (``snapshot`` /
        ``prometheus_text``) BEFORE instruments are read — the pull-model
        hook for sampled values (process RSS, thread count) that would be
        stale if only written on some producer's cadence. Collectors must be
        cheap and must not raise; a raising collector is dropped from
        subsequent exports (telemetry never breaks the scrape)."""
        with self._lock:
            self._collectors.append(fn)

    def _run_collectors(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        dead = []
        for fn in collectors:
            try:
                fn()
            except Exception:
                dead.append(fn)
        if dead:
            with self._lock:
                self._collectors = [
                    c for c in self._collectors if c not in dead
                ]

    def _get(self, cls, name: str, help: str,
             labels: Optional[Dict[str, str]], **kwargs):
        name = sanitize_metric_name(name)
        key = (name, tuple(sorted((str(k), str(v))
                                  for k, v in (labels or {}).items())))
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                kind = self._kinds.get(name)
                if kind is not None and kind != cls.kind:
                    raise TypeError(
                        f"metric {name!r} already registered as {kind}, "
                        f"cannot re-register as {cls.kind}"
                    )
                inst = cls(name, help, key[1], **kwargs)
                self._instruments[key] = inst
                self._kinds[name] = cls.kind
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r}{dict(key[1])} is a "
                    f"{inst.kind}, not a {cls.kind}"
                )
        return inst

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Optional[Dict[str, str]] = None,
                  window: int = 4096) -> Histogram:
        return self._get(Histogram, name, help, labels, window=window)

    def remove(self, name: str,
               labels: Optional[Dict[str, str]] = None) -> bool:
        """Drop ONE ``(name, labels)`` instrument from the exposition;
        returns whether it existed. For bounded-lifecycle label sets only —
        a retired replica's per-replica gauges must leave ``/metrics``
        instead of exporting its last values forever. The name's KIND stays
        reserved (a later re-registration of the same name as a different
        type still raises), and any live reference a producer still holds
        keeps working — it just no longer exports."""
        name = sanitize_metric_name(name)
        key = (name, tuple(sorted((str(k), str(v))
                                  for k, v in (labels or {}).items())))
        with self._lock:
            return self._instruments.pop(key, None) is not None

    def _sorted_instruments(self) -> List[_Instrument]:
        with self._lock:
            return [self._instruments[k] for k in sorted(self._instruments)]

    def instruments_by_key(self) -> Dict[str, _Instrument]:
        """Every instrument keyed by its ``name{labels}`` exposition key —
        how the time-series/alerting layer resolves a series key back to
        the live instrument (e.g. to read a histogram's exemplars)."""
        return {
            inst.name + _label_suffix(inst.labels): inst
            for inst in self._sorted_instruments()
        }

    # -- exporters -----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able view of every instrument (the ``/statz`` body)."""
        self._run_collectors()
        out: Dict[str, Any] = {"counters": {}, "gauges": {}, "histograms": {}}
        for inst in self._sorted_instruments():
            key = inst.name + _label_suffix(inst.labels)
            if isinstance(inst, Counter):
                out["counters"][key] = inst.value
            elif isinstance(inst, Gauge):
                out["gauges"][key] = inst.value
            elif isinstance(inst, Histogram):
                pcts = inst.percentiles()
                entry = {
                    "count": inst.count,
                    "sum": inst.sum,
                    **{f"p{int(q * 100)}": v for q, v in pcts.items()},
                }
                ex = inst.exemplars()
                if ex:
                    entry["exemplars"] = ex
                out["histograms"][key] = entry
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition (version 0.0.4). Histograms export as
        summaries — window quantiles plus exact _sum/_count."""
        self._run_collectors()
        lines: List[str] = []
        seen_header = set()
        for inst in self._sorted_instruments():
            kind = "summary" if isinstance(inst, Histogram) else inst.kind
            if inst.name not in seen_header:
                seen_header.add(inst.name)
                if inst.help:
                    lines.append(f"# HELP {inst.name} {inst.help}")
                lines.append(f"# TYPE {inst.name} {kind}")
            suffix = _label_suffix(inst.labels)
            if isinstance(inst, Histogram):
                for q, v in inst.percentiles().items():
                    q_labels = inst.labels + (("quantile", f"{q:g}"),)
                    lines.append(
                        f"{inst.name}{_label_suffix(q_labels)} {_fmt(v)}"
                    )
                lines.append(f"{inst.name}_sum{suffix} {_fmt(inst.sum)}")
                lines.append(f"{inst.name}_count{suffix} {inst.count}")
            else:
                lines.append(f"{inst.name}{suffix} {_fmt(inst.value)}")
        return "\n".join(lines) + "\n"

    def statz_json(self) -> str:
        return json.dumps(self.snapshot())


def _fmt(v: float) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


_DEFAULT = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (what every layer publishes to when
    not handed an explicit one)."""
    return _DEFAULT


def is_export_process() -> bool:
    """True when this process should export (process 0, or jax not yet
    initialized / single-process).

    Must NEVER force backend initialization: on the tunneled PJRT plugin a
    first device touch can hang indefinitely (CLAUDE.md), and the export
    path (the HTTP sidecar) may start before the entry point's first device
    use. So jax is only consulted when a backend is ALREADY up; otherwise
    this process is assumed to be the exporter (true for every
    single-process flow, and multi-host jobs initialize jax.distributed
    long before anyone exports)."""
    try:
        import sys

        jax = sys.modules.get("jax")
        if jax is None:
            return True
        from jax._src import xla_bridge as xb

        if not getattr(xb, "_backends", None):
            return True  # no backend initialized yet — don't trigger one
        return jax.process_index() == 0
    except Exception:
        return True
