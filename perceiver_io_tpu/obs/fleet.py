"""Fleet-level health + metrics aggregation for the multi-replica router.

The single-process health model (``healthz()``) ANDs every registered source:
one open breaker → 503. Correct for one engine over one device — the process
really cannot serve — but wrong for a router over N replicas, where one
replica's open breaker or burning SLO means *route around it*, not *the
fleet is down*. :class:`FleetHealth` is the aggregation fix (the fleet-aware
``healthz()``): per-replica trouble degrades that replica's LABEL in the
detail body while the router reports healthy as long as at least
``min_serving`` replicas still serve; only a fleet that cannot serve at all
flips ``/healthz`` to 503.

Two supporting pieces:

- :func:`adopt_source` — re-scope a process-global health source (a local
  replica's breaker or SLO tracker, which self-registered into ``healthz()``
  at construction) UNDER the fleet: it is unregistered from the global
  aggregate and folded into its replica's detail instead, so in-process
  replicas get the same degraded-but-serving semantics as subprocess ones
  (whose sources live behind their own ``/healthz``).
- :class:`ReplicaGauges` — the per-replica metric surface the router
  publishes from its scrape loop: ``fleet_replica_up/ready/queue_depth/
  breaker_open/slo_burn{replica=...}`` gauges plus the fleet rollups
  (``fleet_size``, ``fleet_replicas_serving``), so one ``/statz`` scrape of
  the router shows the whole fleet with per-replica labels.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Tuple

from perceiver_io_tpu.obs import health as _health
from perceiver_io_tpu.obs.registry import MetricsRegistry, get_registry

# replica lifecycle states as the router reports them; SERVING counts toward
# the fleet quorum, everything else is visible-but-routed-around
SERVING = "serving"
DEGRADED = "degraded"
JOINING = "joining"
DRAINING = "draining"
DOWN = "down"


class FleetHealth:
    """ONE ``healthz()`` source for a whole replica fleet.

    ``statuses`` is a zero-arg callable returning the router's live view:
    ``{replica_name: {"state": SERVING|DEGRADED|JOINING|DRAINING|DOWN,
    ...detail}}``. The fleet is healthy while at least ``min_serving``
    replicas are in ``SERVING`` — per-replica degradation rides the detail
    body (scrapers see exactly which replica is in trouble and why), never
    the aggregate status code.
    """

    def __init__(self, statuses: Callable[[], Dict[str, Dict[str, Any]]],
                 name: str = "fleet", min_serving: int = 1):
        if min_serving < 1:
            raise ValueError(f"min_serving must be >= 1, got {min_serving}")
        self.name = name
        self.min_serving = min_serving
        self._statuses = statuses
        self._lock = threading.Lock()
        self._adopted: Dict[str, list] = {}
        self._registered = True
        _health.register_health_source(self)

    def adopt_source(self, replica: str, source) -> None:
        """Re-scope ``source`` (breaker / SLO tracker — anything with the
        ``health_status()`` contract) from the process-global ``healthz()``
        aggregate to ``replica``'s detail under this fleet. Without this, an
        in-process replica's open breaker 503s the ROUTER."""
        _health.unregister_health_source(source)
        with self._lock:
            self._adopted.setdefault(replica, []).append(source)

    def release_sources(self, replica: str) -> None:
        """Forget a removed replica's adopted sources (they are NOT re-
        registered globally — the replica is gone)."""
        with self._lock:
            self._adopted.pop(replica, None)

    def _fold_adopted(self, replica: str) -> Tuple[bool, Dict[str, Any]]:
        with self._lock:
            sources = list(self._adopted.get(replica, ()))
        ok, detail = True, {}
        for src in sources:
            try:
                name, src_ok, info = src.health_status()
            except Exception as e:  # a broken source must not break the probe
                name, src_ok, info = (
                    type(src).__name__, False,
                    {"error": f"{type(e).__name__}: {e}"},
                )
            detail[name] = info
            ok = ok and src_ok
        return ok, detail

    # -- the healthz() source contract ---------------------------------------

    def health_status(self) -> Tuple[str, bool, Dict[str, Any]]:
        statuses = dict(self._statuses())
        replicas: Dict[str, Any] = {}
        serving = 0
        for name in sorted(statuses):
            info = dict(statuses[name])
            src_ok, src_detail = self._fold_adopted(name)
            if src_detail:
                info["sources"] = src_detail
            if not src_ok and info.get("state") == SERVING:
                info["state"] = DEGRADED
            if info.get("state") == SERVING:
                serving += 1
            replicas[name] = info
        ok = serving >= self.min_serving
        return f"fleet:{self.name}", ok, {
            "status": ("serving" if serving == len(replicas) and replicas
                       else "degraded" if ok else "down"),
            "serving": serving,
            "replicas_total": len(replicas),
            "min_serving": self.min_serving,
            "replicas": replicas,
        }

    def close(self) -> None:
        if self._registered:
            _health.unregister_health_source(self)
            self._registered = False


class ReplicaGauges:
    """Per-replica labeled gauges + fleet rollups, written by the router's
    scrape loop so one ``/statz`` read shows the whole fleet."""

    def __init__(self, fleet: str = "fleet",
                 registry: Optional[MetricsRegistry] = None):
        self._reg = registry if registry is not None else get_registry()
        self._fleet = fleet
        self._per: Dict[str, Dict[str, Any]] = {}
        # retired replicas are TOMBSTONED: a scrape sweep that snapshotted
        # the fleet before a removal must not resurrect the retired
        # replica's gauges by publishing after remove() (they would export
        # their last values forever); re-admission lifts the tombstone
        self._retired: set = set()
        self._m_size = self._reg.gauge(
            "fleet_size", "replicas the router knows about",
            {"fleet": fleet})
        self._m_serving = self._reg.gauge(
            "fleet_replicas_serving",
            "replicas currently eligible for dispatch", {"fleet": fleet})

    def _gauges(self, replica: str) -> Dict[str, Any]:
        g = self._per.get(replica)
        if g is None:
            labels = {"fleet": self._fleet, "replica": replica}
            g = {
                "up": self._reg.gauge(
                    "fleet_replica_up", "1 = process/transport reachable",
                    labels),
                "ready": self._reg.gauge(
                    "fleet_replica_ready",
                    "1 = warm pool live (engine_ready)", labels),
                "queue_depth": self._reg.gauge(
                    "fleet_replica_queue_depth",
                    "scraped replica queue depth (parts)", labels),
                "inflight": self._reg.gauge(
                    "fleet_replica_inflight",
                    "router-side requests in flight to this replica", labels),
                "breaker_open": self._reg.gauge(
                    "fleet_replica_breaker_open",
                    "1 = any breaker open on the replica", labels),
                "slo_burn": self._reg.gauge(
                    "fleet_replica_slo_burn",
                    "max scraped SLO error-budget burn rate", labels),
                "stream_burn": self._reg.gauge(
                    "fleet_replica_stream_burn",
                    "max scraped per-stream token-latency (TTFT/ITL) "
                    "burn rate", labels),
                "requests_total": self._reg.gauge(
                    "fleet_replica_requests_total",
                    "scraped replica lifetime request count (gauge: the "
                    "router republishes the replica's counter)", labels),
                "scrape_age_s": self._reg.gauge(
                    "fleet_scrape_age_s",
                    "seconds since this replica's last completed scrape — "
                    "staleness beyond N intervals degrades the slot for "
                    "placement", labels),
            }
            self._per[replica] = g
        return g

    def publish(self, replica: str, **values: float) -> None:
        if replica in self._retired:
            return  # a racing post-removal sweep must not resurrect it
        g = self._gauges(replica)
        for key, val in values.items():
            if key in g and val is not None:
                g[key].set(float(val))

    def readmit(self, replica: str) -> None:
        """Lift a retirement tombstone (the replica re-joined the fleet)."""
        self._retired.discard(replica)

    def remove(self, replica: str) -> None:
        """Retire a replica's per-replica gauges from the registry (the
        scale-down path: a drained-and-retired replica must leave
        ``/metrics``, not export its last queue depth forever). The name is
        tombstoned so a scrape sweep racing the removal cannot re-register
        them."""
        self._retired.add(replica)
        g = self._per.pop(replica, None)
        if g is None:
            return
        for inst in g.values():
            self._reg.remove(inst.name, inst.label_dict)

    def publish_fleet(self, size: int, serving: int) -> None:
        self._m_size.set(size)
        self._m_serving.set(serving)
