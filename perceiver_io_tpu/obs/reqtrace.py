"""Distributed request tracing: context propagation, span records, and
cross-process trace assembly.

The r11 phase tracing attributes a request's lifetime WITHIN one engine; at
fleet scale most of a tail request's latency lives elsewhere — router queue,
failover reroutes, the RPC wire, replica queueing, a swap bake. This module
threads one correlation spine through all of it:

- :class:`TraceContext` — a Dapper-style (trace_id, span_id, parent) triple
  plus the head-sampling decision, minted at ``Router.submit`` / engine
  ``submit`` and propagated through the replica RPC as headers
  (:data:`TRACE_HEADERS`) and into engine request parts.
- :func:`record_span` — one span = one :func:`~perceiver_io_tpu.obs.tracing.
  event` record (``event="span"``) carrying the trace triple, a MONOTONIC
  start stamp and duration (PIT-CLOCK: durations never touch the wall
  clock), and whatever attribution fields the hop owns. The
  :class:`~perceiver_io_tpu.obs.tracing.EventLog` stamps every record with
  dual wall+monotonic clocks and the writer's pid, which is what makes
  cross-process assembly possible at all.
- :func:`assemble_traces` — merge per-process JSONL logs into per-request
  span TREES: per-process clock alignment (each process's monotonic spans
  are anchored to the wall clock via the median ``wall − mono`` offset over
  that process's records), parent links joined ACROSS processes, and the
  engine's existing ``request_phases`` records expanded into six child
  spans (the r11 phases ride along as children — they are not
  re-instrumented).
- :func:`tail_sample` — tail-based retention over assembled traces:
  flagged traces (any errored span — which covers in-flight deadline
  expiry and rejection failures — plus failover reroutes and affinity
  spills) and the slowest percentile are always kept; the boring majority
  is sampled down. Admission-time sheds mint no trace at all (nothing ran
  — there is no lifetime to attribute); they remain counted by
  ``router_shed_total`` / ``serving_shed_total``.
- :class:`TraceBuffer` — a bounded in-process ring of recently completed
  trace summaries (the ``/statz``-adjacent "what were my last slow
  requests" view; exemplar-linked from the latency histograms).

Span names are a closed registry (:data:`SPAN_NAMES`) validated statically
(pitlint PIT-SPAN, the PIT-FAULT pattern): a renamed hop cannot silently
decouple its spans from the assembler and docs.
"""

from __future__ import annotations

import itertools
import os
import random
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

import perceiver_io_tpu.obs.tracing as _tracing

__all__ = [
    "SPAN_NAMES",
    "TRACE_HEADERS",
    "TraceBuffer",
    "TraceContext",
    "assemble_traces",
    "maybe_trace",
    "new_span_id",
    "record_span",
    "tail_sample",
]

# the closed span-name registry (pitlint PIT-SPAN validates every literal
# record_span site against it — the PIT-FAULT pattern): one name per hop
# that owns a timed interval of a request's life, plus the fleet-context
# spans the assembler overlays (deploy swaps have no trace of their own)
SPAN_NAMES = frozenset({
    "router_request",         # root: submit() → delivered/failed (router)
    "router_attempt",         # one placement: pick → client.call returned
    "router_reroute",         # failover hop: the backoff gap between attempts
    "router_affinity_spill",  # a session pin died (caller re-encodes)
    "replica_serve",          # replica-side: RPC arrival → response built
    "replica_generate",       # replica-side: one streamed generate RPC
    "generate_step",          # one chunked decode dispatch within a stream
    "decode_stream",          # one stream's whole decode life: enqueue→retire
    "decode_chunk",           # one batched chunk's share of a stream's life
    "deploy_swap",            # install start → bake end (fleet context)
})

# wire propagation (the replica RPC): deliberately minimal — a trace id, the
# caller's span id (the remote child's parent), and the sampling decision
TRACE_HEADERS = ("X-Trace-Id", "X-Parent-Span", "X-Sampled")

# id generation: a per-process random prefix + a shared counter. Counter
# ids cost ~0.3 µs where per-id os.urandom costs ~1.3 µs — on the traced
# serving path (one trace + several span ids per request) that difference
# is a measurable slice of the <=2% overhead budget. Uniqueness: trace ids
# embed the 8-hex process prefix (collision = two processes drawing the
# same 32-bit prefix); span ids only need uniqueness within one trace's
# handful of spans, where a randomly-seeded 32-bit counter is plenty.
# GIL-atomic: itertools.count holds no lock and cannot tear.
_ID_PREFIX = os.urandom(4).hex()
_IDS = itertools.count(int.from_bytes(os.urandom(4), "big"))


def _span_id() -> str:
    return f"{next(_IDS) & 0xFFFFFFFF:08x}"


def new_span_id() -> str:
    """A fresh 8-hex span id — the allocation-free alternative to
    ``ctx.child()`` for hot paths that only need the id triple inline
    (the engine's per-part batch rows)."""
    return _span_id()


class TraceContext:
    """One hop's view of a distributed trace: ``trace_id`` names the
    request fleet-wide, ``span_id`` this hop's span, ``parent_id`` the hop
    above (None at the root). ``sampled`` is the head-sampling decision the
    mint made — every hop honors it (tail retention happens at assembly,
    over whatever was recorded)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "sampled")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.sampled = sampled

    @classmethod
    def mint(cls, sampled: bool = True) -> "TraceContext":
        """A fresh root context (16-hex trace id, 8-hex span id)."""
        return cls(f"{_ID_PREFIX}{next(_IDS) & 0xFFFFFFFF:08x}",
                   f"{next(_IDS) & 0xFFFFFFFF:08x}",
                   parent_id=None, sampled=sampled)

    def child(self) -> "TraceContext":
        """A child context: same trace, fresh span, this span as parent."""
        return TraceContext(self.trace_id, _span_id(),
                            parent_id=self.span_id, sampled=self.sampled)

    def to_headers(self) -> Dict[str, str]:
        """Wire form for the replica RPC: the receiver's ``from_headers``
        yields a context whose ``span_id`` is THIS span (i.e. the caller's),
        so the receiver's ``child()`` parents correctly across the hop."""
        return {
            "X-Trace-Id": self.trace_id,
            "X-Parent-Span": self.span_id,
            "X-Sampled": "1" if self.sampled else "0",
        }

    @classmethod
    def from_headers(cls, headers) -> Optional["TraceContext"]:
        """Reconstruct the CALLER's context from RPC headers (None when the
        request is untraced)."""
        trace_id = headers.get("X-Trace-Id")
        if not trace_id:
            return None
        return cls(trace_id, headers.get("X-Parent-Span") or "",
                   parent_id=None,
                   sampled=headers.get("X-Sampled", "1") != "0")

    def __repr__(self) -> str:
        return (f"TraceContext({self.trace_id}/{self.span_id}"
                f"<-{self.parent_id}, sampled={self.sampled})")


def maybe_trace(sample: float = 1.0) -> Optional[TraceContext]:
    """Mint a root context iff an event log is configured (tracing is free
    when nothing would record the spans) and the head-sampling coin lands.
    ``sample`` is the probability a request is traced (1.0 = all)."""
    if _tracing.get_event_log() is None or sample <= 0.0:
        return None
    if sample < 1.0 and random.random() >= sample:
        return None
    return TraceContext.mint()


def record_span(name: str, ctx: Optional[TraceContext], t0_mono: float,
                dur_s: float, **fields: Any) -> None:
    """Append one span record to the process event log.

    ``t0_mono`` is the span start on THIS process's monotonic clock;
    assembly anchors it to the wall clock via the log's dual stamps.
    ``ctx=None`` records a trace-less context span (``deploy_swap``) that
    assembly overlays rather than attaches."""
    log = _tracing.get_event_log()
    if log is None:
        return
    if ctx is not None and not ctx.sampled:
        return
    # written directly (event()'s first positional is the record's "event"
    # key; a span's own name is a field of the one "span" record shape)
    log.write({
        "event": "span", "name": name,
        "trace": None if ctx is None else ctx.trace_id,
        "span": None if ctx is None else ctx.span_id,
        "parent": None if ctx is None else ctx.parent_id,
        "mono_start": round(t0_mono, 6), "dur_s": round(dur_s, 6), **fields,
    })


class TraceBuffer:
    """Bounded ring of recently completed trace summaries — the in-process
    "what were my last requests" view the latency-histogram exemplars link
    into. One entry per completed root span: ``(trace_id, total_s, flags)``.
    """

    # pitlint PIT-LOCK: the ring is appended by the dispatch pool's worker
    # threads and read by stats/statz pollers — touched only under _lock
    _guarded_by = {"_ring": "_lock"}

    def __init__(self, capacity: int = 256):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)

    def add(self, trace_id: str, total_s: float, **flags: Any) -> None:
        with self._lock:
            self._ring.append({"trace": trace_id,
                               "total_s": round(float(total_s), 6), **flags})

    def recent(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            items = list(self._ring)
        return items if n is None else items[-n:]

    def slowest(self, n: int = 5) -> List[Dict[str, Any]]:
        with self._lock:
            items = list(self._ring)
        return sorted(items, key=lambda r: -r["total_s"])[:n]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


# -- assembly -----------------------------------------------------------------

# engine lifecycle phases, mirrored from inference.engine.PHASES (asserted
# equal by the tier-1 suite) so assembly never imports jax-adjacent modules
_ENGINE_PHASES = ("admission", "queue", "assembly", "dispatch", "device",
                  "complete")

def _clock_offsets(records: Iterable[Dict[str, Any]]) -> Dict[Any, float]:
    """Per-process wall-anchoring offset: median ``wall − mono`` over every
    dual-stamped record the process wrote. Adding the offset to a monotonic
    stamp yields an epoch-comparable time; durations stay pure monotonic."""
    samples: Dict[Any, List[float]] = {}
    for r in records:
        if "t" in r and "mono" in r:
            samples.setdefault(r.get("pid"), []).append(r["t"] - r["mono"])
    offsets = {}
    for pid, vals in samples.items():
        vals.sort()
        offsets[pid] = vals[len(vals) // 2]
    return offsets


def _engine_rows(base: Dict[str, Any], trace: str, span: str,
                 parent: Optional[str], start: float, n_rows,
                 phases_s: List[float], engine, bucket
                 ) -> List[Dict[str, Any]]:
    """One engine span + six phase children from one part's phase values
    (the r11 phases, reused as child spans — never re-instrumented)."""
    out = [{**base, "name": "engine", "trace": trace, "span": span,
            "parent": parent, "mono_start": round(start, 6),
            "dur_s": round(sum(phases_s), 6), "engine": engine,
            "rows": n_rows, "bucket": bucket}]
    t = start
    for i, phase in enumerate(_ENGINE_PHASES):
        dur = phases_s[i]
        out.append({**base, "name": f"phase:{phase}", "trace": trace,
                    "span": f"{span}.{i}", "parent": span,
                    "mono_start": round(t, 6), "dur_s": round(dur, 6)})
        t += dur
    return out


def _span_rows(records: Iterable[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Normalize raw event records into span rows: ``event="span"`` records
    pass through; the engine's compact per-micro-batch
    ``request_phases_batch`` records (integer-microsecond part rows —
    serialization amortized over the batch exactly like the dispatch
    itself) and legacy traced per-part ``request_phases`` records both
    expand into an ``engine`` span plus six phase children."""
    rows: List[Dict[str, Any]] = []
    for r in records:
        kind = r.get("event")
        if kind == "span" and r.get("trace"):
            rows.append(r)
        elif kind == "request_phases_batch":
            base = {k: r.get(k) for k in ("pid", "t", "mono")}
            parts = r.get("parts") or ""
            # packed form: ";"-joined rows of
            # "trace,span,parent,t_entry_us,rows,admission_us,queue_us,
            #  assembly_us,dispatch_us,device_us,complete_us,bucket"
            # (the producer packs so its writer only escape-scans one
            # string; parsing cost lives here, offline)
            for packed in parts.split(";") if parts else ():
                f = packed.split(",")
                trace, span, parent = f[0], f[1], f[2] or None
                phases_s = [int(v) / 1e6 for v in f[5:11]]
                rows.extend(_engine_rows(
                    base, trace, span, parent, int(f[3]) / 1e6, int(f[4]),
                    phases_s, r.get("engine"),
                    int(f[11]) if len(f) > 11 else r.get("bucket")))
        elif kind == "request_phases" and r.get("trace"):
            base = {k: r.get(k) for k in ("pid", "t", "mono")}
            phases_s = [float(r.get(p, 0.0)) for p in _ENGINE_PHASES]
            rows.extend(_engine_rows(
                base, r["trace"], r["span"], r.get("parent"),
                r.get("mono_start", 0.0), r.get("rows"), phases_s,
                r.get("engine"), r.get("bucket")))
    return rows


def assemble_traces(records: Iterable[Dict[str, Any]]
                    ) -> Tuple[Dict[str, Dict[str, Any]],
                               List[Dict[str, Any]]]:
    """Merge raw event records (from ANY number of per-process logs) into
    per-request trace trees.

    Returns ``(traces, context_spans)``: ``traces`` maps trace_id to a dict
    with ``root`` (the parentless span), ``spans`` (all spans, each with an
    ``abs_start`` wall-anchored stamp and a ``children`` id list),
    ``total_s`` (root duration), ``span_sum_s`` (sum of exclusive self
    times — reconciles with ``total_s`` when the tree is complete), and
    ``flags`` (error/reroute/spill booleans). ``context_spans`` carries the
    trace-less fleet spans (deploy swaps) for overlay."""
    records = list(records)
    offsets = _clock_offsets(records)
    rows = _span_rows(records)
    context = [r for r in records
               if r.get("event") == "span" and not r.get("trace")]

    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for row in rows:
        off = offsets.get(row.get("pid"), 0.0)
        row = dict(row)
        row["abs_start"] = row.get("mono_start", 0.0) + off
        by_trace.setdefault(row["trace"], []).append(row)

    traces: Dict[str, Dict[str, Any]] = {}
    for trace_id, spans in by_trace.items():
        by_id = {s["span"]: s for s in spans}
        for s in spans:
            s["children"] = []
        roots = []
        for s in spans:
            parent = by_id.get(s.get("parent"))
            if parent is not None:
                parent["children"].append(s["span"])
            else:
                roots.append(s)
        # prefer the declared root span; fall back to the earliest orphan
        root = next((s for s in roots if s.get("parent") is None), None)
        if root is None and roots:
            root = min(roots, key=lambda s: s["abs_start"])
        if root is None:
            continue

        def self_time(s: Dict[str, Any]) -> float:
            child_sum = sum(by_id[c]["dur_s"] for c in s["children"])
            return max(float(s["dur_s"]) - child_sum, 0.0)

        span_sum = sum(self_time(s) for s in spans
                       if s is root or s.get("parent") in by_id)
        flags = {
            "error": any(s.get("ok") is False or s.get("error")
                         for s in spans),
            "reroute": any(s["name"] == "router_reroute" for s in spans),
            "spill": any(s["name"] == "router_affinity_spill"
                         for s in spans),
        }
        traces[trace_id] = {
            "trace": trace_id,
            "root": root,
            "spans": sorted(spans, key=lambda s: s["abs_start"]),
            "total_s": float(root["dur_s"]),
            "span_sum_s": round(span_sum, 6),
            "processes": sorted({str(s.get("pid")) for s in spans}),
            "flags": flags,
        }
    context = [
        dict(r, abs_start=r.get("mono_start", 0.0)
             + offsets.get(r.get("pid"), 0.0))
        for r in context
    ]
    return traces, context


def tail_sample(traces: Dict[str, Dict[str, Any]],
                slow_pct: float = 0.95,
                sample: float = 0.1,
                seed: int = 0) -> Dict[str, Dict[str, Any]]:
    """Tail-based retention: ALWAYS keep flagged traces (error / reroute /
    spill — the failure tails an investigation needs) and the slowest
    ``1 - slow_pct`` fraction by total duration; keep a ``sample`` fraction
    of the rest (deterministic per trace id hash, so reruns agree)."""
    if not traces:
        return {}
    durs = sorted(t["total_s"] for t in traces.values())
    cut = durs[min(len(durs) - 1, int(slow_pct * len(durs)))]
    rng = random.Random(seed)
    kept: Dict[str, Dict[str, Any]] = {}
    for trace_id in sorted(traces):
        t = traces[trace_id]
        if any(t["flags"].values()) or t["total_s"] >= cut:
            kept[trace_id] = dict(t, kept_for=(
                "flag" if any(t["flags"].values()) else "slow"))
        elif rng.random() < sample:
            kept[trace_id] = dict(t, kept_for="sample")
    return kept
