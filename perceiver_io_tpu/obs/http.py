"""Localhost HTTP sidecar: ``/metrics`` (Prometheus), ``/healthz``,
``/statz``, ``/seriesz``.

A daemon thread running a ``ThreadingHTTPServer`` bound to loopback — the
serving process's observability surface. ``/metrics`` is the registry's text
exposition; ``/healthz`` aggregates the live heartbeats (200 when every
dispatch loop is beating, 503 with detail when one stalled); ``/statz`` is
the JSON snapshot (registry + health) for humans and scripts; ``/seriesz``
is the windowed time-series view over the installed
:class:`~perceiver_io_tpu.obs.timeseries.SeriesStore` (``?window_s=60``
bounds the returned points; 404 until a store is installed).

Multi-host: ``start()`` is a no-op off process 0 (``is_export_process``) —
one exporter per job, the same policy as ``MetricsLogger``.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs

from perceiver_io_tpu.obs import health as _health
from perceiver_io_tpu.obs import timeseries as _timeseries
from perceiver_io_tpu.obs.registry import (
    MetricsRegistry,
    get_registry,
    is_export_process,
)

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class ObsServer:
    """Loopback observability endpoint over a registry + the heartbeat set."""

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        series_store=None,
    ):
        self._registry = registry or get_registry()
        # explicit store wins; otherwise /seriesz follows the process
        # default (installed by the serve CLI / tools when sampling is on)
        self._series_store = series_store
        self._host = host
        self._port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        """The bound port (resolves ``port=0`` ephemeral binds); None until
        started."""
        return self._httpd.server_address[1] if self._httpd else None

    @property
    def url(self) -> Optional[str]:
        return f"http://{self._host}:{self.port}" if self._httpd else None

    def start(self) -> Optional[str]:
        """Bind and serve on a daemon thread; returns the base URL (None when
        this process is not the export process)."""
        if self._httpd is not None:
            return self.url
        if not is_export_process():
            return None
        registry = self._registry
        explicit_store = self._series_store

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args) -> None:
                pass  # scrapes must not spam the serving process's stderr

            def _reply(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self) -> None:
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    self._reply(200, registry.prometheus_text().encode(),
                                PROMETHEUS_CONTENT_TYPE)
                elif path == "/healthz":
                    ok, detail = _health.healthz()
                    self._reply(200 if ok else 503,
                                json.dumps(detail).encode() + b"\n",
                                "application/json")
                elif path == "/statz":
                    ok, detail = _health.healthz()
                    body = {"health": detail, **registry.snapshot()}
                    self._reply(200, json.dumps(body).encode() + b"\n",
                                "application/json")
                elif path == "/seriesz":
                    store = (explicit_store
                             if explicit_store is not None
                             else _timeseries.get_series_store())
                    if store is None:
                        self._reply(
                            404,
                            b"no series store installed (enable sampling: "
                            b"serve --series / install_series_store)\n",
                            "text/plain")
                        return
                    qs = parse_qs(self.path.partition("?")[2])
                    window = None
                    try:
                        if qs.get("window_s"):
                            window = float(qs["window_s"][0])
                    except ValueError:
                        pass  # malformed window: serve the full rings
                    # ?points=0 returns summaries only (kind/n/last) — a
                    # mature store's full rings are a multi-MB body
                    want_points = qs.get("points", ["1"])[0] not in ("0",
                                                                    "false")
                    body = store.snapshot(window_s=window,
                                          points=want_points)
                    self._reply(200, json.dumps(body).encode() + b"\n",
                                "application/json")
                else:
                    self._reply(404, b"not found\n", "text/plain")

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="obs-http", daemon=True
        )
        self._thread.start()
        return self.url

    def close(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            if self._thread is not None:
                self._thread.join(timeout=5)
                self._thread = None

    def __enter__(self) -> "ObsServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()
