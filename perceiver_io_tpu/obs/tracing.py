"""Lightweight span/event tracing to JSONL.

The narrative channel next to the registry's numeric one: discrete runtime
happenings (a bucket program compiled, a warmup finished, a heartbeat
stalled) append one JSON object per line to a configured file. Unconfigured,
``event``/``span`` are near-free no-ops — library code calls them
unconditionally and only entry points opt into a sink.

Thread-safe (one lock around write+flush); timestamps are wall-clock epoch
seconds so lines correlate with external logs. Multi-host: configure the sink
on process 0 only (the helpers never check — the caller owns that policy,
mirroring ``MetricsLogger``).
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Any, Dict, Iterator, Optional

__all__ = ["EventLog", "configure_event_log", "event", "get_event_log", "span"]


class EventLog:
    """Append-only JSONL event sink."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._f = open(path, "a")
        self._write_error_reported = False

    def write(self, record: Dict[str, Any]) -> None:
        line = json.dumps({"t": time.time(), **record}, default=str)
        with self._lock:
            if self._f is None:
                return
            try:
                self._f.write(line + "\n")
                self._f.flush()
            except OSError as e:
                # telemetry must never crash the loop it observes (events
                # are emitted from the engine worker / trainer hot paths);
                # a full disk degrades the log, reported once
                if not self._write_error_reported:
                    self._write_error_reported = True
                    import sys

                    print(f"[obs] event log write failed ({e}) — further "
                          f"events to {self.path!r} may be dropped",
                          file=sys.stderr)

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


_LOG: Optional[EventLog] = None
_LOG_LOCK = threading.Lock()


def configure_event_log(path: Optional[str]) -> Optional[EventLog]:
    """Install (or, with None, remove) the process-wide event sink."""
    global _LOG
    with _LOG_LOCK:
        if _LOG is not None:
            _LOG.close()
        _LOG = EventLog(path) if path else None
        return _LOG


def get_event_log() -> Optional[EventLog]:
    return _LOG


def event(name: str, **fields: Any) -> None:
    """Record one discrete event (no-op until a sink is configured)."""
    log = _LOG
    if log is not None:
        log.write({"event": name, **fields})


@contextlib.contextmanager
def span(name: str, **fields: Any) -> Iterator[None]:
    """Record a timed span as one event carrying ``dur_s`` (and ``ok=False``
    plus the error type when the body raises)."""
    if _LOG is None:  # stay free when unconfigured
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    except BaseException as e:
        event(name, dur_s=round(time.perf_counter() - t0, 6), ok=False,
              error=type(e).__name__, **fields)
        raise
    event(name, dur_s=round(time.perf_counter() - t0, 6), ok=True, **fields)
