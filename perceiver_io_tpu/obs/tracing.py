"""Lightweight span/event tracing to JSONL.

The narrative channel next to the registry's numeric one: discrete runtime
happenings (a bucket program compiled, a warmup finished, a heartbeat
stalled) append one JSON object per line to a configured file. Unconfigured,
``event``/``span`` are near-free no-ops — library code calls them
unconditionally and only entry points opt into a sink.

Thread-safe (one lock around write+flush); timestamps are wall-clock epoch
seconds so lines correlate with external logs. Multi-host: configure the sink
on process 0 only (the helpers never check — the caller owns that policy,
mirroring ``MetricsLogger``).

Bounded by construction: the sink rotates at ``max_bytes`` (keeping
``backups`` numbered segments, newest first: ``events.jsonl.1`` is the most
recent full segment) so a week of serving — or an open-loop load sweep
emitting one span per request — can never grow the log unboundedly. Pass
``max_bytes=None`` to disable rotation (the pre-r11 behavior).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, Iterator, Optional

__all__ = ["EventLog", "configure_event_log", "event", "get_event_log", "span"]

# rotation defaults: ~64 MB live segment + 3 rotated = a ~256 MB hard ceiling
# per process, weeks of serving events at typical rates
DEFAULT_MAX_BYTES = 64 * 1024 * 1024
DEFAULT_BACKUPS = 3


class EventLog:
    """Append-only JSONL event sink with size-capped rotation."""

    def __init__(self, path: str, max_bytes: Optional[int] = DEFAULT_MAX_BYTES,
                 backups: int = DEFAULT_BACKUPS):
        if max_bytes is not None and max_bytes < 1:
            raise ValueError(f"max_bytes must be positive, got {max_bytes}")
        if backups < 0:
            raise ValueError(f"backups must be >= 0, got {backups}")
        self.path = path
        self.max_bytes = max_bytes
        self.backups = backups
        self._lock = threading.Lock()
        self._f = open(path, "a")
        self._size = self._f.tell()  # append mode: tell() is the file size
        self._closed = False
        self._write_error_reported = False

    def write(self, record: Dict[str, Any]) -> None:
        line = json.dumps({"t": time.time(), **record}, default=str) + "\n"
        with self._lock:
            if self._f is None:
                if self._closed:
                    return
                # a FAILED rotation left the log fileless (not closed):
                # retry the reopen so a transient disk condition degrades
                # the log only while it lasts, symmetric with plain write
                # failures which also self-recover
                try:
                    self._f = open(self.path, "a")
                    self._size = self._f.tell()
                except OSError:
                    return
            try:
                if (self.max_bytes is not None
                        and self._size + len(line) > self.max_bytes
                        and self._size > 0):
                    self._rotate_locked()
                self._f.write(line)
                self._f.flush()
                self._size += len(line)
            except OSError as e:
                # telemetry must never crash the loop it observes (events
                # are emitted from the engine worker / trainer hot paths);
                # a full disk degrades the log, reported once
                if not self._write_error_reported:
                    self._write_error_reported = True
                    import sys

                    print(f"[obs] event log write failed ({e}) — further "
                          f"events to {self.path!r} may be dropped",
                          file=sys.stderr)

    def _rotate_locked(self) -> None:
        """Shift ``path.(N-1)`` → ``path.N`` … ``path`` → ``path.1`` and
        reopen a fresh live segment. With ``backups == 0`` the live segment
        is simply truncated (still bounded)."""
        self._f.close()
        self._f = None  # a failure below leaves the log closed, not torn
        if self.backups > 0:
            oldest = f"{self.path}.{self.backups}"
            if os.path.exists(oldest):
                os.remove(oldest)
            for i in range(self.backups - 1, 0, -1):
                src = f"{self.path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{self.path}.{i + 1}")
            os.replace(self.path, f"{self.path}.1")
        else:
            os.remove(self.path)
        self._f = open(self.path, "a")
        self._size = 0

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._f is not None:
                self._f.close()
                self._f = None


_LOG: Optional[EventLog] = None
_LOG_LOCK = threading.Lock()


def configure_event_log(path: Optional[str],
                        max_bytes: Optional[int] = DEFAULT_MAX_BYTES,
                        backups: int = DEFAULT_BACKUPS) -> Optional[EventLog]:
    """Install (or, with None, remove) the process-wide event sink."""
    global _LOG
    with _LOG_LOCK:
        if _LOG is not None:
            _LOG.close()
        _LOG = EventLog(path, max_bytes=max_bytes, backups=backups) \
            if path else None
        return _LOG


def get_event_log() -> Optional[EventLog]:
    return _LOG


def event(name: str, **fields: Any) -> None:
    """Record one discrete event (no-op until a sink is configured)."""
    log = _LOG
    if log is not None:
        log.write({"event": name, **fields})


@contextlib.contextmanager
def span(name: str, **fields: Any) -> Iterator[None]:
    """Record a timed span as one event carrying ``dur_s`` (and ``ok=False``
    plus the error type when the body raises)."""
    if _LOG is None:  # stay free when unconfigured
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    except BaseException as e:
        event(name, dur_s=round(time.perf_counter() - t0, 6), ok=False,
              error=type(e).__name__, **fields)
        raise
    event(name, dur_s=round(time.perf_counter() - t0, 6), ok=True, **fields)
