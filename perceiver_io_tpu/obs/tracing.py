"""Lightweight span/event tracing to JSONL.

The narrative channel next to the registry's numeric one: discrete runtime
happenings (a bucket program compiled, a warmup finished, a heartbeat
stalled) append one JSON object per line to a configured file. Unconfigured,
``event``/``span`` are near-free no-ops — library code calls them
unconditionally and only entry points opt into a sink.

Every record carries DUAL clock stamps plus the writer's pid: ``t``
(wall-clock epoch seconds — external log correlation and cross-process
alignment anchoring) and ``mono`` (the process's monotonic clock — the only
clock durations may be computed from, PIT-CLOCK). The pair is what lets
``obs.reqtrace.assemble_traces`` anchor one process's monotonic span stamps
against another's: per process, the median ``t − mono`` offset maps
monotonic onto the shared wall timeline. Multi-host: configure the sink on
process 0 only (the helpers never check — the caller owns that policy,
mirroring ``MetricsLogger``).

Writes are ASYNCHRONOUS (r15): ``write()`` stamps the clocks and enqueues;
a writer thread serializes, rotates, and flushes off the caller's path —
per-request span emission costs the producer ~2 µs instead of a ~25 µs
serialize+write+flush (the measured difference between tracing overhead
above and below the 2% acceptance bar at CPU serving rates). The bounded
queue DROPS (counted, reported once) rather than blocks when the writer
falls behind — telemetry must never stall the loop it observes. ``close()``
(and ``configure_event_log(None)``) drains the queue before closing, so the
every-record-visible-after-close contract the tests and the serve CLI's
drain path rely on still holds.

Bounded by construction: the sink rotates at ``max_bytes`` (keeping
``backups`` numbered segments, newest first: ``events.jsonl.1`` is the most
recent full segment) so a week of serving — or an open-loop load sweep
emitting one span per request — can never grow the log unboundedly. Pass
``max_bytes=None`` to disable rotation (the pre-r11 behavior).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, Iterator, Optional

__all__ = ["EventLog", "configure_event_log", "event", "get_event_log", "span"]

# rotation defaults: ~64 MB live segment + 3 rotated = a ~256 MB hard ceiling
# per process, weeks of serving events at typical rates
DEFAULT_MAX_BYTES = 64 * 1024 * 1024
DEFAULT_BACKUPS = 3

# producer-side bound: at the measured ~25 µs/record drain rate this absorbs
# multi-second bursts; past it, records drop (counted) rather than block
DEFAULT_QUEUE_DEPTH = 8192


class EventLog:
    """Append-only JSONL event sink with size-capped rotation and an
    asynchronous writer thread (producers enqueue; serialization, rotation,
    and flushing happen off the hot path)."""

    def __init__(self, path: str, max_bytes: Optional[int] = DEFAULT_MAX_BYTES,
                 backups: int = DEFAULT_BACKUPS,
                 queue_depth: int = DEFAULT_QUEUE_DEPTH,
                 registry=None):
        if max_bytes is not None and max_bytes < 1:
            raise ValueError(f"max_bytes must be positive, got {max_bytes}")
        if backups < 0:
            raise ValueError(f"backups must be >= 0, got {backups}")
        self.path = path
        self.max_bytes = max_bytes
        self.backups = backups
        self._pid = os.getpid()  # per-record process label (trace assembly
        # merges logs from many processes; pid keys the clock alignment)
        self._lock = threading.Lock()
        self._f = open(path, "a")
        self._size = self._f.tell()  # append mode: tell() is the file size
        self._closed = False
        self._write_error_reported = False
        self._drop_reported = False
        self.dropped = 0  # records the full buffer refused (never blocks)
        self._depth = max(1, int(queue_depth))
        # a plain deque, NOT queue.Queue: append is GIL-atomic and lock-free
        # and — decisively — does not notify a condition variable per
        # record. Waking the writer thread per span put a context-switch +
        # GIL hand-off on every completion; polling amortizes it to zero
        # (measured: the difference between ~10% and <2% tracing overhead)
        self._buf: deque = deque()
        self._writing = False  # a popped batch is in flight to disk
        # record loss must be VISIBLE, not just counted on the object:
        # eventlog_dropped_total / eventlog_queue_depth ride /metrics (and
        # therefore the time-series + alerting layer) via a registry
        # collector, refreshed at every scrape. The collector holds only a
        # weakref and raises once the log is gone, which drops it from
        # subsequent exports (the registry's documented removal path).
        # ``registry`` lets an owner on a private registry (a Sampler's
        # series log) keep its drop signal sampleable by that owner.
        if registry is None:
            from perceiver_io_tpu.obs.registry import get_registry

            registry = get_registry()
        reg = registry
        labels = {"log": os.path.basename(path)}
        self._m_dropped = reg.counter(
            "eventlog_dropped_total",
            "records the bounded writer queue (or a write failure) refused",
            labels)
        self._m_queue = reg.gauge(
            "eventlog_queue_depth",
            "records buffered for the async writer", labels)
        self._dropped_synced = 0
        ref = weakref.ref(self)

        def _sync_collector():
            log = ref()
            if log is None or log._closed:
                raise LookupError("event log gone — drop this collector")
            log._sync_metrics()

        reg.register_collector(_sync_collector)
        self._stop = threading.Event()
        self._writer = threading.Thread(
            target=self._drain_loop, name="event-log-writer", daemon=True)
        self._writer.start()

    def _sync_metrics(self) -> None:
        """Publish drop/queue state into the registry instruments (counter
        semantics: only the delta since the last sync increments, so many
        EventLog lifetimes sharing one instrument aggregate correctly)."""
        d = self.dropped
        if d > self._dropped_synced:
            self._m_dropped.inc(d - self._dropped_synced)
            self._dropped_synced = d
        self._m_queue.set(len(self._buf))

    def write(self, record: Dict[str, Any]) -> None:
        """Buffer one record (~2 µs, no lock, no thread wakeup). Clock
        stamps are captured HERE — the record's times are submission times,
        however far behind the writer runs. A full buffer drops the record
        (counted, reported once): telemetry must never stall the loop it
        observes."""
        if self._closed:
            return
        if len(self._buf) >= self._depth:  # racy read: the bound is soft
            self.dropped += 1
            if not self._drop_reported:
                self._drop_reported = True
                import sys

                print(f"[obs] event log buffer full — dropping records "
                      f"(writer behind on {self.path!r}; drops are counted "
                      f"on EventLog.dropped)", file=sys.stderr)
            return
        # dual stamps: wall for correlation/alignment anchoring, monotonic
        # for durations (PIT-CLOCK — never subtract wall clocks)
        self._buf.append(
            {"t": time.time(), "mono": time.monotonic(),
             "pid": self._pid, **record})

    def _drain_loop(self) -> None:
        """Writer thread: poll → drain the buffer in batches → ONE write +
        flush per batch (a flush-per-record writer measurably steals
        serving throughput through the GIL). Exits once stopped AND
        drained, so ``close()`` sees every record accepted before the stop
        on disk."""
        while True:
            if not self._buf:
                if self._stop.wait(0.02):
                    if not self._buf:
                        return
                continue
            # flagged BEFORE popping: flush() must not observe an empty
            # deque while a popped batch is still unwritten
            self._writing = True
            batch = []
            while len(batch) < 512:
                try:
                    batch.append(self._buf.popleft())
                except IndexError:
                    break
            try:
                self._write_batch(batch)
            except Exception as e:  # the writer thread is immortal: any
                # surprise drops the batch (counted), never the sink
                self.dropped += len(batch)
                if not self._write_error_reported:
                    self._write_error_reported = True
                    import sys

                    print(f"[obs] event log writer error "
                          f"({type(e).__name__}: {e}) — batch dropped",
                          file=sys.stderr)
            finally:
                self._writing = False

    def _write_batch(self, records) -> None:
        """Serialize and land a batch: rotation is checked per record (the
        size cap stays exact), but the flush is per batch."""
        with self._lock:
            for record in records:
                self._write_one_locked(record, flush=False)
            if self._f is not None:
                try:
                    self._f.flush()
                except OSError:
                    pass  # the per-record handler already reported

    def _write_line(self, record: Dict[str, Any]) -> None:
        with self._lock:
            self._write_one_locked(record, flush=True)

    def _write_one_locked(self, record: Dict[str, Any],
                          flush: bool) -> None:
        try:
            line = json.dumps(record, default=str) + "\n"
        except (TypeError, ValueError) as e:
            # default=str does not cover every shape (non-scalar dict
            # keys, circular refs); one bad record must DROP, not kill
            # the writer thread and silently end all event logging
            self.dropped += 1
            if not self._write_error_reported:
                self._write_error_reported = True
                import sys

                print(f"[obs] event log record not serializable ({e}) — "
                      f"dropped (counted on EventLog.dropped)",
                      file=sys.stderr)
            return
        if self._f is None:
            if self._closed:
                return
            # a FAILED rotation left the log fileless (not closed):
            # retry the reopen so a transient disk condition degrades
            # the log only while it lasts, symmetric with plain write
            # failures which also self-recover
            try:
                self._f = open(self.path, "a")
                self._size = self._f.tell()
            except OSError:
                return
        try:
            if (self.max_bytes is not None
                    and self._size + len(line) > self.max_bytes
                    and self._size > 0):
                self._rotate_locked()
            self._f.write(line)
            if flush:
                self._f.flush()
            self._size += len(line)
        except OSError as e:
                # telemetry must never crash the loop it observes (events
                # are emitted from the engine worker / trainer hot paths);
                # a full disk degrades the log, reported once
                if not self._write_error_reported:
                    self._write_error_reported = True
                    import sys

                    print(f"[obs] event log write failed ({e}) — further "
                          f"events to {self.path!r} may be dropped",
                          file=sys.stderr)

    def flush(self, timeout_s: float = 10.0) -> bool:
        """Block until every record buffered so far is on disk (bounded).
        Returns False if the writer did not catch up in time."""
        deadline = time.monotonic() + timeout_s
        while self._buf or self._writing:
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.002)
        return True

    def _rotate_locked(self) -> None:
        """Shift ``path.(N-1)`` → ``path.N`` … ``path`` → ``path.1`` and
        reopen a fresh live segment. With ``backups == 0`` the live segment
        is simply truncated (still bounded)."""
        self._f.close()
        self._f = None  # a failure below leaves the log closed, not torn
        if self.backups > 0:
            oldest = f"{self.path}.{self.backups}"
            if os.path.exists(oldest):
                os.remove(oldest)
            for i in range(self.backups - 1, 0, -1):
                src = f"{self.path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{self.path}.{i + 1}")
            os.replace(self.path, f"{self.path}.1")
        else:
            os.remove(self.path)
        self._f = open(self.path, "a")
        self._size = 0

    def close(self) -> None:
        """Stop accepting records, DRAIN the queue to disk, close the file —
        the flush half of the serve CLI's drain contract."""
        self._closed = True  # write() refuses new records from here on
        self._stop.set()
        self._writer.join(timeout=10.0)
        # a writer wedged past the join bound is abandoned (daemon); any
        # records it left behind are drained synchronously so close() keeps
        # its everything-accepted-is-on-disk promise
        while True:
            try:
                self._write_line(self._buf.popleft())
            except IndexError:
                break
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None
        # the collector stops reporting for a closed log — push the final
        # drop tally and zero the queue gauge while we still can
        self._sync_metrics()
        self._m_queue.set(0)


_LOG: Optional[EventLog] = None
_LOG_LOCK = threading.Lock()


def configure_event_log(path: Optional[str],
                        max_bytes: Optional[int] = DEFAULT_MAX_BYTES,
                        backups: int = DEFAULT_BACKUPS) -> Optional[EventLog]:
    """Install (or, with None, remove) the process-wide event sink."""
    global _LOG
    with _LOG_LOCK:
        if _LOG is not None:
            _LOG.close()
        _LOG = EventLog(path, max_bytes=max_bytes, backups=backups) \
            if path else None
        return _LOG


def get_event_log() -> Optional[EventLog]:
    return _LOG


def event(name: str, **fields: Any) -> None:
    """Record one discrete event (no-op until a sink is configured)."""
    log = _LOG
    if log is not None:
        log.write({"event": name, **fields})


@contextlib.contextmanager
def span(name: str, **fields: Any) -> Iterator[None]:
    """Record a timed span as one event carrying ``dur_s`` (and ``ok=False``
    plus the error type when the body raises)."""
    if _LOG is None:  # stay free when unconfigured
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    except BaseException as e:
        event(name, dur_s=round(time.perf_counter() - t0, 6), ok=False,
              error=type(e).__name__, **fields)
        raise
    event(name, dur_s=round(time.perf_counter() - t0, 6), ok=True, **fields)
