"""Declarative alerting over the metrics time-series.

:class:`AlertRule` declares a condition over a :class:`SeriesStore` window —
threshold (windowed aggregate vs a bound), rate-of-change, or absence (the
series stopped arriving) — plus the two pieces that keep a flapping gauge
from flapping the alert:

- ``for_s`` **hold-down**: the condition must hold continuously this long
  before the alert fires (a one-sample spike never pages);
- **hysteresis**: once firing, the alert resolves only after the signal has
  stayed on the *resolve* side — ``resolve_threshold``, which for a ``>``
  rule sits at or below the firing threshold — continuously for
  ``resolve_for_s``. A gauge oscillating between the two thresholds keeps
  the alert FIRING (one page, not a page storm).

:class:`AlertEngine` evaluates the rules (``evaluate()`` directly, or on a
cadence thread via ``start()``), and on every transition:

- emits ``alert_firing``/``alert_resolved`` events into the EventLog,
  trace-linked: when the rule's base metric is a histogram carrying r15
  exemplars, the firing event lists the exemplar trace ids (``"p99 is
  burning" → the assembled traces that burned it``);
- exports ``alert_state{rule=}`` gauges (1 = firing) plus fired/resolved
  counters;
- serves as a ``healthz()`` source: a firing ``page``-severity alert
  degrades ``/healthz`` through the same aggregation as a stalled
  heartbeat, an open breaker, or a burning SLO (``warn`` alerts ride the
  detail body only).

Metric-name literals in ``AlertRule(metric=...)`` are statically resolved
against the registry's known instrument names by pitlint's PIT-METRIC rule —
a typo'd rule fails lint instead of silently never firing. Rules loaded at
runtime (``load_rules``) get the dynamic complement: the engine's health
detail reports rules whose metric has never matched a series.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from perceiver_io_tpu.obs import health as _health
from perceiver_io_tpu.obs import tracing as _tracing
from perceiver_io_tpu.obs.registry import MetricsRegistry, get_registry
from perceiver_io_tpu.obs.timeseries import SeriesStore, split_series_key

__all__ = ["AlertEngine", "AlertRule", "load_rules"]

KINDS = ("threshold", "rate", "absence")
OPS = (">", ">=", "<", "<=")
SEVERITIES = ("page", "warn")
AGGS = ("last", "mean", "max", "min")


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One declarative alert.

    ``metric`` is a series key (``series_key()`` form). A bare instrument
    name (no ``{label}`` suffix) matches EVERY label set of that instrument
    — one rule alerts per replica / per engine, each labeled series with
    its own independent fire/resolve state.

    Kinds: ``threshold`` compares the ``agg`` of the last ``window_s`` of
    samples against ``threshold`` with ``op``; ``rate`` compares the
    per-second rate of change over the window (counter-reset-aware);
    ``absence`` breaches when the series has no sample within ``window_s``
    (threshold/op ignored). A threshold/rate rule with NO in-window data
    does not breach — silence is absence's job, not a phantom breach.
    """

    name: str
    metric: str
    kind: str = "threshold"
    op: str = ">"
    threshold: float = 0.0
    window_s: float = 30.0
    agg: str = "last"
    for_s: float = 0.0
    resolve_threshold: Optional[float] = None
    resolve_for_s: Optional[float] = None
    severity: str = "page"
    description: str = ""

    def __post_init__(self):
        if not self.name:
            raise ValueError("alert rule needs a name")
        if not self.metric:
            raise ValueError(f"rule {self.name!r}: metric is required")
        if self.kind not in KINDS:
            raise ValueError(
                f"rule {self.name!r}: kind {self.kind!r} not in {KINDS}")
        if self.op not in OPS:
            raise ValueError(
                f"rule {self.name!r}: op {self.op!r} not in {OPS}")
        if self.agg not in AGGS:
            raise ValueError(
                f"rule {self.name!r}: agg {self.agg!r} not in {AGGS}")
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"rule {self.name!r}: severity {self.severity!r} "
                f"not in {SEVERITIES}")
        if self.window_s <= 0:
            raise ValueError(f"rule {self.name!r}: window_s must be positive")
        if self.for_s < 0 or (self.resolve_for_s is not None
                              and self.resolve_for_s < 0):
            raise ValueError(f"rule {self.name!r}: hold-downs must be >= 0")
        if self.resolve_threshold is not None:
            # hysteresis must open AGAINST the firing direction, or the
            # resolve condition would be stricter than not-firing and the
            # alert could resolve while still past the firing threshold
            widens = (self.resolve_threshold <= self.threshold
                      if self.op in (">", ">=")
                      else self.resolve_threshold >= self.threshold)
            if not widens:
                raise ValueError(
                    f"rule {self.name!r}: resolve_threshold "
                    f"{self.resolve_threshold} must sit on the resolved side "
                    f"of threshold {self.threshold} for op {self.op!r}")

    @property
    def effective_resolve_threshold(self) -> float:
        return (self.threshold if self.resolve_threshold is None
                else self.resolve_threshold)

    @property
    def effective_resolve_for_s(self) -> float:
        return self.for_s if self.resolve_for_s is None else self.resolve_for_s


def _cmp(value: float, op: str, bound: float) -> bool:
    if op == ">":
        return value > bound
    if op == ">=":
        return value >= bound
    if op == "<":
        return value < bound
    return value <= bound


def load_rules(path: str) -> List[AlertRule]:
    """Rules from a JSON file: a list of rule objects, or ``{"rules":
    [...]}``. Unknown fields are rejected loudly — a misspelled
    ``for_s`` must not silently become a no-hold-down rule."""
    with open(path) as f:
        body = json.load(f)
    if isinstance(body, dict):
        if "rules" not in body:
            raise ValueError(
                f"{path}: dict form needs a 'rules' key (found "
                f"{sorted(body)}) — a top-level typo must not silently "
                f"disable all alerting")
        body = body["rules"]
    if not isinstance(body, list):
        raise ValueError(f"{path}: expected a list of rules")
    if not body:
        raise ValueError(f"{path}: zero rules — an explicitly-passed "
                         f"rules file with nothing in it is a mistake")
    fields = {f.name for f in dataclasses.fields(AlertRule)}
    rules = []
    for i, entry in enumerate(body):
        unknown = set(entry) - fields
        if unknown:
            raise ValueError(
                f"{path}: rule #{i} has unknown fields {sorted(unknown)}")
        rules.append(AlertRule(**entry))
    names = [r.name for r in rules]
    if len(set(names)) != len(names):
        raise ValueError(f"{path}: duplicate rule names")
    return rules


class _InstanceState:
    __slots__ = ("firing", "bad_since", "ok_since", "value", "fired_at")

    def __init__(self):
        self.firing = False
        self.bad_since: Optional[float] = None
        self.ok_since: Optional[float] = None
        self.value: Optional[float] = None
        self.fired_at: Optional[float] = None


class AlertEngine:
    """Evaluates :class:`AlertRule`\\ s against one :class:`SeriesStore`.

    Call ``evaluate()`` per tick (or ``start()`` a cadence thread); each
    call returns the transitions it produced (``[{"rule", "metric",
    "action": "firing"|"resolved", "value"}]``). State is per (rule,
    matched series key), so one bare-name rule pages per replica.
    """

    # pitlint PIT-LOCK: instance states are written by the evaluation tick
    # and read by health probes / stats from other threads
    _guarded_by = {"_states": "_lock", "_never_matched": "_lock"}

    def __init__(self, store: SeriesStore, rules: Sequence[AlertRule],
                 registry: Optional[MetricsRegistry] = None,
                 interval_s: float = 1.0, name: str = "alerts"):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be positive, got {interval_s}")
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate alert rule names in {names}")
        self.store = store
        self.rules = list(rules)
        self.name = name
        self.interval_s = interval_s
        self.registry = registry if registry is not None else get_registry()
        self._lock = threading.Lock()
        # evaluate() is one logical tick: the cadence thread and explicit
        # final-tick callers (serve drain, load_bench teardown) must not
        # interleave inside a state machine or transitions double-emit
        self._eval_lock = threading.Lock()
        self._states: Dict[Tuple[str, str], _InstanceState] = {}
        self._never_matched: Dict[str, bool] = {r.name: True for r in rules}
        self._start_mono = time.monotonic()
        self._m_state = {
            r.name: self.registry.gauge(
                "alert_state", "1 = rule firing (any matched series)",
                {"rule": r.name})
            for r in self.rules
        }
        self._m_fired = {
            r.name: self.registry.counter(
                "alerts_fired_total", "rule transitions into firing",
                {"rule": r.name})
            for r in self.rules
        }
        self._m_resolved = {
            r.name: self.registry.counter(
                "alerts_resolved_total", "rule transitions out of firing",
                {"rule": r.name})
            for r in self.rules
        }
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._registered = True
        _health.register_health_source(self)

    # -- evaluation ----------------------------------------------------------

    def _instances(self, rule: AlertRule, now: float) -> List[str]:
        keys = self.store.match(rule.metric)
        if keys:
            with self._lock:
                self._never_matched[rule.name] = False
        elif rule.kind == "absence":
            # an absence rule's series may have NEVER arrived — that is
            # itself the alert, once the engine has watched a full window
            if now - self._start_mono >= rule.window_s:
                keys = [rule.metric]
        return keys

    def _signal(self, rule: AlertRule, key: str,
                now: float) -> Tuple[Optional[float], Optional[bool], bool]:
        """``(value, breached, resolvable)`` for one instance; breached None
        = no data (state holds). ``resolvable`` carries the hysteresis-side
        verdict for a currently-firing instance."""
        if rule.kind == "absence":
            age = self.store.age_s(key, now=now)
            value = age if age is not None else float("inf")
            breached = value > rule.window_s
            return value, breached, not breached
        if rule.kind == "rate":
            value = self.store.rate(key, rule.window_s, now=now)
        else:
            value = self.store.window_agg(key, rule.window_s, rule.agg,
                                          now=now)
        if value is None:
            return None, None, False
        breached = _cmp(value, rule.op, rule.threshold)
        resolvable = not _cmp(value, rule.op,
                              rule.effective_resolve_threshold)
        return value, breached, resolvable

    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One evaluation tick over every rule × matched series; returns
        the transitions. ``now`` (monotonic) is injectable for tests.
        Serialized: a caller's explicit tick and the cadence thread never
        interleave inside a state machine."""
        with self._eval_lock:
            return self._evaluate_locked(
                time.monotonic() if now is None else now)

    def _evaluate_locked(self, now: float) -> List[Dict[str, Any]]:
        transitions: List[Dict[str, Any]] = []
        for rule in self.rules:
            any_firing = False
            keys = self._instances(rule, now)
            # a PHANTOM absence instance (keyed by the rule's bare metric,
            # minted while NOTHING matched) must resolve once real labeled
            # series arrive — match() will never return it again, so
            # without this sweep it would page forever
            if rule.kind == "absence" and keys and rule.metric not in keys:
                with self._lock:
                    st = self._states.get((rule.name, rule.metric))
                if st is not None and st.firing:
                    st.firing = False
                    st.bad_since = None
                    st.fired_at = None
                    self._m_resolved[rule.name].inc()
                    transitions.append(self._transition(
                        rule, rule.metric, "resolved", None))
            for key in keys:
                with self._lock:
                    st = self._states.get((rule.name, key))
                    if st is None:
                        st = self._states[(rule.name, key)] = _InstanceState()
                value, breached, resolvable = self._signal(rule, key, now)
                st.value = value
                if breached is None:
                    any_firing = any_firing or st.firing
                    continue
                if not st.firing:
                    if breached:
                        if st.bad_since is None:
                            st.bad_since = now
                        if now - st.bad_since >= rule.for_s:
                            st.firing = True
                            st.fired_at = now
                            st.ok_since = None
                            self._m_fired[rule.name].inc()
                            transitions.append(
                                self._transition(rule, key, "firing", value))
                    else:
                        st.bad_since = None
                else:
                    if resolvable:
                        if st.ok_since is None:
                            st.ok_since = now
                        if (now - st.ok_since
                                >= rule.effective_resolve_for_s):
                            st.firing = False
                            st.bad_since = None
                            st.fired_at = None
                            self._m_resolved[rule.name].inc()
                            transitions.append(self._transition(
                                rule, key, "resolved", value))
                    else:
                        st.ok_since = None
                any_firing = any_firing or st.firing
            self._m_state[rule.name].set(1.0 if any_firing else 0.0)
        return transitions

    def _transition(self, rule: AlertRule, key: str, action: str,
                    value: Optional[float]) -> Dict[str, Any]:
        rec: Dict[str, Any] = {
            "rule": rule.name, "metric": key, "action": action,
            "value": None if value is None else round(float(value), 6),
            "threshold": (rule.threshold if action == "firing"
                          else rule.effective_resolve_threshold),
            "severity": rule.severity,
        }
        if action == "firing":
            exemplars = self._exemplar_traces(key)
            if exemplars:
                rec["trace_exemplars"] = exemplars
        _tracing.event(f"alert_{action}", engine=self.name,
                       **{k: v for k, v in rec.items() if k != "action"})
        return rec

    def _exemplar_traces(self, key: str) -> List[str]:
        """Trace ids from the underlying histogram's exemplar ring, when the
        alerted metric derives from one — the firing event links straight
        to the assembled traces that breached it."""
        name, label_suffix, field = split_series_key(key)
        if not field or field == "count":
            return []
        from perceiver_io_tpu.obs.registry import Histogram

        inst = self.registry.instruments_by_key().get(name + label_suffix)
        if not isinstance(inst, Histogram):
            return []
        return [e["trace"] for e in inst.exemplars()[:4]]

    # -- introspection -------------------------------------------------------

    def firing(self) -> Dict[str, List[str]]:
        """``{rule_name: [series keys currently firing]}``."""
        with self._lock:
            out: Dict[str, List[str]] = {}
            for (rule, key), st in self._states.items():
                if st.firing:
                    out.setdefault(rule, []).append(key)
        return {r: sorted(ks) for r, ks in sorted(out.items())}

    def stats(self) -> Dict[str, Any]:
        return {
            "rules": len(self.rules),
            "fired": int(sum(c.value for c in self._m_fired.values())),
            "resolved": int(
                sum(c.value for c in self._m_resolved.values())),
            "firing": self.firing(),
        }

    # -- healthz() source ----------------------------------------------------

    def health_status(self) -> Tuple[str, bool, Dict[str, Any]]:
        firing = self.firing()
        by_sev = {r.name: r.severity for r in self.rules}
        paging = sorted(r for r in firing if by_sev.get(r) == "page")
        with self._lock:
            never = sorted(r for r, nm in self._never_matched.items() if nm)
        detail: Dict[str, Any] = {
            "rules": len(self.rules),
            "firing": firing,
            "paging": paging,
        }
        if never:
            # a rule whose metric never matched any series is not wrong by
            # itself (the instrument may not have produced yet) but is the
            # runtime shadow of what PIT-METRIC checks statically — surface it
            detail["never_matched"] = never
        return f"alerts:{self.name}", not paging, detail

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "AlertEngine":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name=f"{self.name}-alerts", daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate()
            except Exception:
                pass  # telemetry must never kill its own thread

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._registered:
            _health.unregister_health_source(self)
            self._registered = False

    def __enter__(self) -> "AlertEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
