"""Bounded-exit failure detection for multi-host training.

A dead or wedged peer turns every subsequent collective into a trap: the
survivors' next train dispatch simply never completes, and a pod burns its
allocation doing nothing until a human notices (SURVEY.md §5; the r12
serving fabric already solves this for replicas — this is the training-side
twin). Nothing can *unblock* a host stuck inside a collective, so the only
sane contract is **bounded exit**: detect the dead peer within a configured
window, dump diagnostics, and leave with a *transient* exit code so the
restart-the-world supervisor (``cli/common.py maybe_spawn_hosts``) relaunches
the whole job from the newest checkpoint.

Two detectors, complementary by construction:

- :class:`PeerLivenessMonitor` — a host-side heartbeat over the
  ``jax.distributed`` coordinator KV store (the one cross-host channel that
  does NOT ride device collectives, so it keeps working while the main
  thread is stuck in one). Every host publishes a beat counter; every host
  watches every peer's counter through an :class:`~perceiver_io_tpu.obs
  .health.Heartbeat` (deadline-monitored, healthz-aggregated, stall-dumping
  — the serving loops' liveness primitive, reused verbatim). A peer whose
  counter stops advancing for ``deadline_s`` is declared down once:
  ``multihost_peer_down_total`` increments and ``on_peer_down`` fires —
  by default :func:`abort_transient`.
- :class:`StepDeadline` — a per-step deadline on the training loop itself
  (arm before the dispatch, beat at the completion the host observes): the
  wedged-collective detector for failure modes the KV channel cannot see
  (a peer that still heartbeats but whose device wedged — the axon-tunnel
  signature from CLAUDE.md).

Exit discipline: :func:`abort_transient` leaves with ``EXIT_TRANSIENT``
(75, ``EX_TEMPFAIL``) via ``os._exit`` — a daemon thread cannot raise into
a main thread that is blocked in a collective, and a ``sys.exit`` there
would be swallowed. The supervisor treats any child death as
restart-the-world; the dedicated code makes the *reason* legible in logs
and drills. The KV error taxonomy rides ``resilience.retry.classify_error``:
transient KV hiccups are tolerated (counted, retried next beat), but a
persistently failing KV store means the coordinator itself is gone — a
peer-down event in its own right.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from perceiver_io_tpu.resilience import faults
from perceiver_io_tpu.resilience.retry import is_transient

# EX_TEMPFAIL: the bounded-exit code — "transient failure, retry the world".
# The supervisor restarts on ANY nonzero child exit; this code exists so a
# bounded-exit abort is distinguishable from a crash in logs and drills.
EXIT_TRANSIENT = 75

_KV_PREFIX = "pit_hb"


def abort_transient(reason: str, exit_code: int = EXIT_TRANSIENT) -> None:
    """Leave the process NOW with a transient exit code.

    ``os._exit`` on purpose: this runs on a monitor thread while the main
    thread is (by hypothesis) stuck inside a dead collective — no exception
    can reach it, no atexit hook involving jax/device state can be trusted
    to return. Checkpoints are the recovery source, not a graceful unwind.
    """
    print(f"[multihost] bounded exit ({exit_code}): {reason}",
          file=sys.stderr)
    sys.stderr.flush()
    os._exit(exit_code)


class InMemoryKV:
    """Dict-backed stand-in for the coordinator KV store (tests, and
    single-process dry runs of the monitor). Thread-safe like the real one."""

    _guarded_by = {"_data": "_lock"}

    def __init__(self):
        self._lock = threading.Lock()
        self._data: Dict[str, str] = {}

    def key_value_set(self, key: str, value: str,
                      allow_overwrite: bool = False) -> None:
        with self._lock:
            if not allow_overwrite and key in self._data:
                raise ValueError(f"key {key!r} already set")
            self._data[key] = value

    def key_value_dir_get(self, key: str) -> List[Tuple[str, str]]:
        with self._lock:
            return [(k, v) for k, v in sorted(self._data.items())
                    if k.startswith(key)]


def distributed_kv_client():
    """The live ``jax.distributed`` coordinator KV client, or None when no
    distributed runtime is up (single-process runs)."""
    from jax._src import distributed

    return distributed.global_state.client


class PeerLivenessMonitor:
    """Cross-host liveness over the coordinator KV store.

    Each host runs one monitor: a daemon thread publishes this host's beat
    counter every ``interval_s`` and scans every peer's counter. Peer
    liveness state is held by one :class:`obs.health.Heartbeat` per peer
    (``deadline_s`` stale → stalled), so ``healthz()`` aggregates peer
    health for free and a stall produces the standard diagnostic dump. The
    first stall of a peer fires ``on_peer_down(peer_id)`` exactly once and
    bumps ``multihost_peer_down_total``.

    ``kv`` defaults to the live ``jax.distributed`` client; tests pass an
    :class:`InMemoryKV` shared between two monitors. Constructing without
    any KV store raises — a monitor that silently watches nothing is worse
    than none.
    """

    _guarded_by = {"_down": "_lock", "_last_seen": "_lock",
                   "_kv_failures": "_lock"}

    def __init__(
        self,
        process_id: Optional[int] = None,
        num_processes: Optional[int] = None,
        kv=None,
        interval_s: float = 1.0,
        deadline_s: Optional[float] = None,
        on_peer_down: Optional[Callable[[int], None]] = None,
        kv_failure_limit: int = 5,
        namespace: str = _KV_PREFIX,
    ):
        import jax

        import perceiver_io_tpu.obs as obs

        if kv is None:
            kv = distributed_kv_client()
        if kv is None:
            raise ValueError(
                "PeerLivenessMonitor needs a KV store: initialize "
                "jax.distributed first, or pass kv= explicitly"
            )
        self._kv = kv
        self._pid = (jax.process_index() if process_id is None
                     else int(process_id))
        self._n = (jax.process_count() if num_processes is None
                   else int(num_processes))
        if interval_s <= 0:
            raise ValueError(f"interval_s must be positive, got {interval_s}")
        self._interval_s = float(interval_s)
        self._deadline_s = float(deadline_s if deadline_s is not None
                                 else 5.0 * interval_s)
        self._on_peer_down = on_peer_down or (lambda peer: abort_transient(
            f"peer {peer} unresponsive for >{self._deadline_s:.1f}s "
            f"(no KV heartbeat advance) — presumed dead; exiting before the "
            f"next collective wedges"))
        self._kv_failure_limit = int(kv_failure_limit)
        self._namespace = namespace
        self._counter = 0
        self._lock = threading.Lock()
        self._down: set = set()
        self._last_seen: Dict[int, str] = {}
        self._kv_failures = 0
        self._m_peer_down = obs.get_registry().counter(
            "multihost_peer_down_total",
            "peers declared dead by the KV liveness monitor")
        from perceiver_io_tpu.obs.health import Heartbeat

        # one deadline-monitored heartbeat per PEER; its stall hook fires
        # every monitor poll while stale, so _peer_down de-dupes under _lock
        self._peer_beats = {
            peer: Heartbeat(
                f"multihost_peer{peer}", deadline_s=self._deadline_s,
                on_stall=(lambda p=peer: self._peer_down(p)),
            )
            for peer in range(self._n) if peer != self._pid
        }
        self._closed = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "PeerLivenessMonitor":
        for hb in self._peer_beats.values():
            hb.arm()
        self._thread = threading.Thread(
            target=self._run, name=f"peer-liveness-p{self._pid}", daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._closed.set()
        for hb in self._peer_beats.values():
            hb.close()
        if self._thread is not None:
            self._thread.join(timeout=2 * self._interval_s + 1.0)

    def __enter__(self) -> "PeerLivenessMonitor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- elastic generation changes ------------------------------------------

    def set_peers(self, peer_ids) -> None:
        """Watch exactly ``peer_ids`` from now on (elastic world resize).

        A shrink/grow changes WHO counts as a peer without restarting the
        monitor: removed peers' heartbeats close and their down-verdicts
        clear (a rank that left the world on purpose — or whose death was
        already acted on — must not keep reading as a live failure), new
        peers get fresh armed heartbeats, and surviving peers keep their
        beat state uninterrupted. ``peer_ids`` may include this host's own
        id; it is ignored.
        """
        from perceiver_io_tpu.obs.health import Heartbeat

        wanted = {int(p) for p in peer_ids} - {self._pid}
        started = self._thread is not None
        stale = set(self._peer_beats) - wanted
        for peer in stale:
            self._peer_beats.pop(peer).close()
        with self._lock:
            self._down -= stale
            for peer in stale:
                self._last_seen.pop(peer, None)
        for peer in sorted(wanted - set(self._peer_beats)):
            hb = Heartbeat(
                f"multihost_peer{peer}", deadline_s=self._deadline_s,
                on_stall=(lambda p=peer: self._peer_down(p)),
            )
            self._peer_beats[peer] = hb
            if started:
                hb.arm()
        self._n = len(wanted) + 1

    # -- introspection (tests / healthz detail) ------------------------------

    def peers_down(self) -> Tuple[int, ...]:
        with self._lock:
            return tuple(sorted(self._down))

    def kv_failures(self) -> int:
        with self._lock:
            return self._kv_failures

    # -- the monitor thread --------------------------------------------------

    def _run(self) -> None:
        while not self._closed.wait(self._interval_s):
            self._beat_once()

    def _beat_once(self) -> None:
        """One publish + scan round (exposed for deterministic tests)."""
        import perceiver_io_tpu.obs as obs

        try:
            # chaos hook: hang = this host stops beating (peers mark it
            # down); transient = a KV write failing (tolerated, counted)
            faults.inject("multihost.heartbeat")
            self._counter += 1
            self._kv.key_value_set(
                f"{self._namespace}/{self._pid}", str(self._counter),
                allow_overwrite=True)
            entries = dict(self._kv.key_value_dir_get(self._namespace))
        except Exception as e:
            with self._lock:
                self._kv_failures += 1
                failures = self._kv_failures
            obs.event("multihost_kv_error", error=type(e).__name__,
                      transient=is_transient(e), consecutive=failures)
            if failures >= self._kv_failure_limit:
                # the KV store IS the coordinator: persistently unreachable
                # means rank 0's service is gone — a peer-down of its own
                self._peer_down(-1)
            return
        with self._lock:
            self._kv_failures = 0
        # snapshot: set_peers (elastic resize, main thread) mutates the dict
        for peer, hb in list(self._peer_beats.items()):
            value = entries.get(f"{self._namespace}/{peer}")
            with self._lock:
                advanced = (value is not None
                            and value != self._last_seen.get(peer))
                if advanced:
                    self._last_seen[peer] = value
            if advanced:
                hb.beat()

    def _peer_down(self, peer: int) -> None:
        with self._lock:
            if peer in self._down:
                return
            self._down.add(peer)
        self._m_peer_down.inc()
        import perceiver_io_tpu.obs as obs

        obs.event("multihost_peer_down", peer=peer,
                  deadline_s=self._deadline_s)
        self._on_peer_down(peer)


class StepDeadline:
    """Bounded-exit deadline on the training loop's dispatch cycle.

    ``arm()`` before the dispatch, ``beat()`` at the completion the host
    observes, ``disarm()`` around long legitimate pauses (eval, checkpoint
    save). If no beat lands within ``deadline_s`` the underlying
    :class:`obs.health.Heartbeat` stalls — diagnostics dump (every thread's
    stack: *where* is the collective stuck?) and ``on_expire`` fires once,
    by default :func:`abort_transient`. This is the guarantee the chaos
    drill pins: a surviving host never blocks longer than the configured
    window inside a dead collective.
    """

    _guarded_by = {"_expired": "_lock"}

    def __init__(self, name: str, deadline_s: float,
                 on_expire: Optional[Callable[[], None]] = None):
        from perceiver_io_tpu.obs.health import Heartbeat

        if deadline_s <= 0:
            raise ValueError(f"deadline_s must be positive, got {deadline_s}")
        self.deadline_s = float(deadline_s)
        self._on_expire = on_expire or (lambda: abort_transient(
            f"step deadline {deadline_s:.1f}s expired — dispatch presumed "
            f"wedged in a dead collective"))
        self._lock = threading.Lock()
        self._expired = False
        self._hb = Heartbeat(name, deadline_s=self.deadline_s,
                             on_stall=self._expire_once)
        self._armed_at: Optional[float] = None

    def arm(self) -> None:
        self._armed_at = time.monotonic()
        self._hb.arm()

    def beat(self) -> None:
        self._hb.beat()

    def disarm(self) -> None:
        self._hb.disarm()

    def close(self) -> None:
        self._hb.close()

    def _expire_once(self) -> None:
        with self._lock:
            if self._expired:
                return
            self._expired = True
        self._on_expire()
