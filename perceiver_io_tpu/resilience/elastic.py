"""Elastic multi-host training: shrink/grow the world without restarting it.

r19's answer to a dead peer is bounded exit + restart-the-world: every
survivor leaves with ``EXIT_TRANSIENT`` and the supervisor relaunches all N
processes from the newest checkpoint — measured at 10–11 s decision→resume
on the 2-process CPU sim, all of it process teardown, re-spawn, jax
re-init, and re-registration. This module keeps the survivors ALIVE
instead: on an agreed :class:`~perceiver_io_tpu.resilience.multihost
.PeerLivenessMonitor` verdict they stop at the step boundary they already
reached, demolish the device runtime in-process, rebuild the world at N−1,
re-shard data assignments, and continue from in-memory state — no process
relaunch. The same rebuild path admits a hot spare back to N. Measured on
the 4→3→4 CPU drill: decision→resume ≈1.7 s, grow ≈0.3–0.4 s.

Every mechanism below encodes a failure mode found during bring-up (the
probes are summarized in PERF.md §Elastic training); none is decorative:

- **Control plane sized for the pool.** The coordinator service is started
  for ``n_max`` (train world + spares) with heartbeats slowed to
  never-expire, and every process keeps ``shutdown_on_destruction=False``.
  The coordinator must outlive any single generation: it is the rendezvous
  and KV channel the resize itself rides. WHO is dead is decided by the
  fast KV-counter monitor (sub-second), never by the service's own
  liveness, which would take the whole job down with one verdict.
- **Socket fencing, not client teardown.** gloo has no timeout: a rank
  blocked in ``recv`` on a dead pair unblocks ONLY when the socket dies.
  The CpuClient cannot be freed in-process (live executables pin it), so
  :meth:`ElasticRuntime.fence` walks ``/proc/self/fd``, finds every TCP
  socket created AFTER control-plane bring-up, and ``shutdown(SHUT_RDWR)``
  s it — releasing wedged peers in milliseconds. LISTEN sockets are
  skipped (shutting one down wakes gloo's ``accept`` with ``EINVAL`` and
  aborts the process); so is the coordinator connection.
- **Generation rebuild.** ``reset_backend()`` (parallel/mesh.py) clears
  backends/caches/mesh registry; survivors rendezvous on per-generation KV
  keys; the generation leader deletes the stale PJRT topology/gloo keys so
  re-registration at the new size cannot collide with generation g−1; then
  ``adopt_world`` points ``jax.distributed.global_state`` at the new dense
  rank/size and a fresh mesh is built. Programs recompile against the new
  mesh (sub-second on CPU; a persistent compile cache absorbs it on TPU).
- **State carries over in host memory**, placed onto the new mesh with
  ``jax.make_array_from_process_local_data`` — never ``jax.device_put``,
  whose replicated placement is a hidden broadcast collective that wedges
  exactly like the one being recovered from. Elastic resume requires the
  fully-replicated state layout (``snapshot_is_complete``); ZeRO-sharded
  state degrades to restart-the-world.
- **Peer-redundant in-memory checkpoints.** Each host mirrors its state
  snapshot to a buddy (ring neighbor in the world descriptor) over a unix
  socket speaking the r22 length-prefixed frame + raw-array codec
  (``serving/transport.py``). The mirror's content digest
  (``utils/treepath.tree_digest`` — the r13 checkpoint-sidecar discipline)
  is computed BEFORE the ``multihost.buddy_send`` fault hook, so a
  corrupted mirror is rejected at restore, never trusted. Unix sockets are
  untouched by the TCP fence, so mirrors survive resizes.
- **Quorum floor.** Below ``quorum`` survivors (or with the coordinator
  host itself dead) elastic resume is off the table:
  :func:`~perceiver_io_tpu.resilience.multihost.abort_transient` degrades
  to r19 restart-the-world, which remains the backstop for every failure
  this module cannot absorb.

Fault sites (drilled in ``tests/test_multihost_recovery.py``):
``multihost.resize`` fires at the start of every shrink/grow attempt
(a fatal there = a survivor dying MID-RESIZE; the retry loop re-runs the
verdict and shrinks again at the next generation), ``multihost.buddy_send``
fires over the snapshot before framing (nan = a torn mirror the digest
check must reject), ``multihost.join`` fires on the spare's join edge.

Importing this module never initializes a jax backend.
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import tempfile
import threading
import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from perceiver_io_tpu.resilience import faults
from perceiver_io_tpu.resilience.multihost import (
    PeerLivenessMonitor,
    abort_transient,
)

_INVITE_KEY = "invite"


# -- control-plane plumbing ----------------------------------------------------


def _xe():
    from jax._src.lib import xla_extension as xe

    return xe


def _sock_fds() -> Dict[int, Tuple[Optional[int], Optional[str]]]:
    """fd → (remote_port, tcp_state_hex) for every TCP socket fd of this
    process, via /proc (inode join between net/tcp* and /proc/self/fd)."""
    inode_info: Dict[str, Tuple[int, str]] = {}
    for net in ("/proc/self/net/tcp", "/proc/self/net/tcp6"):
        try:
            with open(net) as f:
                next(f)
                for line in f:
                    parts = line.split()
                    inode_info[parts[9]] = (
                        int(parts[2].split(":")[1], 16), parts[3])
        except (OSError, StopIteration):
            pass
    out: Dict[int, Tuple[Optional[int], Optional[str]]] = {}
    try:
        fds = os.listdir("/proc/self/fd")
    except OSError:
        return out
    for fd in fds:
        try:
            tgt = os.readlink(f"/proc/self/fd/{fd}")
        except OSError:
            continue
        if tgt.startswith("socket:["):
            out[int(fd)] = inode_info.get(tgt[8:-1], (None, None))
    return out


def fetch_with_deadline(arr, deadline_s: float):
    """Fetch ``arr`` to host with a hard deadline, off-thread.

    Returns ``("ok", value)``, ``("err", exception)`` or ``("wedged",
    None)``. A fetch that rides a dead collective never returns — the
    daemon thread is abandoned (the fence then kills the socket it is
    blocked on) rather than joined forever.
    """
    box: Dict[str, Any] = {}

    def _fetch():
        try:
            box["v"] = np.asarray(arr)
        except Exception as e:  # noqa: BLE001 — verdict, not handling
            box["e"] = e

    t = threading.Thread(target=_fetch, daemon=True)
    t.start()
    t.join(deadline_s)
    if "v" in box:
        return "ok", box["v"]
    if "e" in box:
        return "err", box["e"]
    return "wedged", None


# -- elastic progress (the supervisor's rejoin-success probe) ------------------


def progress_path(root: str) -> str:
    """The per-job elastic progress file (leader-written, supervisor-read)."""
    return os.path.join(root, "elastic_progress.json")


def note_progress(path: str, *, generation: int, step: int,
                  world_size: int) -> None:
    """Record a clean step boundary (atomic tmp+rename). The supervisor's
    ``--elastic`` mode reads this to tell a SUCCESSFUL elastic rejoin from a
    crash loop: progress advancing past a launch resets the restart budget."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"generation": int(generation), "step": int(step),
                   "world_size": int(world_size),
                   "wall": time.time()}, f)
    os.replace(tmp, path)


def read_progress(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# -- the elastic runtime -------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    """Knobs for one elastic pool member. ``node_id`` is the STABLE pool
    identity (coordination node id, also the KV heartbeat id) — distinct
    from the dense per-generation rank a ``WorldDescriptor`` derives."""

    node_id: int
    n_max: int
    coordinator_address: str  # "host:port"; node 0 hosts the service
    quorum: int = 1
    namespace: str = "es"
    monitor_interval_s: float = 0.25
    monitor_deadline_s: float = 1.5
    fetch_deadline_s: float = 3.0
    sync_timeout_ms: int = 60_000
    resize_attempts: int = 3
    connect_timeout_s: int = 60

    def __post_init__(self):
        if not 0 <= self.node_id < self.n_max:
            raise ValueError(
                f"node_id {self.node_id} outside pool [0, {self.n_max})")
        if self.quorum < 1:
            raise ValueError(f"quorum must be >= 1, got {self.quorum}")

    @property
    def coordinator_port(self) -> int:
        return int(self.coordinator_address.rsplit(":", 1)[1])


class ElasticRuntime:
    """One pool member's handle on the elastic control plane.

    Lifecycle: :meth:`start` brings up the pool-sized coordinator
    connection, captures the socket baseline, and starts the peer monitor
    (verdict-recording, never process-killing — the RESIZE is the response
    to a death here, not bounded exit). The training loop then drives
    :meth:`adopt` / :meth:`rebuild` / :meth:`shrink_until_stable` /
    invite-based grow at step boundaries. Everything cross-host rides the
    coordinator KV store; nothing here dispatches a device collective.
    """

    def __init__(self, config: ElasticConfig,
                 on_peer_down: Optional[Callable[[int], None]] = None):
        self.cfg = config
        self.client = None
        self.monitor: Optional[PeerLivenessMonitor] = None
        self.world = None  # Optional[WorldDescriptor]
        self._service = None
        self._baseline: set = set()
        self._fenced: set = set()
        self._on_peer_down = on_peer_down
        self._last_invite_gen = -1

    # -- bring-up / teardown --------------------------------------------------

    def start(self) -> "ElasticRuntime":
        import jax
        from jax._src import distributed

        if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
            # gloo is the only CPU collectives backend that tolerates the
            # in-process rebuild (mpi pins world size at MPI_Init)
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        xe = _xe()
        st = distributed.global_state
        cfg = self.cfg
        if cfg.node_id == 0:
            # heartbeats slowed to never-expire: the service must outlive
            # every generation; the KV monitor owns death verdicts
            self._service = xe.get_distributed_runtime_service(
                f"[::]:{cfg.coordinator_port}", cfg.n_max,
                heartbeat_interval=300, max_missing_heartbeats=100,
                cluster_register_timeout=cfg.connect_timeout_s,
                shutdown_timeout=5)
            st.service = self._service
        client = xe.get_distributed_runtime_client(
            cfg.coordinator_address, cfg.node_id,
            init_timeout=cfg.connect_timeout_s, shutdown_timeout=5,
            heartbeat_interval=300, max_missing_heartbeats=100,
            shutdown_on_destruction=False, use_compression=True)
        client.connect()
        st.client = client
        self.client = client
        self._baseline = set(_sock_fds())
        self._fenced = set()
        self.monitor = PeerLivenessMonitor(
            process_id=cfg.node_id, num_processes=cfg.n_max, kv=client,
            interval_s=cfg.monitor_interval_s,
            deadline_s=cfg.monitor_deadline_s,
            on_peer_down=self._record_peer_down,
        ).start()
        return self

    def close(self) -> None:
        if self.monitor is not None:
            self.monitor.close()

    def _record_peer_down(self, peer: int) -> None:
        # peer -1 is the monitor's "coordinator itself unreachable" verdict:
        # the KV channel the resize would ride is gone — only
        # restart-the-world can recover that
        if peer < 0:
            abort_transient(
                "coordinator KV store unreachable — elastic resize "
                "impossible without it; degrading to restart-the-world")
        import perceiver_io_tpu.obs as obs

        obs.event("elastic_peer_down", peer=peer,
                  generation=self.world.generation if self.world else -1)
        if self._on_peer_down is not None:
            self._on_peer_down(peer)

    # -- socket fencing -------------------------------------------------------

    def fence(self) -> int:
        """``shutdown(SHUT_RDWR)`` every TCP socket opened since bring-up.

        Releases peers blocked in gloo recv on pairs to a dead rank NOW
        (there is no gloo timeout — only socket death unblocks them).
        Skips the coordinator connection and LISTEN sockets (tcp state 0A:
        shutting a listener down wakes gloo's accept with EINVAL and aborts
        the process). fds are detached, never closed — a close would free
        the fd number for reuse while gloo still holds it.
        """
        n = 0
        for fd, (rport, state_hex) in _sock_fds().items():
            if (fd in self._baseline or fd in self._fenced
                    or rport is None  # not in the TCP tables: a unix socket
                    # (buddy mirrors) or other non-TCP fd — never gloo's
                    or rport == self.cfg.coordinator_port
                    or state_hex == "0A"):
                continue
            try:
                s = socket.socket(fileno=fd)
            except OSError:
                continue
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            s.detach()
            self._fenced.add(fd)
            n += 1
        return n

    # -- KV rendezvous --------------------------------------------------------

    def _key(self, *parts) -> str:
        return "/".join((self.cfg.namespace,) + tuple(str(p) for p in parts))

    def kv_sync(self, tag: str, ranks: Sequence[int],
                timeout_ms: Optional[int] = None) -> None:
        """Barrier over ``ranks`` on per-tag KV keys. set + blocking-get per
        rank on purpose: ``key_value_dir_get_bytes`` can crash the client in
        the immediate aftermath of a collective failure."""
        timeout_ms = timeout_ms or self.cfg.sync_timeout_ms
        self.client.key_value_set(
            self._key("sync", tag, self.cfg.node_id), "1",
            allow_overwrite=True)
        for r in ranks:
            self.client.blocking_key_value_get(
                self._key("sync", tag, r), timeout_ms)

    def _pjrt_cleanup(self) -> int:
        """Generation leader: delete the stale PJRT topology and gloo
        rendezvous keys so re-registration at the new world size cannot
        collide with the previous generation's entries."""
        doomed = ["cpu:global_topology"]
        for prefix in ("cpu:local_topology", "cpu:gloo"):
            try:
                doomed += [k for k, _ in
                           self.client.key_value_dir_get_bytes(prefix)]
            except Exception:  # noqa: BLE001 — absent prefix on gen 0
                pass
        for k in doomed:
            try:
                self.client.key_value_delete(k)
            except Exception:  # noqa: BLE001 — already gone
                pass
        return len(doomed)

    # -- generations ----------------------------------------------------------

    def adopt(self, descriptor) -> None:
        """Point jax.distributed and the peer monitor at ``descriptor``."""
        from perceiver_io_tpu.parallel.mesh import adopt_world

        adopt_world(descriptor)
        self.world = descriptor
        self.monitor.set_peers(descriptor.ranks)

    def check_quorum(self, descriptor) -> None:
        """Degrade to restart-the-world when elastic resume is off the
        table: below the quorum floor, or the coordinator host itself gone
        (node 0 hosts the service — without it there is no control plane
        to resize over)."""
        if descriptor.num_processes < self.cfg.quorum:
            abort_transient(
                f"elastic world {list(descriptor.ranks)} below quorum floor "
                f"{self.cfg.quorum} — degrading to restart-the-world")
        if 0 not in descriptor.ranks and self.cfg.node_id != 0:
            abort_transient(
                "coordinator host (node 0) left the world — elastic resize "
                "impossible without its service; restart-the-world")

    def rebuild(self, descriptor) -> float:
        """Demolish the current device runtime and bring up ``descriptor``.

        Returns the rebuild wall seconds. Sequencing is load-bearing:
        demolish+fence BEFORE the rendezvous (a survivor still wedged on a
        dead pair would miss the barrier), barrier BEFORE the leader's key
        cleanup (a straggler re-registering under generation g−1 keys while
        the leader deletes them would deadlock bring-up).
        """
        t0 = time.monotonic()
        # chaos hook: the resize negotiation edge — fatal here = a survivor
        # dying MID-RESIZE (peers' kv_sync below times out; the caller's
        # retry loop takes a fresh verdict and shrinks again)
        faults.inject("multihost.resize")
        from perceiver_io_tpu.parallel.mesh import reset_backend

        reset_backend()
        self.fence()
        gen = descriptor.generation
        self.kv_sync(f"pre_del_g{gen}", descriptor.ranks)
        if self.cfg.node_id == descriptor.leader:
            self._pjrt_cleanup()
            self.client.key_value_set(
                self._key("clean", f"g{gen}"), "1", allow_overwrite=True)
        else:
            self.client.blocking_key_value_get(
                self._key("clean", f"g{gen}"), self.cfg.sync_timeout_ms)
        self.adopt(descriptor)
        wall = time.monotonic() - t0
        import perceiver_io_tpu.obs as obs

        obs.event("elastic_rebuild", generation=gen,
                  ranks=list(descriptor.ranks), wall_s=round(wall, 3))
        return wall

    def dead_in(self, descriptor) -> Tuple[int, ...]:
        """The monitor's current verdict, restricted to ``descriptor``."""
        return tuple(p for p in self.monitor.peers_down()
                     if p in descriptor.ranks)

    def await_death_verdict(self, grace_s: float = 2.0) -> Tuple[int, ...]:
        """Dispatch failed / fetch wedged: fence immediately (release peers
        wedged on OUR dead pairs before they miss the verdict window), then
        wait out one monitor deadline for an agreed verdict."""
        self.fence()
        deadline = (time.monotonic()
                    + self.cfg.monitor_deadline_s + grace_s)
        while time.monotonic() < deadline:
            dead = self.dead_in(self.world)
            if dead:
                return dead
            time.sleep(0.05)
        return self.dead_in(self.world)

    def shrink_until_stable(self, attempts: Optional[int] = None):
        """Shrink the world until one rebuild completes with every
        participant alive. A survivor dying MID-RESIZE surfaces as a
        rendezvous timeout: take a fresh verdict, shrink again at the next
        generation. Exhausting ``attempts`` degrades to restart-the-world.
        Returns the stable :class:`~perceiver_io_tpu.parallel.mesh
        .WorldDescriptor`.
        """
        cur = self.world
        budget = attempts if attempts is not None else self.cfg.resize_attempts
        for _ in range(budget):
            nxt = cur.shrink(self.dead_in(cur))
            self.check_quorum(nxt)
            try:
                self.rebuild(nxt)
                return nxt
            except faults.InjectedFatalError:
                # the multihost.resize fatal drill: a fault-killed survivor
                # must DIE here (the worker exits on it), not consume a
                # retry as if the rendezvous had merely timed out
                raise
            except Exception as e:  # noqa: BLE001 — rendezvous timeout
                import perceiver_io_tpu.obs as obs

                obs.event("elastic_resize_retry",
                          generation=nxt.generation, error=type(e).__name__)
                self.fence()
                # let the monitor reach a verdict on whoever died mid-resize
                time.sleep(self.cfg.monitor_deadline_s + 1.0)
                cur = nxt
        abort_transient(
            f"elastic resize failed {budget} consecutive attempts — "
            f"degrading to restart-the-world")

    # -- grow / hot-spare join ------------------------------------------------

    def post_invite(self, new_ids: Sequence[int],
                    **extra: Any) -> Dict[str, Any]:
        """Leader: invite ``new_ids`` into the next generation. Survivors
        see it at their next step boundary (:meth:`check_invite`); parked
        spares see it via :meth:`await_invite`. ``extra`` rides the invite
        verbatim (e.g. ``at_step`` — the agreed boundary every participant
        switches generations at, so late readers of the sticky key still
        grow at the same step as the leader)."""
        ranks = sorted(set(self.world.ranks) | {int(i) for i in new_ids})
        invite = {"gen": self.world.generation + 1, "ranks": ranks, **extra}
        self.client.key_value_set(
            self._key(_INVITE_KEY), json.dumps(invite), allow_overwrite=True)
        return invite

    def _read_invite(self, timeout_ms: int) -> Optional[Dict[str, Any]]:
        try:
            raw = self.client.blocking_key_value_get(
                self._key(_INVITE_KEY), timeout_ms)
        except Exception:  # noqa: BLE001 — no invite posted yet
            return None
        invite = json.loads(raw)
        if invite["gen"] <= self._last_invite_gen:
            return None  # stale: already acted on (the key is sticky)
        return invite

    def check_invite(self) -> Optional[Dict[str, Any]]:
        """Survivor, at a step boundary: a pending grow invite, or None.
        1 ms poll — cheap enough for every step."""
        invite = self._read_invite(1)
        if invite is not None and invite["gen"] <= self.world.generation:
            return None
        return invite

    def await_invite(self, timeout_ms: int = 600_000,
                     ) -> Optional[Dict[str, Any]]:
        """Parked spare: block until invited into a generation."""
        return self._read_invite(timeout_ms)

    def accept_invite(self, invite: Dict[str, Any]):
        """Build the invited world descriptor and mark the invite consumed
        (on every participant — survivors and the joining spare alike)."""
        from perceiver_io_tpu.parallel.mesh import WorldDescriptor

        self._last_invite_gen = invite["gen"]
        return WorldDescriptor(generation=invite["gen"],
                               ranks=tuple(invite["ranks"]),
                               node_id=self.cfg.node_id)

    def join(self, invite: Dict[str, Any]) -> float:
        """Spare side of a grow: the same rebuild path the survivors run.
        Returns the rebuild wall seconds."""
        # chaos hook: the join edge — transient here = a spare whose join
        # attempt fails (it re-parks and waits for the next invite)
        faults.inject("multihost.join")
        return self.rebuild(self.accept_invite(invite))


# -- peer-redundant in-memory checkpoints (buddy mirrors) ----------------------


def buddy_path_for(node_id: int, root: Optional[str] = None) -> str:
    """The node's buddy-mirror unix-socket path (stable across resizes)."""
    return os.path.join(root or tempfile.gettempdir(),
                        f"pit-buddy-{node_id}.sock")


class BuddyStore:
    """The receive half: a unix-socket server holding peers' mirrored
    snapshots in memory, keyed by owner node id. Speaks the r22 transport
    frame (``serving/transport.py send_frame/recv_frame``); ops: ``put``
    (store a mirror, ack), ``get`` (return a mirror + its metadata).
    Mirrors live in THIS process's memory — the redundancy is across
    hosts, which is exactly the failure domain a resize recovers from.
    """

    _guarded_by = {"_mirrors": "_lock"}

    def __init__(self, node_id: int, root: Optional[str] = None):
        self.node_id = int(node_id)
        self.path = buddy_path_for(node_id, root)
        self._lock = threading.Lock()
        self._mirrors: Dict[int, Tuple[Dict[str, Any], bytes]] = {}
        self._listener: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "BuddyStore":
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        listener.bind(self.path)
        listener.listen(8)
        self._listener = listener
        self._thread = threading.Thread(
            target=self._accept_loop, name=f"buddy-store-{self.node_id}",
            daemon=True)
        self._thread.start()
        return self

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        from perceiver_io_tpu.serving.transport import recv_frame, send_frame

        try:
            with conn:
                header, payload = recv_frame(conn)
                op = header.get("op")
                if op == "put":
                    meta = {k: header[k] for k in
                            ("owner", "gen", "step", "digest")}
                    with self._lock:
                        self._mirrors[int(header["owner"])] = (meta, payload)
                    send_frame(conn, {"ok": True})
                elif op == "get":
                    with self._lock:
                        entry = self._mirrors.get(int(header["owner"]))
                    if entry is None:
                        send_frame(conn, {"ok": False})
                    else:
                        meta, payload = entry
                        send_frame(conn, dict(meta, ok=True), payload)
                else:
                    send_frame(conn, {"ok": False})
        except (ConnectionError, OSError, ValueError, KeyError):
            pass  # a dying peer mid-frame: drop the connection

    def mirror_meta(self, owner: int) -> Optional[Dict[str, Any]]:
        """Local introspection (tests, drill reporting): the stored
        mirror's metadata, without moving the payload."""
        with self._lock:
            entry = self._mirrors.get(int(owner))
        return dict(entry[0]) if entry else None

    def close(self) -> None:
        if self._listener is not None:
            try:
                self._listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass


class BuddyMirror:
    """The send half: mirror this host's state snapshot to its buddy, and
    pull a mirror back for restore. Payload = the snapshot's leaves in
    ``jax.tree`` order through the raw-array codec; structure is supplied
    at restore time by a template snapshot, and integrity by the tree
    digest carried in the header — computed over the PRE-send tree, so a
    mirror corrupted in flight (the ``multihost.buddy_send`` nan drill)
    fails verification at restore instead of poisoning the resumed run."""

    def __init__(self, node_id: int, root: Optional[str] = None,
                 timeout_s: float = 10.0):
        self.node_id = int(node_id)
        self.root = root
        self.timeout_s = float(timeout_s)
        self._thread: Optional[threading.Thread] = None
        self.last_error: Optional[BaseException] = None
        self.last_meta: Optional[Dict[str, Any]] = None

    def _roundtrip(self, buddy_id: int, header: Dict[str, Any],
                   payload: bytes = b"") -> Tuple[Dict[str, Any], bytes]:
        from perceiver_io_tpu.serving.transport import recv_frame, send_frame

        with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
            s.settimeout(self.timeout_s)
            s.connect(buddy_path_for(buddy_id, self.root))
            send_frame(s, header, payload)
            return recv_frame(s)

    def mirror_to(self, buddy_id: int, snapshot, *, generation: int,
                  step: int) -> Dict[str, Any]:
        """Push ``snapshot`` (a ``host_state_snapshot`` tree) to the buddy;
        returns the stored metadata. Synchronous — see
        :meth:`mirror_async` for the off-step-boundary path."""
        import jax

        from perceiver_io_tpu.serving.transport import pack_raw_arrays
        from perceiver_io_tpu.utils.treepath import tree_digest

        digest = tree_digest(snapshot)
        # chaos hook AFTER the digest: a poisoned mirror must carry the
        # honest digest of what the sender MEANT to send, so the restore
        # side's verification rejects it
        snapshot = faults.fire("multihost.buddy_send", snapshot)
        leaves = [np.asarray(x) for x in jax.tree.leaves(snapshot)]
        meta = {"op": "put", "owner": self.node_id, "gen": int(generation),
                "step": int(step), "digest": digest}
        resp, _ = self._roundtrip(buddy_id, meta, pack_raw_arrays(leaves))
        if not resp.get("ok"):
            raise ConnectionError(
                f"buddy {buddy_id} refused mirror from node {self.node_id}")
        self.last_meta = {k: meta[k] for k in
                          ("owner", "gen", "step", "digest")}
        return self.last_meta

    def mirror_async(self, buddy_id: int, snapshot, *, generation: int,
                     step: int) -> bool:
        """Fire-and-forget mirror off the training thread. At most one in
        flight — a push landing while the previous is still sending is
        DROPPED (latest-wins cadence; the next boundary re-mirrors).
        Returns whether the push was started; failures land in
        ``last_error`` and are surfaced at the next call."""
        if self._thread is not None and self._thread.is_alive():
            return False

        def _push():
            try:
                self.mirror_to(buddy_id, snapshot,
                               generation=generation, step=step)
                self.last_error = None
            except BaseException as e:  # noqa: BLE001 — reported next call
                self.last_error = e

        self._thread = threading.Thread(
            target=_push, name=f"buddy-mirror-{self.node_id}", daemon=True)
        self._thread.start()
        return True

    def flush(self, timeout_s: Optional[float] = None) -> None:
        """Wait for an in-flight async mirror (step-boundary fence before a
        resize consumes the mirrors)."""
        if self._thread is not None:
            self._thread.join(timeout_s if timeout_s is not None
                              else self.timeout_s)

    def fetch_from(self, buddy_id: int, owner: int, template,
                   ) -> Optional[Tuple[Any, Dict[str, Any]]]:
        """Pull ``owner``'s mirror from ``buddy_id`` and verify it. Returns
        ``(snapshot, meta)``, or None when the buddy has no mirror OR the
        digest does not match (a corrupted mirror is rejected here — the
        caller falls back to the next recovery source, never resumes from
        torn state)."""
        import jax

        from perceiver_io_tpu.serving.transport import read_raw_arrays
        from perceiver_io_tpu.utils.treepath import tree_digest

        resp, payload = self._roundtrip(
            buddy_id, {"op": "get", "owner": int(owner)})
        if not resp.get("ok"):
            return None
        leaves = read_raw_arrays(payload, copy=True)
        treedef = jax.tree.structure(template)
        snapshot = jax.tree.unflatten(treedef, leaves)
        if tree_digest(snapshot) != resp.get("digest"):
            import perceiver_io_tpu.obs as obs

            obs.event("elastic_buddy_mirror_corrupt", owner=int(owner),
                      buddy=int(buddy_id), expected=resp.get("digest"))
            return None
        meta = {k: resp[k] for k in ("owner", "gen", "step", "digest")}
        return snapshot, meta
