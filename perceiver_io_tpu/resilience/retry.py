"""Error taxonomy + exponential backoff with jitter.

The taxonomy answers ONE question for every exception escaping a device
dispatch (or an HTTP fetch): *is retrying sane?* It is deliberately
conservative and string-based — jaxlib surfaces every PJRT failure as
``XlaRuntimeError`` with an absl status prefix, and importing jaxlib types
here would force jax into processes (the download path, the obs sidecar)
that must stay backend-free.

Classification rules, in order:

- injected faults carry their class (``InjectedTransientError`` /
  ``InjectedFatalError``) — the chaos suite's ground truth;
- connection-ish OS errors (reset/aborted/broken pipe/timeout) are transient
  — the tunnel's failure signature;
- ``XlaRuntimeError``-family messages are transient only under status
  prefixes that name infrastructure (UNAVAILABLE, ABORTED, CANCELLED,
  DEADLINE_EXCEEDED, UNKNOWN, INTERNAL) — **RESOURCE_EXHAUSTED is fatal**:
  on this stack those are real scoped-VMEM OOMs with measured boundaries
  (PERF.md r3), and retrying one blind re-runs a deterministic failure;
- everything else (tracing/type/shape errors, ``FloatingPointError`` from the
  NaN guards) is fatal.
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Optional

from perceiver_io_tpu.resilience.faults import (
    InjectedFatalError,
    InjectedTransientError,
)

TRANSIENT = "transient"
FATAL = "fatal"


class RejectedError(RuntimeError):
    """A request refused at admission (bounded-queue load shedding or an open
    circuit breaker) — shed fast instead of queueing toward a timeout."""


class DeadlineExceeded(TimeoutError):
    """A request shed because its deadline expired before (or at) dispatch —
    the work would have been dead on arrival."""


# absl status prefixes as they appear in XlaRuntimeError messages.
# RESOURCE_EXHAUSTED deliberately absent: real scoped-VMEM OOMs (PERF.md r3).
_TRANSIENT_STATUS_PREFIXES = (
    "UNAVAILABLE", "ABORTED", "CANCELLED", "DEADLINE_EXCEEDED", "UNKNOWN",
    "INTERNAL",
)
# connection-level failure text (tunnel drops surface these inside URLError /
# XlaRuntimeError messages as well as bare OSErrors)
_TRANSIENT_MESSAGE_MARKERS = (
    "connection reset", "connection aborted", "broken pipe", "socket closed",
    "failed to connect", "connection closed", "transient",
)
_RUNTIME_ERROR_TYPES = ("XlaRuntimeError", "PjRtError", "JaxRuntimeError")
# deterministic failures that can surface under infra-looking status
# prefixes: the remote-compile scoped-VMEM OOMs (CLAUDE.md / PERF.md r3)
_FATAL_MESSAGE_MARKERS = ("scoped vmem", "scoped allocation", "out of memory")


def classify_error(exc: BaseException) -> str:
    """``'transient'`` (retry is sane) or ``'fatal'`` (it is not)."""
    if isinstance(exc, InjectedTransientError):
        return TRANSIENT
    if isinstance(exc, InjectedFatalError):
        return FATAL
    # self-declared class: an error that crossed a process boundary (the
    # replica RPC shim mirrors the REMOTE side's classification as a bool
    # `transient` attribute) keeps its original verdict — re-deriving it
    # from the mirrored message text would misread, e.g., a fatal shape
    # error whose repr happens to contain 'connection'
    declared = getattr(exc, "transient", None)
    if isinstance(declared, bool):
        return TRANSIENT if declared else FATAL
    if isinstance(exc, (ConnectionResetError, ConnectionAbortedError,
                        BrokenPipeError, TimeoutError)):
        return TRANSIENT
    msg = str(exc)
    lowered = msg.lower()
    mro_names = {c.__name__ for c in type(exc).__mro__}
    if mro_names.intersection(_RUNTIME_ERROR_TYPES):
        if any(m in lowered for m in _FATAL_MESSAGE_MARKERS):
            # deterministic compiler failures ride infra-looking prefixes on
            # the remote-compile path (PERF.md r3) — never retry these
            return FATAL
        head = msg.lstrip().split(":", 1)[0].strip()
        if head in _TRANSIENT_STATUS_PREFIXES:
            return TRANSIENT
        if any(m in lowered for m in _TRANSIENT_MESSAGE_MARKERS):
            return TRANSIENT
        return FATAL
    if isinstance(exc, OSError) and any(
        m in lowered for m in _TRANSIENT_MESSAGE_MARKERS
    ):
        return TRANSIENT
    return FATAL


def is_transient(exc: BaseException) -> bool:
    return classify_error(exc) == TRANSIENT


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Capped exponential backoff with deterministic-when-seeded jitter.

    ``max_retries`` counts RE-tries: 0 means one attempt, no retry. Backoff
    for retry *i* (1-based) is ``min(base_s * multiplier**(i-1), max_s)``
    scaled by a jitter factor in ``[1 - jitter, 1 + jitter]``.
    """

    max_retries: int = 3
    base_s: float = 0.05
    multiplier: float = 2.0
    max_s: float = 2.0
    jitter: float = 0.25

    def __post_init__(self):
        if self.max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {self.max_retries}")
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")

    def backoff_s(self, retry: int, rng: Optional[random.Random] = None) -> float:
        """Sleep before 1-based retry ``retry``; pass a seeded ``rng`` for a
        reproducible schedule (the chaos tests do)."""
        if retry < 1:
            return 0.0
        base = min(self.base_s * self.multiplier ** (retry - 1), self.max_s)
        if self.jitter == 0.0:
            return base
        r = rng if rng is not None else random
        return base * (1.0 + self.jitter * (2.0 * r.random() - 1.0))


def call_with_retry(
    fn: Callable,
    policy: RetryPolicy = RetryPolicy(),
    classify: Callable[[BaseException], str] = classify_error,
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
    rng: Optional[random.Random] = None,
):
    """Call ``fn()``; on a TRANSIENT exception back off and retry up to
    ``policy.max_retries`` times. Fatal errors and exhausted budgets re-raise
    the original exception. ``on_retry(retry_index, error, backoff_s)`` is the
    observability hook (counters, event log)."""
    retry = 0
    while True:
        try:
            return fn()
        except Exception as e:
            if retry >= policy.max_retries or classify(e) != TRANSIENT:
                raise
            retry += 1
            pause = policy.backoff_s(retry, rng=rng)
            if on_retry is not None:
                on_retry(retry, e, pause)
            if pause > 0:
                sleep(pause)
