"""Deterministic, test-seedable fault injection for the runtime paths.

The environment this framework targets exhibits real failure modes — wedged
device tunnels that hang a dispatch indefinitely, transient PJRT/remote-compile
errors, throughput collapses, silent NaN outputs (CLAUDE.md, PERF.md r3/r6).
None of them can be provoked on demand from a CPU test box, so the recovery
machinery (``retry``/``breaker``, the engine's shed/retry paths, the trainer's
bad-step guard) would otherwise ship untested. This module is the substrate
for the chaos suite: instrumented sites in the dispatch paths call
:func:`inject` / :func:`corrupt`, which are no-ops until a
:class:`FaultInjector` is installed — then they raise, hang, sleep, or
NaN-corrupt exactly where the real failures would.

Faults are **deterministic**: each spec names the 1-based call indices at
which it fires (``at=(2, 5)``), or an every-N cadence, so a chaos drill
replays identically. A ``hang`` spec blocks on a ``threading.Event`` the test
holds (the wedged-tunnel simulation — release it to "un-wedge" the tunnel).

Instrumented sites (grep for ``faults.inject`` / ``faults.corrupt``):

- ``engine.dispatch`` — inside :meth:`ServingEngine._execute`, before the
  jitted call (raise/hang here = the dispatch itself failing/wedging);
- ``engine.complete`` — before the worker's ``device_get`` (a completion-side
  failure);
- ``trainer.dispatch`` — before the trainer's train-step dispatch;
- ``trainer.metrics`` — ``corrupt`` hook over the train-step metrics (NaN
  loss injection: the signature of a poisoned step);
- ``deploy.publish`` / ``deploy.gate`` / ``deploy.swap`` — the train→serve
  deployment loop (``perceiver_io_tpu.deploy``): checkpoint publication
  (``fire`` hook: raise kinds AND nan corruption of the published tree),
  the serving-side admission gate, and the fleet hot-swap;
- ``trainer.collective`` — ``fire`` hook over the host-local batch right
  before every train dispatch (multi-host chaos: per-host NaN corruption,
  wedged-host hangs, per-step throttling);
- ``multihost.heartbeat`` — the peer-liveness publisher
  (``resilience/multihost.py``);
- ``spawn.child_exit`` — the restart-the-world supervisor's child watch
  loop (``cli/common.py``);
- ``transport.send`` / ``transport.recv`` — the replica RPC data plane
  (``serving/transport.py`` and the HTTP client): before a frame is
  written / after one is accepted, so transport chaos drills (mid-call
  connection death, torn exchanges) run without killing real processes;
- ``multihost.resize`` — the elastic world-resize edge
  (``resilience/elastic.py``): fired at the start of every shrink/grow
  attempt, so drills can kill a survivor mid-resize or throttle a
  straggler;
- ``multihost.buddy_send`` — ``fire`` hook over the host-local state
  snapshot before it is framed to the buddy host (NaN corruption here is
  the corrupted-mirror drill the digest check must catch at restore);
- ``multihost.join`` — the spare/hot-join path (a spare dying mid-join,
  or joining while a shrink is in flight).

The registered sites live in :data:`SITES`; :func:`parse_spec` validates
every clause against them (and the kind set), so a typo'd drill fails
loudly at install instead of silently injecting nothing.

Env gating for whole-process chaos runs (no code changes)::

    PIT_FAULTS="engine.dispatch:transient@2,5;trainer.metrics:nan@3" python ...

is parsed by :func:`install_from_env`, called lazily on the first ``inject``.
Production default: ``PIT_FAULTS`` unset, no injector installed, every hook
is a None-check.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Dict, Iterable, Optional, Tuple

import numpy as np

ENV_VAR = "PIT_FAULTS"

_KINDS = ("transient", "fatal", "hang", "slow", "nan")

# The registered instrumentation sites. parse_spec VALIDATES against this
# set: a typo'd PIT_FAULTS drill must fail loudly at install, not silently
# inject nothing while the operator believes chaos is running. Sites in
# _SUFFIXED also accept a ".<qualifier>" suffix (the per-engine drill
# targets, e.g. ``engine.dispatch.replica0-infer``).
SITES = (
    "engine.dispatch",
    "engine.complete",
    "trainer.dispatch",
    "trainer.metrics",
    # the train->serve deployment loop (perceiver_io_tpu.deploy): publish
    # (raise = a publish dying mid-write; nan = a poisoned tree whose digest
    # still verifies), admission gate, and the fleet swap itself
    "deploy.publish",
    "deploy.gate",
    "deploy.swap",
    # the serving control loop (perceiver_io_tpu.serving): the autoscaler's
    # actuation edge (raise = a spawn/retire failing — the backoff drill:
    # PIT_FAULTS="autoscale.scale:transient@1" fails the first spawn) and
    # the router's admission gate (raise/hang inside admit, before any
    # queue slot or token is consumed)
    "autoscale.scale",
    "router.admit",
    # the generative decode path (perceiver_io_tpu.inference.generate): the
    # prefix encode and the chunked decode dispatch — the mid-stream chaos
    # drills target a replica's step path without code changes
    "generation.prefill",
    "generation.step",
    # the continuous-batching arena (perceiver_io_tpu.inference.batching):
    # ONE batched decode dispatch covers every active stream, so a fault
    # here is the blast-radius drill — all in-flight streams on the replica
    # observe the same failure and must reroute content-losslessly
    "generation.batch_dispatch",
    # multi-host training fault tolerance (r19): the collective train-step
    # edge (fire hook over the HOST-LOCAL batch before dispatch — nan =
    # one host's shard corrupted, whose NaN then rides the global loss
    # reduction to every peer; hang = a wedged host inside the collective;
    # slow = per-step throttle for drill timing), the peer-liveness
    # publisher (resilience/multihost.py — transient = a KV-store write
    # failing; hang = this host stops beating, so PEERS mark it down), and
    # the world supervisor's child watch loop (cli/common.py — a raise is
    # treated as an observed child death, driving restart drills without
    # killing real processes)
    "trainer.collective",
    "multihost.heartbeat",
    "spawn.child_exit",
    # the replica transport data plane (serving/transport.py + the HTTP
    # client): "send" fires just before a request/response frame hits the
    # wire (client request writes AND replica response writes share the
    # site), "recv" just after a frame is accepted — the chaos drills for
    # mid-RPC connection death and torn-exchange failover without killing
    # real processes
    "transport.send",
    "transport.recv",
    # elastic multi-host training (resilience/elastic.py): the resize
    # negotiation edge (inject at the start of every shrink/grow attempt —
    # fatal/kill here = a survivor dying MID-RESIZE, so the remaining peers
    # must re-verdict and resize AGAIN; slow = a straggler survivor), the
    # buddy in-memory-checkpoint send (fire hook over the host-local
    # snapshot before it is framed — nan = a corrupted mirror the
    # tree-digest check must reject at restore), and the spare/hot-join
    # edge (inject inside the join path — a spare failing, or joining while
    # a shrink is in flight)
    "multihost.resize",
    "multihost.buddy_send",
    "multihost.join",
)
_SUFFIXED = ("engine.dispatch", "engine.complete")


def validate_site(site: str) -> str:
    """Return ``site`` if registered (exactly, or a registered per-engine
    prefix); raise ValueError naming the valid options otherwise."""
    if site in SITES or any(site.startswith(s + ".") and len(site) > len(s) + 1
                            for s in _SUFFIXED):
        return site
    raise ValueError(
        f"unknown fault site {site!r}; one of {SITES} "
        f"(or {', '.join(s + '.<engine-name>' for s in _SUFFIXED)})"
    )


class InjectedTransientError(RuntimeError):
    """An injected fault standing in for a transient runtime error (the
    classifier in :mod:`perceiver_io_tpu.resilience.retry` maps it to
    ``'transient'``, like a PJRT UNAVAILABLE)."""


class InjectedFatalError(RuntimeError):
    """An injected fault the taxonomy must treat as fatal (no retry)."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One fault at one site.

    ``at``: 1-based call indices of the site at which the fault fires;
    ``every``: alternatively fire on every Nth call (``at`` wins when set).
    ``kind``: ``transient`` / ``fatal`` raise; ``hang`` blocks until
    ``release`` is set (or ``delay_s`` elapses, when given); ``slow`` sleeps
    ``delay_s``; ``nan`` fires only through :func:`corrupt` and NaN-fills
    every floating leaf of the payload.
    """

    site: str
    kind: str
    at: Tuple[int, ...] = ()
    every: int = 0
    delay_s: float = 0.0
    release: Optional[threading.Event] = None

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; one of {_KINDS}")
        if not self.at and self.every <= 0:
            raise ValueError("FaultSpec needs at=(indices...) or every=N")

    def fires(self, call_index: int) -> bool:
        if self.at:
            return call_index in self.at
        return call_index % self.every == 0


class FaultInjector:
    """Holds the fault plan plus per-site call counters (thread-safe: sites
    are hit from engine workers, submitter threads, and the trainer loop)."""

    def __init__(self, specs: Iterable[FaultSpec] = ()):
        self._specs = list(specs)
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self.fired: Dict[str, int] = {}  # site -> faults actually fired

    def add(self, spec: FaultSpec) -> "FaultInjector":
        self._specs.append(spec)
        return self

    def calls(self, site: str) -> int:
        with self._lock:
            return self._counts.get(site, 0)

    def _tick(self, site: str, kinds: Tuple[str, ...]):
        """Count one call of ``site`` and return the specs that fire on it."""
        with self._lock:
            n = self._counts.get(site, 0) + 1
            self._counts[site] = n
            due = [
                s for s in self._specs
                if s.site == site and s.kind in kinds and s.fires(n)
            ]
            if due:
                self.fired[site] = self.fired.get(site, 0) + len(due)
        return due

    def inject(self, site: str) -> None:
        for spec in self._tick(site, ("transient", "fatal", "hang", "slow")):
            self._execute(spec, site)

    def corrupt(self, site: str, payload):
        """NaN-fill the floating leaves of ``payload`` when a ``nan`` spec
        fires on this call of ``site``; otherwise return it unchanged."""
        if not self._tick(site, ("nan",)):
            return payload
        return _poison_tree(payload)

    def fire(self, site: str, payload):
        """Combined hook for sites that support BOTH raise-type faults and
        payload corruption (``deploy.publish``): ONE tick of ``site`` per
        call, every spec kind considered, so a drill's 1-based call indices
        count real calls — not the two internal ticks a separate
        inject+corrupt pair would burn. Returns the (possibly corrupted)
        payload, or raises/sleeps/hangs per the due raise-kind specs."""
        due = self._tick(site, _KINDS)
        for spec in due:
            if spec.kind != "nan":
                self._execute(spec, site)
        if any(spec.kind == "nan" for spec in due):
            payload = _poison_tree(payload)
        return payload

    def _execute(self, spec: FaultSpec, site: str) -> None:
        """Run one due raise-kind spec (shared by inject and fire, so the
        hang/slow/raise semantics cannot drift between the two hooks)."""
        if spec.kind == "slow":
            _interruptible_sleep(spec.delay_s)
        elif spec.kind == "hang":
            # the wedged tunnel: block until the test un-wedges it (or a
            # bounded delay, so a forgotten release can't hang a suite)
            if spec.release is not None:
                spec.release.wait(spec.delay_s or None)
            else:
                _interruptible_sleep(spec.delay_s or 3600.0)
        elif spec.kind == "transient":
            raise InjectedTransientError(
                f"injected transient fault at {site!r} "
                f"(call {self.calls(site)})"
            )
        else:
            raise InjectedFatalError(
                f"injected fatal fault at {site!r} (call {self.calls(site)})"
            )


def _poison_tree(payload):
    import jax

    def poison(x):
        a = np.asarray(x)
        if np.issubdtype(a.dtype, np.floating):
            return np.full_like(a, np.nan)
        return x

    return jax.tree.map(poison, payload)


def _interruptible_sleep(seconds: float) -> None:
    # Event.wait rather than time.sleep: a daemon thread stuck in a plain
    # sleep delays interpreter shutdown on some platforms
    threading.Event().wait(seconds)


# -- process-global install point --------------------------------------------

_ACTIVE: Optional[FaultInjector] = None
_ENV_CHECKED = False


def install(injector: Optional[FaultInjector]) -> Optional[FaultInjector]:
    """Install (or with None, remove) the process-global injector; returns the
    previous one so tests can restore it."""
    global _ACTIVE, _ENV_CHECKED
    previous, _ACTIVE = _ACTIVE, injector
    _ENV_CHECKED = True  # an explicit install wins over the env var
    return previous


def get() -> Optional[FaultInjector]:
    return _ACTIVE


def parse_spec(text: str) -> FaultInjector:
    """Parse the ``PIT_FAULTS`` grammar:
    ``site:kind@1,4;site2:kind2@every:3[@delay:0.5]``.

    Each ``;``-separated clause is ``site:kind@WHEN`` where WHEN is a
    comma-list of 1-based call indices or ``every:N``; an optional trailing
    ``@delay:SECONDS`` sets the hang/slow duration.
    """
    inj = FaultInjector()
    for clause in filter(None, (c.strip() for c in text.split(";"))):
        try:
            site, rest = clause.split(":", 1)
            # validate EAGERLY against the registered site and kind sets: a
            # typo'd drill must fail at install with the valid options named,
            # not silently inject nothing (the kind check lives in FaultSpec;
            # both surface through the clause-naming ValueError below)
            validate_site(site)
            kind, _, when = rest.partition("@")
            delay = 0.0
            if "@delay:" in when:
                when, _, d = when.partition("@delay:")
                delay = float(d)
            if when.startswith("every:"):
                inj.add(FaultSpec(site=site, kind=kind,
                                  every=int(when[len("every:"):]),
                                  delay_s=delay))
            else:
                inj.add(FaultSpec(
                    site=site, kind=kind, delay_s=delay,
                    at=tuple(int(i) for i in when.split(",") if i),
                ))
        except (ValueError, TypeError) as e:
            raise ValueError(
                f"bad {ENV_VAR} clause {clause!r} "
                f"(expected site:kind@1,4 or site:kind@every:N): {e}"
            ) from e
    return inj


def install_from_env() -> None:
    """Install an injector from ``PIT_FAULTS`` once per process (no-op when
    unset or an injector was installed explicitly)."""
    global _ENV_CHECKED, _ACTIVE
    if _ENV_CHECKED:
        return
    _ENV_CHECKED = True
    text = os.environ.get(ENV_VAR)
    if text:
        _ACTIVE = parse_spec(text)


# -- the site-side hooks (near-zero cost when inactive) ----------------------


def inject(site: str) -> None:
    """Instrumentation hook: raise/hang/sleep if a fault is due at ``site``."""
    if not _ENV_CHECKED:
        install_from_env()
    if _ACTIVE is not None:
        _ACTIVE.inject(site)


def corrupt(site: str, payload):
    """Instrumentation hook: NaN-corrupt ``payload`` if a fault is due."""
    if not _ENV_CHECKED:
        install_from_env()
    if _ACTIVE is not None:
        return _ACTIVE.corrupt(site, payload)
    return payload


def fire(site: str, payload):
    """Combined raise+corrupt hook (one site tick per call — see
    :meth:`FaultInjector.fire`); returns the possibly-corrupted payload."""
    if not _ENV_CHECKED:
        install_from_env()
    if _ACTIVE is not None:
        return _ACTIVE.fire(site, payload)
    return payload
