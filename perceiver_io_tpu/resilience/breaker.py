"""Circuit breaker for a dispatch loop: fail fast while the device is down.

When the tunnel wedges or PJRT starts throwing, every queued request is dead
weight: it occupies queue slots, burns dispatch attempts, and holds its
caller in a blocking ``result()``. The breaker turns *repeated* failure into
an admission-control signal:

- **closed** (healthy): requests flow; each dispatch outcome is recorded.
  ``failure_threshold`` consecutive failures — or an explicit :meth:`trip`
  from the heartbeat's stall monitor — open it.
- **open**: admission fast-fails (:class:`BreakerOpen`) for ``cooldown_s``.
  No queue growth, no doomed dispatches, callers learn immediately.
- **half-open**: after the cooldown, the next :meth:`allow` lets traffic
  probe the device. One recorded success closes the breaker; a failure (or a
  stall trip) re-opens it with a fresh cooldown.

State is exported to the metrics registry (``breaker_state`` gauge: 0 closed,
1 half-open, 2 open; ``breaker_transitions_total`` counter per target state)
and to ``healthz()`` — an open breaker makes ``/healthz`` 503 via the obs
health-source registration, so orchestrators see the outage the same way they
see a heartbeat stall.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.resilience.retry import RejectedError

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class BreakerOpen(RejectedError):
    """Admission refused: the circuit breaker is open (device presumed down)."""


class CircuitBreaker:
    """Thread-safe closed → open → half-open breaker around one dispatch loop.

    ``failure_threshold`` consecutive ``record_failure`` calls open it;
    ``trip()`` opens it immediately (the heartbeat-stall path); ``cooldown_s``
    after opening, one probe round is admitted and its outcome decides.

    ``clock`` is injectable for deterministic tests.
    """

    def __init__(
        self,
        name: str = "device",
        failure_threshold: int = 5,
        cooldown_s: float = 5.0,
        registry: Optional[obs.MetricsRegistry] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}"
            )
        if cooldown_s <= 0:
            raise ValueError(f"cooldown_s must be > 0, got {cooldown_s}")
        self.name = name
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._last_reason = ""

        reg = registry if registry is not None else obs.get_registry()
        labels = {"breaker": name}
        self._m_state = reg.gauge(
            "breaker_state", "0 closed, 1 half-open, 2 open", labels)
        self._m_transitions = {
            s: reg.counter(
                "breaker_transitions_total", "state transitions by target",
                {**labels, "to": s})
            for s in (CLOSED, OPEN, HALF_OPEN)
        }
        self._m_state.set(0)
        obs.register_health_source(self)

    # -- state machine -------------------------------------------------------

    def _transition(self, state: str, reason: str = "") -> None:
        # callers hold self._lock
        if state == self._state:
            return
        self._state = state
        self._last_reason = reason
        if state == OPEN:
            self._opened_at = self._clock()
        if state != CLOSED:
            # entering OPEN always starts a fresh failure count; HALF_OPEN
            # keeps it so a failed probe reopens on the first failure
            self._consecutive_failures = (
                0 if state == OPEN else self._consecutive_failures
            )
        self._m_state.set(_STATE_CODES[state])
        self._m_transitions[state].inc()
        obs.event("breaker_transition", breaker=self.name, to=state,
                  reason=reason)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """Admission check: True when requests may enter. An open breaker
        whose cooldown elapsed flips to half-open and admits the probe."""
        with self._lock:
            if self._state == OPEN:
                if self._clock() - self._opened_at >= self.cooldown_s:
                    self._transition(HALF_OPEN, "cooldown elapsed")
                    return True
                return False
            return True

    def check(self) -> None:
        """Raise :class:`BreakerOpen` unless :meth:`allow` admits."""
        if not self.allow():
            raise BreakerOpen(
                f"circuit breaker {self.name!r} is open "
                f"({self._last_reason or 'consecutive dispatch failures'}); "
                f"retry after {self.cooldown_s:g}s cooldown"
            )

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if self._state == HALF_OPEN:
                self._transition(CLOSED, "probe succeeded")

    def record_failure(self, error: Optional[BaseException] = None) -> None:
        reason = f"{type(error).__name__}: {error}" if error else "failure"
        with self._lock:
            self._consecutive_failures += 1
            if self._state == HALF_OPEN:
                self._transition(OPEN, f"probe failed ({reason})")
            elif (self._state == CLOSED
                  and self._consecutive_failures >= self.failure_threshold):
                self._transition(
                    OPEN,
                    f"{self._consecutive_failures} consecutive failures "
                    f"(last: {reason})",
                )

    def trip(self, reason: str = "tripped") -> None:
        """Open immediately regardless of counts — the heartbeat-stall hook
        (a wedged dispatch never *fails*, it just never completes). The
        stall monitor re-trips on every poll while the stall persists, so an
        already-open breaker EXTENDS its cooldown window here: a wedge
        outlasting ``cooldown_s`` must not park the breaker half-open,
        admitting traffic behind a worker still stuck in the device call."""
        with self._lock:
            if self._state == OPEN:
                self._opened_at = self._clock()
                self._last_reason = reason
            else:
                self._transition(OPEN, reason)

    # -- obs integration -----------------------------------------------------

    def health_status(self) -> Tuple[str, bool, Dict[str, Any]]:
        """The obs health-source contract: ``(name, ok, detail)``. Open =
        unhealthy; half-open is probing and counts as healthy (traffic is
        admitted again)."""
        with self._lock:
            state = self._state
            detail = {
                "state": state,
                "consecutive_failures": self._consecutive_failures,
                "reason": self._last_reason,
            }
            if state == OPEN:
                detail["open_for_s"] = round(self._clock() - self._opened_at, 3)
        return f"breaker:{self.name}", state != OPEN, detail

    def close(self) -> None:
        """Deregister from ``healthz()`` (engines call this on shutdown)."""
        obs.unregister_health_source(self)
