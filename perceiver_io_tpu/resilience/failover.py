"""Router-side failover policy: which replica errors displace a request to
another replica, and how many placements one request may burn.

The engine-side taxonomy (:mod:`perceiver_io_tpu.resilience.retry`) answers
"is retrying *this dispatch* sane?"; this module answers the router's
question one level up: "is retrying *on a different replica* sane?" The two
differ in exactly three places:

- **admission refusals re-route**: a ``RejectedError`` (bounded queue full,
  breaker open, replica draining) is FATAL engine-side — retrying the same
  engine re-asks a full queue — but it is precisely the signal that another
  replica should take the work. Load-aware failover IS re-routing rejections.
- **deadline expiry never re-routes**: a ``DeadlineExceeded`` request is dead
  on every replica; placing it again burns capacity on work whose caller
  already gave up. (It must be carved out explicitly — it subclasses
  ``TimeoutError``, which the transient classifier would happily retry.)
- **a dead replica is transient-class**: ``kill -9`` surfaces router-side as
  connection reset/refused/EOF on the RPC socket — the tunnel-drop signature
  the taxonomy already classifies transient — so in-flight requests on a
  killed replica re-route instead of failing their callers. The request was
  ACCEPTED by the router; acceptance is the router's delivery promise.

At-most-once delivery: the router re-routes only requests for which NO
response was received. A replica may have executed work whose response died
with it — inference is idempotent, so re-execution is safe — but a completed
(delivered) request is never dispatched again.
"""

from __future__ import annotations

import dataclasses

from perceiver_io_tpu.resilience.retry import (
    DeadlineExceeded,
    RejectedError,
    RetryPolicy,
    is_transient,
)

REROUTE = "reroute"
FAIL = "fail"


class AffinityLost(RuntimeError):
    """The replica holding this session's cached state (latents) is gone —
    the request CANNOT be transparently re-routed because the state it
    referenced died with the replica. The caller re-establishes the session
    (re-encode) on whichever replica the router pins next; the router drops
    the dead pin so the re-encode lands on a live replica (spill-on-death)."""


@dataclasses.dataclass(frozen=True)
class FailoverPolicy:
    """How a router re-places failed requests.

    ``max_attempts`` counts total placements (1 = never fail over).
    ``reroute_rejections``: treat admission refusals (queue full / breaker
    open / draining) as displacement signals — on by default, the
    load-shedding-becomes-load-balancing behavior. ``backoff`` paces the
    attempts (default: immediate — a dead replica is already detected, and
    the next placement goes elsewhere; pacing matters only when the whole
    fleet is refusing).
    """

    max_attempts: int = 3
    reroute_rejections: bool = True
    backoff: RetryPolicy = dataclasses.field(
        default_factory=lambda: RetryPolicy(max_retries=0, base_s=0.0,
                                            jitter=0.0)
    )

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {self.max_attempts}"
            )

    def classify(self, error: BaseException) -> str:
        """``'reroute'`` (place on another replica) or ``'fail'`` (the
        caller sees this error)."""
        if isinstance(error, (DeadlineExceeded, AffinityLost)):
            # dead-on-arrival everywhere / state died with the replica —
            # both checked BEFORE the transient classes they subclass or
            # resemble would claim them
            return FAIL
        if isinstance(error, RejectedError):
            return REROUTE if self.reroute_rejections else FAIL
        return REROUTE if is_transient(error) else FAIL

    def should_reroute(self, error: BaseException, attempt: int) -> bool:
        """``attempt`` is 1-based (the placement that just failed)."""
        return attempt < self.max_attempts and self.classify(error) == REROUTE
