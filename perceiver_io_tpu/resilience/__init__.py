"""Self-healing primitives for the runtime paths (SURVEY.md §5, actuation).

r7 built the *detection* half of the reliability story — heartbeats, stall
diagnostics, ``/healthz``. This package is the *actuation* half, plus the
chaos substrate that proves it works without real hardware failures:

- :mod:`faults` — deterministic, test-seedable fault injection (transient
  errors, wedged-dispatch hangs, host slowdowns, NaN corruption) behind
  no-op-by-default hooks at the dispatch sites; env-gated via ``PIT_FAULTS``.
- :mod:`retry` — the error taxonomy (transient vs fatal, with the measured
  scoped-VMEM-OOM carve-out) and capped exponential backoff with jitter.
- :mod:`breaker` — a circuit breaker (closed → open on consecutive failures
  or heartbeat stalls → half-open probe), exported to the metrics registry
  and ``healthz()``.
- :mod:`failover` — the router-side placement policy: which replica errors
  displace a request to ANOTHER replica (rejections and dead-replica socket
  errors re-route, deadline expiry and lost session affinity never do), and
  how many placements one request may burn.
- :mod:`multihost` — bounded-exit failure detection for multi-host
  training: the KV-store peer-liveness monitor and the per-step deadline,
  both exiting with :data:`~perceiver_io_tpu.resilience.multihost
  .EXIT_TRANSIENT` so restart-the-world supervision relaunches the job.
- :mod:`elastic` — the in-process alternative to restart-the-world:
  shrink/grow the world on a peer-death verdict without relaunching
  survivors, with peer-redundant in-memory checkpoints (buddy mirrors)
  and hot-spare join; degrades to :mod:`multihost` bounded exit below
  the quorum floor.

Consumers: ``inference/engine.py`` (deadline shedding, bounded-queue
admission, transient re-dispatch, breaker-gated submission),
``training/trainer.py`` (bad-step skip/rollback, dispatch retry,
``fit_with_recovery``), ``data/download.py`` (transient-HTTP backoff).

Importing this package never initializes a jax backend.
"""

from perceiver_io_tpu.resilience.breaker import BreakerOpen, CircuitBreaker
from perceiver_io_tpu.resilience.elastic import (
    BuddyMirror,
    BuddyStore,
    ElasticConfig,
    ElasticRuntime,
)
from perceiver_io_tpu.resilience.failover import AffinityLost, FailoverPolicy
from perceiver_io_tpu.resilience.faults import (
    FaultInjector,
    FaultSpec,
    InjectedFatalError,
    InjectedTransientError,
)
from perceiver_io_tpu.resilience.multihost import (
    EXIT_TRANSIENT,
    InMemoryKV,
    PeerLivenessMonitor,
    StepDeadline,
    abort_transient,
)
from perceiver_io_tpu.resilience.retry import (
    DeadlineExceeded,
    RejectedError,
    RetryPolicy,
    call_with_retry,
    classify_error,
    is_transient,
)

__all__ = [
    "AffinityLost",
    "BreakerOpen",
    "BuddyMirror",
    "BuddyStore",
    "CircuitBreaker",
    "DeadlineExceeded",
    "ElasticConfig",
    "ElasticRuntime",
    "EXIT_TRANSIENT",
    "FailoverPolicy",
    "FaultInjector",
    "FaultSpec",
    "InMemoryKV",
    "InjectedFatalError",
    "InjectedTransientError",
    "PeerLivenessMonitor",
    "RejectedError",
    "RetryPolicy",
    "StepDeadline",
    "abort_transient",
    "call_with_retry",
    "classify_error",
    "is_transient",
]
