"""The serving-side deployment loop: watch → gate → gated hot-swap.

``CheckpointWatcher`` polls a publish directory for complete, non-quarantined
publications (atomic-rename format, ``deploy/publication.py``).
``ModelDeployer`` drives the loop: every new publication is loaded, run
through the :class:`~perceiver_io_tpu.deploy.gate.AdmissionGate` *before any
serving surface hears about it*, and only a passing tree flows into the swap
target. Failure at any layer quarantines the publication (sticky marker +
``deploy_rejected_total{reason}``) so it is never re-attempted — by this
process or any other.

Two swap targets cover the serving topologies:

- :class:`EngineSwapTarget` — a single in-process ``ServingEngine`` /
  ``MLMServer``: hot-swap via ``update_params`` (re-cast/re-quantized under
  the engine's serving mode — int8w fleets re-quantize here), then BAKE:
  watch the engine's SLO burn and breaker for a window; regression swaps the
  previous tree straight back (kept in memory — rollback is an install, not
  a load).
- :class:`RouterSwapTarget` — the multi-replica fabric: the publication
  flows into ``Router.rolling_update`` as a ``{"kind": "publication"}``
  params spec (each replica loads it digest-verified), one replica at a
  time with the r12 bake window; post-swap SLO-burn/breaker regression rolls
  the WHOLE fleet back to the incumbent (the router's own auto-rollback).

``deploy.swap`` is a ``PIT_FAULTS`` site: an injected raise fails the swap
(rollback + quarantine) — every failure path of the loop is drillable.

The deployer runs on a daemon thread (``start()``/``stop()``); ``stop()``
WAITS for an in-progress deployment to finish, so a SIGTERM drain never
exits mid-swap — the fleet is always wholly on one tree (``cli/serve.py``
wires this into its drain path).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.deploy.gate import REASONS, AdmissionGate
from perceiver_io_tpu.deploy.publication import (
    PublicationInfo,
    list_publications,
    load_publication,
    quarantine,
)
from perceiver_io_tpu.resilience import faults


class CheckpointWatcher:
    """Detects new publications: complete (manifest present — i.e. the
    atomic rename landed), not quarantined, step above ``min_step`` and not
    seen before. Pure detection; the deployer owns judgment."""

    def __init__(self, publish_dir: str, min_step: int = -1):
        self.publish_dir = publish_dir
        self.min_step = min_step
        self._seen: set = set()

    def poll(self) -> List[PublicationInfo]:
        """New publications in step order (each returned exactly once)."""
        fresh = []
        for info in list_publications(self.publish_dir):
            if info.step <= self.min_step or info.step in self._seen:
                continue
            self._seen.add(info.step)
            fresh.append(info)
        return fresh


def swap_window_stats(completions, swap_times, window_s: float = 0.5):
    """Attribute request latencies to swap windows: the per-swap latency
    *blip* methodology ``tools/deploy_bench.py`` and ``tools/load_bench.py
    --publish_every_s`` share (PERF.md §Deployment).

    ``completions``: ``(t_done_monotonic, latency_s)`` pairs for every
    delivered request; ``swap_times``: one entry per completed swap — a
    monotonic stamp, or a ``(t_start, t_end)`` interval (the honest form
    for fleet rolls, whose install-plus-bake spans seconds: a point stamp
    at the end would misattribute the early replicas' installs to steady
    state). A request belongs to a swap window when it completed within
    ±``window_s`` of the stamp/interval. Returns p99s in SECONDS:
    steady-state (outside every window), per-swap, and the worst swap
    window — the blip is ``p99_swap / p99_steady``.
    """

    def p99(vals):
        v = sorted(vals)
        return v[min(len(v) - 1, int(0.99 * len(v)))] if v else None

    spans = [ts if isinstance(ts, (tuple, list)) else (ts, ts)
             for ts in swap_times]
    steady, per_swap = [], [[] for _ in spans]
    for t_done, lat in completions:
        hit = False
        for i, (lo, hi) in enumerate(spans):
            if lo - window_s <= t_done <= hi + window_s:
                per_swap[i].append(lat)
                hit = True
        if not hit:
            steady.append(lat)
    swap_p99s = [p99(v) for v in per_swap]
    observed = [p for p in swap_p99s if p is not None]
    return {
        "window_s": window_s,
        "steady_n": len(steady),
        "p99_steady_s": p99(steady),
        "per_swap_p99_s": swap_p99s,
        "per_swap_n": [len(v) for v in per_swap],
        "p99_swap_s": max(observed) if observed else None,
    }


# -- swap targets -------------------------------------------------------------


def _bake_engines(engines, bake_s: float, burn_threshold: float,
                  poll_s: float, min_requests: int) -> Optional[str]:
    """Post-swap observation over in-process engines (the single-process
    sibling of ``Router._bake``): returns a regression reason or None.
    ``min_requests`` > 0 extends the window (up to 4x) until that much
    post-swap traffic was actually served — an idle bake proves nothing."""
    engines = list(engines)
    t0 = time.monotonic()
    base = sum(e.requests_served for e in engines)
    while True:
        for e in engines:
            if e.breaker is not None and e.breaker.state == "open":
                return "breaker opened post-swap"
            t = e.slo_tracker
            if (t is not None and t.sample_count() >= t.slo.min_samples):
                burn = t.burn_rate()
                if burn > burn_threshold:
                    return (f"SLO burn {burn:.2f} exceeded threshold "
                            f"{burn_threshold:g} post-swap")
        now = time.monotonic()
        if now - t0 >= bake_s:
            served = sum(e.requests_served for e in engines) - base
            if (min_requests <= 0 or served >= min_requests
                    or now - t0 >= 4 * bake_s):
                return None
        time.sleep(poll_s)


class EngineSwapTarget:
    """Gated hot-swap into one in-process engine family (``ServingEngine``
    or ``MLMServer`` — anything with ``update_params``). Keeps the incumbent
    RAW tree in memory so a failed bake rolls back instantly.

    ``last_swap_installed`` / ``last_swap_rolled_back`` record what the most
    recent :meth:`swap` actually DID — the deployer classifies a refusal as
    a rollback only when a tree was installed and the incumbent restored,
    never as a phantom."""

    def __init__(self, target, incumbent, bake_s: float = 1.0,
                 burn_threshold: float = 2.0, poll_s: float = 0.05,
                 min_bake_requests: int = 0,
                 engines: Optional[List[Any]] = None):
        self.target = target
        self._current = incumbent
        self.bake_s = bake_s
        self.burn_threshold = burn_threshold
        self.poll_s = poll_s
        self.min_bake_requests = min_bake_requests
        self.last_swap_installed = False
        self.last_swap_rolled_back = False
        if engines is None:
            # an MLMServer exposes its three engines; a ServingEngine is one
            engines = ([target.engine, target.encoder, target.decoder]
                       if hasattr(target, "encoder") else [target])
        self._engines = engines

    @property
    def current(self):
        return self._current

    def swap(self, tree, info: PublicationInfo) -> Tuple[bool, Optional[str]]:
        self.last_swap_installed = False
        self.last_swap_rolled_back = False
        prev = self._current
        self.target.update_params(tree)  # raising here installed NOTHING
        self.last_swap_installed = True
        try:
            reason = _bake_engines(self._engines, self.bake_s,
                                   self.burn_threshold, self.poll_s,
                                   self.min_bake_requests)
        except Exception as e:
            # the candidate IS installed at this point: a bake that dies
            # (engine closed under a concurrent drain, …) must not leave a
            # quarantined tree serving — roll back, then report
            reason = f"bake failed: {type(e).__name__}: {e}"
        if reason is not None:
            # instant rollback: the previous raw tree re-prepares and
            # installs between micro-batches, exactly like the swap did
            try:
                self.target.update_params(prev)
                self.last_swap_rolled_back = True
            except Exception as e:
                reason += (f"; ROLLBACK FAILED ({type(e).__name__}: {e}) — "
                           "the rejected candidate may still be serving")
            return False, reason
        self._current = tree
        return True, None


class RouterSwapTarget:
    """Gated rollout through ``Router.rolling_update``: replicas realize the
    ``{"kind": "publication", "path": ...}`` spec themselves (digest-verified
    load on the replica — ``serving/replica.py``), the router bakes each
    swap and auto-rolls the whole fleet back on regression."""

    def __init__(self, router, bake_s: float = 1.0,
                 burn_threshold: float = 2.0, poll_s: float = 0.05,
                 min_bake_requests: int = 0,
                 update_timeout_s: Optional[float] = None,
                 spec_fn: Optional[Callable[[PublicationInfo], Dict]] = None):
        self.router = router
        self.bake_s = bake_s
        self.burn_threshold = burn_threshold
        self.poll_s = poll_s
        self.min_bake_requests = min_bake_requests
        self.update_timeout_s = update_timeout_s
        self.spec_fn = spec_fn
        self.last_report: Optional[Dict[str, Any]] = None
        self.last_swap_installed = False
        self.last_swap_rolled_back = False

    def swap(self, tree, info: PublicationInfo) -> Tuple[bool, Optional[str]]:
        self.last_swap_installed = False
        self.last_swap_rolled_back = False
        spec = (self.spec_fn(info) if self.spec_fn is not None
                else {"kind": "publication", "path": info.path,
                      "step": info.step})
        report = self.router.rolling_update(
            spec, bake_s=self.bake_s, burn_threshold=self.burn_threshold,
            poll_s=self.poll_s, min_bake_requests=self.min_bake_requests,
            update_timeout_s=self.update_timeout_s,
        )
        self.last_report = report
        self.last_swap_installed = bool(report.get("updated"))
        self.last_swap_rolled_back = bool(report.get("rolled_back"))
        if report.get("rolled_back"):
            return False, report.get("reason") or "fleet rolled back"
        if not report.get("updated"):
            # nothing installed anywhere — a failed swap, NOT a rollback
            return False, "no replica accepted the update"
        return True, None


# -- the loop -----------------------------------------------------------------


class ModelDeployer:
    """watch → load → gate → gated swap, with quarantine on every failure.

    ``target.swap(tree, info) -> (ok, reason)`` owns rollback semantics (see
    the two targets above); the deployer owns detection, gating, quarantine,
    counters, and the thread. ``on_deployed(record)`` fires after every
    processed publication — ``record["action"]`` is ``swapped`` /
    ``rejected`` / ``rolled_back``.
    """

    # pitlint PIT-LOCK: the history log is appended by whichever thread runs
    # a deployment and read by stats pollers; deploy_once runs with _busy
    # already held by poll_once (the one-deployment-at-a-time critical
    # section), so it is declared rather than re-acquiring
    _guarded_by = {"history": "_busy"}
    _assumes_locked = ("deploy_once",)

    def __init__(
        self,
        publish_dir: str,
        gate,
        target,
        poll_s: float = 2.0,
        name: str = "deploy",
        registry: Optional[obs.MetricsRegistry] = None,
        on_deployed: Optional[Callable[[Dict[str, Any]], None]] = None,
        min_step: int = -1,
    ):
        """``gate``: an :class:`AdmissionGate`, or a zero-arg factory for
        one — the factory is resolved LAZILY on the watcher thread at the
        first poll, keeping the gate's golden-program compile off the
        caller's startup path (``cli/serve.py`` must serve immediately even
        when no publication ever arrives). ``min_step``: publications at or
        below this step are ignored — a restarted process passes the step
        of the checkpoint it booted from, so the backlog of older
        publications is neither replayed onto traffic nor mislabeled
        rejected."""
        self.watcher = CheckpointWatcher(publish_dir, min_step=min_step)
        self._gate = gate if hasattr(gate, "check") else None
        self._gate_factory = None if self._gate is not None else gate
        self.target = target
        self.poll_s = poll_s
        self.name = name
        self.on_deployed = on_deployed
        self.history: List[Dict[str, Any]] = []
        self._busy = threading.Lock()  # held across one whole deployment
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = registry if registry is not None else obs.get_registry()
        self._m_seen = reg.counter(
            "deploy_publications_seen_total",
            "complete publications the watcher detected", {"deploy": name})
        self._m_swaps = reg.counter(
            "deploy_swaps_total",
            "gated swaps that completed and baked healthy", {"deploy": name})
        self._m_rollbacks = reg.counter(
            "deploy_rollbacks_total",
            "swaps rolled back on post-swap regression", {"deploy": name})
        self._m_rejected = {
            reason: reg.counter(
                "deploy_rejected_total",
                "publications refused before or after the swap, by reason "
                "(each is quarantined and never re-attempted)",
                {"deploy": name, "reason": reason})
            for reason in REASONS
        }
        self._m_step = reg.gauge(
            "deploy_current_step",
            "step of the newest publication serving traffic (0 = the boot "
            "tree)", {"deploy": name})

    @property
    def gate(self) -> AdmissionGate:
        if self._gate is None:
            self._gate = self._gate_factory()
        return self._gate

    # -- one publication -----------------------------------------------------

    def _reject(self, info: PublicationInfo, reason: str, detail: str,
                rolled_back: bool = False) -> Dict[str, Any]:
        reason = reason if reason in REASONS else "gate_error"
        quarantine(info.path, f"{reason}: {detail}")
        self._m_rejected[reason].inc()
        if rolled_back:
            self._m_rollbacks.inc()
        return {
            "action": "rolled_back" if rolled_back else "rejected",
            "step": info.step, "reason": reason, "detail": detail,
        }

    def deploy_once(self, info: PublicationInfo) -> Dict[str, Any]:
        """Process ONE publication end to end; returns the history record."""
        t0 = time.monotonic()
        record: Dict[str, Any]
        try:
            tree, manifest = load_publication(info.path, verify_digest=False)
        except Exception as e:  # unreadable payload (tampered npz, IO error)
            record = self._reject(info, "unreadable",
                                  f"{type(e).__name__}: {e}")
        else:
            result = self.gate.check(tree, manifest)
            if not result.ok:
                record = self._reject(info, result.reason or "gate_error",
                                      result.detail)
                record["gate_s"] = result.seconds
            else:
                t_swap = time.monotonic()
                try:
                    faults.inject("deploy.swap")  # chaos hook
                    ok, reason = self.target.swap(tree, info)
                except Exception as e:
                    # the targets own rollback: an exception ESCAPING swap
                    # means nothing was installed (update_params raised, or
                    # the injected pre-swap fault fired) or the target
                    # already restored the incumbent — record a failed
                    # swap, not a rollback
                    reason = f"{type(e).__name__}: {e}"
                    record = self._reject(info, "swap_failed", reason)
                else:
                    if ok:
                        self.gate.set_incumbent(tree)
                        self._m_swaps.inc()
                        self._m_step.set(float(info.step))
                        record = {"action": "swapped", "step": info.step,
                                  "reason": None, "detail": ""}
                    else:
                        # a refusal is a ROLLBACK only if the target
                        # actually installed something and restored the
                        # incumbent; "no replica accepted" must not count
                        # phantom rollbacks
                        installed = getattr(self.target,
                                            "last_swap_installed", True)
                        record = self._reject(
                            info,
                            "post_swap_regression" if installed
                            else "swap_failed",
                            reason or "",
                            rolled_back=getattr(
                                self.target, "last_swap_rolled_back",
                                installed))
                record["gate_s"] = result.seconds
                record["t_swap"] = t_swap  # install START (fleet rolls can
                # span seconds of bake; blip attribution needs the interval)
                record["swap_s"] = time.monotonic() - t_swap
        record["t_done"] = time.monotonic()
        record["seconds"] = record["t_done"] - t0
        if "t_swap" in record:
            # the install-start → bake-end interval as a trace-less context
            # span: trace assembly overlays it on whatever requests were in
            # flight (the swap-blip window, now attributable per trace)
            obs.record_span(
                "deploy_swap", None, record["t_swap"],
                record["t_done"] - record["t_swap"], deploy=self.name,
                step=info.step, action=record["action"])
        self.history.append(record)
        obs.event("deploy_result", deploy=self.name, **{
            k: record.get(k) for k in ("action", "step", "reason", "detail")})
        if self.on_deployed is not None:
            try:
                self.on_deployed(dict(record))
            except Exception:
                pass  # a callback must never take the loop down
        return record

    def poll_once(self) -> List[Dict[str, Any]]:
        """One synchronous sweep (the loop body; tests call it directly)."""
        records = []
        for info in self.watcher.poll():
            self._m_seen.inc()
            with self._busy:
                if self._stop.is_set():
                    break
                records.append(self.deploy_once(info))
        return records

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ModelDeployer":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, name=f"{self.name}-watcher", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_once()
            except Exception as e:  # the loop survives anything
                obs.event("deploy_loop_error", deploy=self.name,
                          error=f"{type(e).__name__}: {e}")

    def stop(self, timeout_s: float = 120.0) -> bool:
        """Stop the loop, WAITING for an in-progress deployment: on return
        the fleet is wholly on one tree (swap completed or rolled back) —
        the SIGTERM-drain contract. Returns False if the wait timed out."""
        self._stop.set()
        ok = True
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            ok = not self._thread.is_alive()
            if ok:
                self._thread = None
        else:
            # programmatic (never-started) use: just ensure no deploy_once
            # is mid-flight on some caller thread
            ok = self._busy.acquire(timeout=timeout_s)
            if ok:
                self._busy.release()
        return ok

    def stats(self) -> Dict[str, Any]:
        return {
            "swaps": int(self._m_swaps.value),
            "rollbacks": int(self._m_rollbacks.value),
            "rejected": {r: int(c.value)
                         for r, c in self._m_rejected.items() if c.value},
            "current_step": int(self._m_step.value),
            "seen": int(self._m_seen.value),
        }

    def __enter__(self) -> "ModelDeployer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
