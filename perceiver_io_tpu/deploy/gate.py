"""The admission gate: no tree reaches a replica without passing it.

The deployment loop's safety property is *provable non-admission*: a bad,
torn, or corrupt publication must be structurally unable to reach traffic.
The gate is the single choke point — the deployer hands every detected
publication through :meth:`AdmissionGate.check` before any swap surface
(``ServingEngine.update_params`` / ``Router.rolling_update``) hears about
it. Four independent layers, each catching a failure mode the others cannot:

1. **digest** — recompute the content digest over the loaded tree and match
   the manifest. Catches bit corruption and tampering between publish and
   load (a torn WRITE cannot exist: publication is an atomic rename).
2. **finite scan** — every floating leaf must be all-finite. Catches a
   poisoned training run (NaN moments published before the trainer's own
   guards tripped) whose digest *verifies* — the digest proves provenance,
   not health.
3. **golden forward** — run the candidate on a fixed golden batch; outputs
   must be finite AND within a configurable quality bound of the incumbent
   tree's outputs on the same batch. Catches finite-but-garbage trees (a
   scale bug, a wrong-step restore) that neither hash nor scan can see.
   Default quality metric: relative mean absolute deviation from the
   incumbent's outputs (an online-refresh candidate continues the same
   run — its outputs live in the same regime; a garbage tree's do not).
   Pass ``quality_fn(outputs) -> float`` (lower = better, e.g. golden-batch
   loss) for a task metric instead: the candidate must then score within
   ``quality_tol`` of the incumbent's score.
4. **prewarm** — an optional callable run with the validated tree LAST, so
   the swap never pays a compile wall mid-traffic (for a same-family tree
   the engines' programs already fit — the hook matters when avals change:
   dtype/sharding/quantization drift). A raising prewarm is a gate failure.

``deploy.gate`` is a ``PIT_FAULTS`` site: an injected raise makes the gate
itself fail (counted and quarantined as ``gate_error`` by the deployer) —
the drill that proves a broken gate fails CLOSED, not open.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, Optional, Sequence

import numpy as np

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.resilience import faults
from perceiver_io_tpu.utils.treepath import tree_digest

# normalized rejection reasons — the deploy_rejected_total{reason} label set
# (bounded cardinality; the free-text detail rides the GateResult/event)
REASONS = (
    "digest_mismatch", "nonfinite_params", "nonfinite_outputs", "quality",
    "prewarm_failed", "gate_error", "unreadable", "swap_failed",
    "post_swap_regression",
)


@dataclasses.dataclass(frozen=True)
class GateResult:
    ok: bool
    reason: Optional[str] = None     # one of REASONS when not ok
    detail: str = ""                 # free text for events/logs
    checks: Dict[str, Any] = dataclasses.field(default_factory=dict)
    seconds: float = 0.0


def _all_finite(tree) -> Optional[str]:
    """Key path of the first non-finite floating leaf, or None."""
    import jax

    from perceiver_io_tpu.utils.treepath import simple_keystr

    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        a = np.asarray(leaf)
        if np.issubdtype(a.dtype, np.floating) and not np.isfinite(a).all():
            return simple_keystr(path)
    return None


class AdmissionGate:
    """Validates candidate param trees against an incumbent.

    Args:
      apply_fn: pure ``(params, *golden_inputs) -> outputs`` — the serving
        forward (or any representative program).
      golden_inputs: the fixed golden batch the forward runs on.
      incumbent_params: the currently-served tree (the quality reference).
        Call :meth:`set_incumbent` after every successful swap so the next
        candidate is judged against what is actually serving.
      quality_tol: bound on the quality check. Default metric: relative mean
        absolute deviation of candidate outputs from incumbent outputs
        (``mean|c-i| / (mean|i|+eps) <= quality_tol``). With ``quality_fn``:
        ``quality_fn(candidate_out) <= quality_fn(incumbent_out) +
        quality_tol``.
      quality_fn: optional scalar scorer over the forward's outputs (lower =
        better; e.g. golden-batch loss).
      prewarm: optional hook run with the validated tree (AOT prewarm /
        compile under the new fingerprint) — raising fails the gate.
    """

    def __init__(
        self,
        apply_fn: Callable[..., Any],
        golden_inputs: Sequence[np.ndarray],
        incumbent_params,
        quality_tol: float = 0.5,
        quality_fn: Optional[Callable[[Any], float]] = None,
        prewarm: Optional[Callable[[Any], None]] = None,
        registry: Optional[obs.MetricsRegistry] = None,
        name: str = "deploy",
    ):
        import jax

        if quality_tol <= 0:
            raise ValueError(f"quality_tol must be > 0, got {quality_tol}")
        self.name = name
        self.quality_tol = float(quality_tol)
        self.quality_fn = quality_fn
        self.prewarm = prewarm
        self._golden = tuple(np.asarray(a) for a in golden_inputs)
        # one jitted program for both incumbent and candidates (same family
        # => same treedef/avals => one compile, paid at gate construction
        # time rather than on the first publication)
        self._forward = jax.jit(lambda p, inputs: apply_fn(p, *inputs))
        self._incumbent_out = None
        # set_incumbent is eager, so construction also pays the golden
        # program's ONE compile here; for the serving CLI this whole
        # constructor runs lazily on the deployer thread (ModelDeployer
        # gate factory), off the serve startup path
        self.set_incumbent(incumbent_params)
        reg = registry if registry is not None else obs.get_registry()
        self._m_seconds = reg.histogram(
            "deploy_gate_seconds",
            "wall seconds one admission-gate evaluation took",
            {"gate": name})

    # -- incumbent management ------------------------------------------------

    def set_incumbent(self, params) -> None:
        """Adopt ``params`` as the quality reference (call after a
        successful swap). Only the golden OUTPUTS are kept (the gate never
        needs the tree again — no second full-model copy lives here), and
        they are computed EAGERLY: on return, a ``check()`` can never mix
        an old reference output with a new incumbent."""
        import jax

        self._incumbent_out = jax.device_get(
            self._forward(params, self._golden))

    def _incumbent_outputs(self):
        return self._incumbent_out

    # -- the gate ------------------------------------------------------------

    def check(self, candidate, manifest: Optional[Dict[str, Any]] = None,
              ) -> GateResult:
        """Run every layer; returns a :class:`GateResult` (never raises —
        an exception inside the gate is itself a rejection: fail CLOSED)."""
        import jax

        t0 = time.monotonic()
        checks: Dict[str, Any] = {}
        try:
            faults.inject("deploy.gate")  # chaos hook (no-op by default)

            # 1. provenance: the loaded tree is the published tree
            if manifest is not None and manifest.get("digest"):
                got = tree_digest(candidate)
                checks["digest"] = got == manifest["digest"]
                if not checks["digest"]:
                    return self._done(GateResult(
                        False, "digest_mismatch",
                        f"content digest {got[:12]} != manifest "
                        f"{str(manifest['digest'])[:12]}",
                        checks), t0)

            # 2. health: every floating leaf finite
            bad = _all_finite(candidate)
            checks["finite_params"] = bad is None
            if bad is not None:
                return self._done(GateResult(
                    False, "nonfinite_params",
                    f"non-finite values at param leaf {bad!r}", checks), t0)

            # 3. behavior: golden forward, finite + within quality bound
            out = jax.device_get(self._forward(candidate, self._golden))
            bad = _all_finite(out)
            checks["finite_outputs"] = bad is None
            if bad is not None:
                return self._done(GateResult(
                    False, "nonfinite_outputs",
                    "golden-batch forward produced non-finite outputs",
                    checks), t0)
            inc = self._incumbent_outputs()
            if self.quality_fn is not None:
                q_cand = float(self.quality_fn(out))
                q_inc = float(self.quality_fn(inc))
                checks["quality"] = {"candidate": q_cand, "incumbent": q_inc}
                ok = np.isfinite(q_cand) and q_cand <= q_inc + self.quality_tol
                detail = (f"quality {q_cand:.6g} vs incumbent {q_inc:.6g} "
                          f"(tol {self.quality_tol:g})")
            else:
                c = np.concatenate([np.ravel(np.asarray(x, np.float64))
                                    for x in jax.tree.leaves(out)])
                i = np.concatenate([np.ravel(np.asarray(x, np.float64))
                                    for x in jax.tree.leaves(inc)])
                dev = float(np.mean(np.abs(c - i))
                            / (np.mean(np.abs(i)) + 1e-9))
                checks["quality"] = {"rel_deviation": dev}
                ok = dev <= self.quality_tol
                detail = (f"golden-output relative deviation {dev:.4g} vs "
                          f"incumbent (tol {self.quality_tol:g})")
            if not ok:
                return self._done(GateResult(False, "quality", detail,
                                             checks), t0)

            # 4. no compile wall mid-traffic: prewarm under the new tree
            if self.prewarm is not None:
                try:
                    self.prewarm(candidate)
                    checks["prewarm"] = True
                except Exception as e:
                    checks["prewarm"] = False
                    return self._done(GateResult(
                        False, "prewarm_failed",
                        f"{type(e).__name__}: {e}", checks), t0)

            return self._done(GateResult(True, None, detail, checks), t0)
        except Exception as e:
            # the gate itself failed: fail CLOSED — the tree is NOT admitted
            return self._done(GateResult(
                False, "gate_error", f"{type(e).__name__}: {e}", checks), t0)

    def _done(self, result: GateResult, t0: float) -> GateResult:
        result = dataclasses.replace(
            result, seconds=time.monotonic() - t0)
        self._m_seconds.observe(result.seconds)
        obs.event("deploy_gate", gate=self.name, ok=result.ok,
                  reason=result.reason, detail=result.detail,
                  seconds=round(result.seconds, 4))
        return result
