"""Atomic checkpoint publication: the wire format between trainer and fleet.

A *publication* is one directory under the publish root::

    publish_dir/
      step_00000040/
        params.npz      # flattened param tree (simple_keystr -> array)
        MANIFEST.json   # step, val metrics, content digest, package version
      step_00000080/...
      .tmp-step_00000120-77123/   # an in-progress publish (readers skip it)

Atomicity is the whole point of the format: the payload and manifest are
written into a ``.tmp-*`` sibling in the SAME directory and the finished
directory lands with one ``os.replace`` — a rename on the same filesystem is
atomic, so a reader either sees the complete publication or nothing. There
is no observable torn state (``tests/test_deploy.py`` races a reader against
a publishing thread to pin this).

The manifest carries a sha256 CONTENT DIGEST over the param tree
(``utils/treepath.tree_digest`` — same definition the checkpoint sidecars
use), so the serving-side admission gate can prove the tree it loaded is the
tree the trainer published: silent bit corruption or tampering between the
two halves is a digest mismatch, not a served model.

A rejected publication is *quarantined* in place: a ``REJECTED.json`` marker
written next to the manifest. Quarantine is sticky across processes — every
scanner skips marked publications, so a bad tree is never re-attempted.

Fault sites (``PIT_FAULTS``): ``deploy.publish`` supports ``transient`` /
``fatal`` raises (a publish that dies mid-write leaves only a ``.tmp-*``
residue) and ``nan`` corruption — the NaN tree is poisoned BEFORE the digest
is computed, so its digest *verifies* and only the gate's all-finite scan
can stop it: the drill that proves the gate layers are independent.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
import warnings
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import perceiver_io_tpu.obs as obs
from perceiver_io_tpu.resilience import faults
from perceiver_io_tpu.utils.treepath import digest_named, flatten_named

MANIFEST_NAME = "MANIFEST.json"
PARAMS_NAME = "params.npz"
REJECT_MARKER = "REJECTED.json"
TMP_PREFIX = ".tmp-"
MANIFEST_FORMAT = 1


class DigestMismatchError(ValueError):
    """A publication's params do not hash to the manifest's digest —
    corruption or tampering between publish and load."""


@dataclasses.dataclass(frozen=True)
class PublicationInfo:
    """One complete publication as a scanner sees it."""

    path: str
    step: int
    manifest: Dict[str, Any]

    @property
    def rejected(self) -> bool:
        return os.path.exists(os.path.join(self.path, REJECT_MARKER))


def _package_version() -> str:
    try:
        import perceiver_io_tpu

        return str(getattr(perceiver_io_tpu, "__version__", "0"))
    except Exception:
        return "0"


def publication_name(step: int) -> str:
    return f"step_{int(step):08d}"


def publish_params(
    publish_dir: str,
    step: int,
    params,
    val_metrics: Optional[Dict[str, float]] = None,
    extra: Optional[Dict[str, Any]] = None,
) -> str:
    """Atomically publish ``params`` as ``publish_dir/step_NNNNNNNN``.

    Returns the final publication path. Raises ``FileExistsError`` when the
    step was already published (a publication is immutable — republish under
    a new step). The payload is flattened to host numpy (one ``.npz``), the
    manifest carries the content digest, and the finished directory lands
    with a single same-dir ``os.replace`` — a concurrent reader can never
    observe a half-written publication.
    """
    # chaos hook (no-op unless installed): raise kinds simulate a publish
    # dying mid-write; the NaN kind corrupts BEFORE the digest, so the
    # corrupted tree's digest VERIFIES and only the gate's finite scan can
    # reject it — the layer separation the chaos suite pins
    params = faults.fire("deploy.publish", params)

    publish_dir = os.path.abspath(publish_dir)
    final = os.path.join(publish_dir, publication_name(step))
    if os.path.exists(final):
        raise FileExistsError(f"publication already exists: {final}")
    os.makedirs(publish_dir, exist_ok=True)

    named = flatten_named(params)
    digest = digest_named(named)  # one flatten + host fetch, not two
    manifest = {
        "format": MANIFEST_FORMAT,
        "step": int(step),
        "val_metrics": {k: float(v) for k, v in (val_metrics or {}).items()},
        "digest": digest,
        "leaf_count": len(named),
        "package_version": _package_version(),
        "published_unix_s": round(time.time(), 3),
    }
    if extra:
        manifest["extra"] = extra

    tmp = os.path.join(
        publish_dir, f"{TMP_PREFIX}{publication_name(step)}-{os.getpid()}"
    )
    os.makedirs(tmp, exist_ok=False)
    try:
        with open(os.path.join(tmp, PARAMS_NAME), "wb") as f:
            np.savez(f, **named)
            f.flush()
            os.fsync(f.fileno())
        with open(os.path.join(tmp, MANIFEST_NAME), "w") as f:
            json.dump(manifest, f, indent=2, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        # THE atomic step: the complete payload appears under its final name
        # in one rename (same dir => same filesystem => atomic)
        os.replace(tmp, final)
    except BaseException:
        # a failed publish leaves at most a .tmp-* residue, which every
        # scanner skips — never a half-publication under the final name
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
        raise
    try:  # make the rename durable (best-effort: not all OSes allow it)
        dirfd = os.open(publish_dir, os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)
    except OSError:
        pass
    obs.event("deploy_published", step=int(step), path=final,
              digest=digest[:12])
    return final


def read_manifest(path: str) -> Dict[str, Any]:
    with open(os.path.join(path, MANIFEST_NAME)) as f:
        return json.load(f)


def list_publications(publish_dir: str,
                      include_rejected: bool = False) -> List[PublicationInfo]:
    """Complete publications under ``publish_dir``, ascending by step.

    Skips in-progress ``.tmp-*`` residue and anything without a readable
    manifest (a manifest exists only inside a directory that landed via the
    atomic rename, so "has a manifest" == "is complete"). Quarantined
    publications are skipped unless ``include_rejected``.
    """
    out: List[PublicationInfo] = []
    try:
        entries = sorted(os.listdir(publish_dir))
    except FileNotFoundError:
        return out
    for name in entries:
        if name.startswith(TMP_PREFIX):
            continue
        path = os.path.join(publish_dir, name)
        if not os.path.isdir(path):
            continue
        try:
            manifest = read_manifest(path)
            step = int(manifest["step"])
        except (OSError, ValueError, KeyError, TypeError):
            continue  # no/unreadable manifest: not a publication
        info = PublicationInfo(path=path, step=step, manifest=manifest)
        if info.rejected and not include_rejected:
            continue
        out.append(info)
    out.sort(key=lambda p: p.step)
    return out


def _unflatten(named: Dict[str, np.ndarray]):
    """Rebuild the nested-dict param tree from "/"-joined key paths."""
    tree: Dict[str, Any] = {}
    for key, leaf in named.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = leaf
    return tree


def load_publication(path: str,
                     verify_digest: bool = True) -> Tuple[Any, Dict[str, Any]]:
    """Load one publication as ``(param_tree, manifest)``.

    ``verify_digest=True`` recomputes the content digest over the loaded
    arrays and raises :class:`DigestMismatchError` on mismatch — the
    replica-side defense (``serving/replica.py`` publication specs), so a
    tree corrupted AFTER the router-side gate passed it still cannot be
    installed. The gate itself loads with ``verify_digest=False`` and owns
    the check (one reject counter, one quarantine decision).
    """
    manifest = read_manifest(path)
    with np.load(os.path.join(path, PARAMS_NAME)) as z:
        named = {k: z[k] for k in z.files}
    tree = _unflatten(named)
    if verify_digest:
        got = digest_named(named)
        want = manifest.get("digest")
        if got != want:
            raise DigestMismatchError(
                f"publication {path} digest mismatch: manifest {want!r} vs "
                f"loaded content {got!r} — corrupted or tampered payload"
            )
    return tree, manifest


def quarantine(path: str, reason: str) -> None:
    """Mark a publication rejected (sticky: every scanner skips it, in this
    process and any other, forever — a bad tree is never re-attempted)."""
    marker = {"reason": reason, "rejected_unix_s": round(time.time(), 3)}
    tmp = os.path.join(path, REJECT_MARKER + ".tmp")
    try:
        with open(tmp, "w") as f:
            json.dump(marker, f, indent=2)
        os.replace(tmp, os.path.join(path, REJECT_MARKER))
    except OSError as e:
        # quarantine is bookkeeping: failing to write the marker must not
        # take the deployment loop down (the in-memory seen set still
        # prevents re-attempts this process)
        warnings.warn(f"could not quarantine {path}: {e}", stacklevel=2)
    obs.event("deploy_quarantined", path=path, reason=reason)


def read_quarantine(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(os.path.join(path, REJECT_MARKER)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class CheckpointPublisher:
    """Trainer-side publisher: counters + fail-soft wrapper over
    :func:`publish_params` (a publish failure must cost one warning, never
    the training run)."""

    def __init__(self, publish_dir: str,
                 registry: Optional[obs.MetricsRegistry] = None):
        self.publish_dir = os.path.abspath(publish_dir)
        reg = registry if registry is not None else obs.get_registry()
        self._m_published = reg.counter(
            "deploy_published_total",
            "checkpoint publications landed (atomic rename completed)")
        self._m_failures = reg.counter(
            "deploy_publish_failures_total",
            "publish attempts that raised (training continued)")

    def publish(self, step: int, params,
                val_metrics: Optional[Dict[str, float]] = None,
                extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Publish; returns the publication path, or None on failure (warned
        and counted — the trainer keeps training)."""
        try:
            path = publish_params(self.publish_dir, step, params,
                                  val_metrics=val_metrics, extra=extra)
        except Exception as e:
            self._m_failures.inc()
            warnings.warn(
                f"checkpoint publication at step {step} failed "
                f"({type(e).__name__}: {e}) — training continues; the "
                f"serving side simply never sees this step",
                stacklevel=2,
            )
            obs.event("deploy_publish_failed", step=int(step),
                      error=type(e).__name__)
            return None
        self._m_published.inc()
        return path
