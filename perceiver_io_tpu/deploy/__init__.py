"""Continuous train→serve deployment: validated checkpoint publication and
gated hot-swap (the composition layer over training/, serving/, aot/, quant/
and resilience/ — ROADMAP item 5).

The trainer *publishes* checkpoints on a cadence
(``TrainerConfig.publish_dir`` / ``publish_every_n_steps`` →
:class:`CheckpointPublisher`): one atomic directory per step with the param
tree and a manifest carrying step, val metrics, a sha256 content digest, and
the package version. The serving side *watches* the publish directory
(:class:`ModelDeployer`) and runs every new publication through the
:class:`AdmissionGate` — digest verification, all-finite scan, golden-batch
forward within a quality bound of the incumbent, optional AOT prewarm —
BEFORE any replica sees the tree. A passing tree hot-swaps via
``ServingEngine.update_params`` (:class:`EngineSwapTarget`, with an
SLO/breaker bake and instant in-memory rollback) or rolls across the fleet
via ``Router.rolling_update`` (:class:`RouterSwapTarget`, replicas loading
the publication digest-verified themselves). Any failure quarantines the
publication in place — sticky across processes, counted by reason — so a
bad tree is never re-attempted and provably never reaches traffic.

Chaos: ``PIT_FAULTS`` sites ``deploy.publish`` / ``deploy.gate`` /
``deploy.swap`` make every failure path of the loop drillable
(``tests/test_deploy.py``); ``tools/deploy_bench.py`` measures swap cadence
and the per-swap latency blip under open-loop traffic (PERF.md §Deployment).
"""

from perceiver_io_tpu.deploy.gate import REASONS, AdmissionGate, GateResult
from perceiver_io_tpu.deploy.publication import (
    MANIFEST_NAME,
    PARAMS_NAME,
    REJECT_MARKER,
    CheckpointPublisher,
    DigestMismatchError,
    PublicationInfo,
    list_publications,
    load_publication,
    publication_name,
    publish_params,
    quarantine,
    read_manifest,
    read_quarantine,
)
from perceiver_io_tpu.deploy.watcher import (
    CheckpointWatcher,
    EngineSwapTarget,
    ModelDeployer,
    RouterSwapTarget,
    swap_window_stats,
)
from perceiver_io_tpu.utils.treepath import tree_digest

__all__ = [
    "AdmissionGate",
    "CheckpointPublisher",
    "CheckpointWatcher",
    "DigestMismatchError",
    "EngineSwapTarget",
    "GateResult",
    "MANIFEST_NAME",
    "ModelDeployer",
    "PARAMS_NAME",
    "PublicationInfo",
    "REASONS",
    "REJECT_MARKER",
    "RouterSwapTarget",
    "list_publications",
    "load_publication",
    "publication_name",
    "publish_params",
    "quarantine",
    "read_manifest",
    "read_quarantine",
    "swap_window_stats",
    "tree_digest",
]
