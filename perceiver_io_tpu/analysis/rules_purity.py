"""PIT-JIT: no host side effects inside functions reachable from jitted code.

A clock read, ``np.random`` draw, ``print``, file touch, or ``.item()`` /
``float()`` scalar fetch inside traced code is at best a silent
trace-time-frozen constant and at worst a per-dispatch ~100 ms tunnel round
trip (PERF.md). The compiler never complains — the value just goes stale or
the hot path just gets slow.

Root set (per file):

- functions syntactically handed to the jit family: ``@jax.jit`` /
  ``@partial(jax.jit, ...)`` decorators, and names passed to
  ``jax.jit(f)`` / ``pjit(f)`` / ``pl.pallas_call(kernel, ...)`` /
  ``shard_map(f, ...)`` / ``jax.checkpoint(f)``;
- every function/method in the always-traced modules (``ops/``,
  ``models/`` — the compute core; their code exists to run under ``jit``).

Reachability then propagates through same-file calls: ``name(...)`` to a
function defined in the file, ``self.m(...)`` to a method of any class in
the file. Cross-file reachability is deliberately out of scope — the traced
core is module-local by construction here, and a lint that imports nothing
stays fast and safe.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from perceiver_io_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    dotted_name,
)

_JIT_WRAPPERS = {
    "jit", "jax.jit", "pjit", "jax.pjit",
    "shard_map", "jax.experimental.shard_map.shard_map",
    "pallas_call", "pl.pallas_call",
    "checkpoint", "jax.checkpoint", "jax.remat",
}

_CLOCK_CALLS = {
    "time.time", "time.monotonic", "time.perf_counter", "time.process_time",
    "time.sleep", "time.time_ns", "time.monotonic_ns",
    "time.perf_counter_ns",
}

_HOST_RANDOM_PREFIXES = ("np.random.", "numpy.random.", "random.")

_SHAPE_ATTRS = {"shape", "ndim", "size", "dtype"}


def _qualname(stack: List[str]) -> str:
    return ".".join(stack)


class _DefCollector(ast.NodeVisitor):
    """Every function/method (including nested) with its qualname, plus the
    set of class names (for ``self.m()`` resolution)."""

    def __init__(self):
        self.defs: Dict[str, List[Tuple[str, ast.AST]]] = {}  # bare name ->
        self.by_qual: Dict[str, ast.AST] = {}
        self._stack: List[str] = []

    def _add(self, node):
        qual = _qualname(self._stack + [node.name])
        self.defs.setdefault(node.name, []).append((qual, node))
        self.by_qual[qual] = node
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _add
    visit_AsyncFunctionDef = _add

    def visit_ClassDef(self, node):
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()


def _is_jit_wrapper(func_node: ast.AST) -> bool:
    name = dotted_name(func_node)
    if name is None:
        return False
    return name in _JIT_WRAPPERS or name.endswith(".jit") \
        or name.endswith(".pallas_call")


class JitPurityRule(Rule):
    rule_id = "PIT-JIT"

    # modules whose whole surface is traced code (the compute core)
    PURE_MODULE_PREFIXES = (
        "perceiver_io_tpu/ops/",
        "perceiver_io_tpu/models/",
    )

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        collector = _DefCollector()
        collector.visit(ctx.tree)
        roots = self._roots(ctx, collector)
        reachable = self._propagate(collector, roots)
        findings: List[Finding] = []
        for qual in sorted(reachable):
            node = collector.by_qual[qual]
            findings.extend(self._scan_body(ctx, node, qual, reachable,
                                            collector))
        return findings

    # -- root discovery ------------------------------------------------------

    def _roots(self, ctx: FileContext, collector: _DefCollector) -> Set[str]:
        roots: Set[str] = set()
        if any(ctx.relpath.startswith(p) for p in self.PURE_MODULE_PREFIXES):
            roots.update(collector.by_qual)

        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    target = deco.func if isinstance(deco, ast.Call) else deco
                    if _is_jit_wrapper(target) or (
                            isinstance(deco, ast.Call)
                            and dotted_name(deco.func) in
                            ("partial", "functools.partial")
                            and deco.args
                            and _is_jit_wrapper(deco.args[0])):
                        roots.update(q for q, n in
                                     collector.defs.get(node.name, ())
                                     if n is node)
            elif isinstance(node, ast.Call) and _is_jit_wrapper(node.func):
                for arg in node.args[:1]:  # the wrapped fn is positional 0
                    if isinstance(arg, ast.Name):
                        roots.update(
                            q for q, _ in collector.defs.get(arg.id, ()))
        return roots

    # -- reachability --------------------------------------------------------

    def _propagate(self, collector: _DefCollector,
                   roots: Set[str]) -> Set[str]:
        reachable = set(roots)
        frontier = list(roots)
        while frontier:
            qual = frontier.pop()
            node = collector.by_qual[qual]
            for callee in self._local_callees(node, collector):
                if callee not in reachable:
                    reachable.add(callee)
                    frontier.append(callee)
        return reachable

    def _local_callees(self, node: ast.AST,
                       collector: _DefCollector) -> Iterable[str]:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            if isinstance(sub.func, ast.Name):
                for qual, _ in collector.defs.get(sub.func.id, ()):
                    yield qual
            elif (isinstance(sub.func, ast.Attribute)
                  and isinstance(sub.func.value, ast.Name)
                  and sub.func.value.id == "self"):
                for qual, _ in collector.defs.get(sub.func.attr, ()):
                    yield qual

    # -- the banned-construct scan -------------------------------------------

    def _scan_body(self, ctx: FileContext, func: ast.AST, qual: str,
                   reachable: Set[str],
                   collector: _DefCollector) -> Iterable[Finding]:
        findings: List[Finding] = []

        def walk(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    continue  # scanned on its own iff itself reachable
                if isinstance(child, ast.Call):
                    msg = self._banned(child)
                    if msg:
                        findings.append(self.finding(ctx, child, qual, msg))
                walk(child)

        walk(func)
        return findings

    def _banned(self, call: ast.Call) -> str:
        name = dotted_name(call.func)
        if name in _CLOCK_CALLS:
            return (f"calls {name}() in jit-reachable code (clock reads "
                    f"freeze at trace time)")
        if name and name.startswith(_HOST_RANDOM_PREFIXES):
            return (f"calls {name}() in jit-reachable code (host RNG is "
                    f"trace-time-frozen; use jax.random)")
        if isinstance(call.func, ast.Attribute) and call.func.attr == "item" \
                and not call.args:
            return (".item() in jit-reachable code (host scalar fetch — "
                    "~100 ms over the tunnel)")
        if name in ("print", "open", "input"):
            return (f"calls {name}() in jit-reachable code (host I/O runs at "
                    f"trace time, not per step)")
        if name in ("float", "int") and len(call.args) == 1 \
                and self._is_scalar_fetch(call.args[0]):
            return (f"{name}() scalar fetch in jit-reachable code (device "
                    f"sync — keep values traced)")
        return ""

    @staticmethod
    def _is_scalar_fetch(arg: ast.AST) -> bool:
        """``float(metrics["loss"])``-style fetches; static shape/config math
        (``int(x.shape[0])``, ``float(len(xs))``, literals) stays allowed."""
        if isinstance(arg, ast.Constant) or isinstance(arg, ast.BinOp):
            return False
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Attribute) and sub.attr in _SHAPE_ATTRS:
                return False
            if isinstance(sub, ast.Call) and dotted_name(sub.func) in (
                    "len", "ord", "np.prod", "math.prod"):
                return False
        # bare names (config scalars, bools) stay allowed — the fetch shapes
        # are metrics["loss"]-style subscripts and method-call results
        return isinstance(arg, (ast.Subscript, ast.Call))
