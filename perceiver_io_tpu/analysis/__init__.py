"""pitlint — repo-invariant static analysis + runtime sanitizers.

The port's correctness rests on invariants no compiler checks: torch-parity
param-tree names that sharding regexes key on, jit-purity on the dispatch hot
path (one stray ``.item()`` costs a ~100 ms tunnel round trip, PERF.md),
registered ``PIT_FAULTS`` sites, the one-JSON-line stdout contract of
``tools/`` and ``bench.py``, and lock discipline across the engine/router/
deployer thread soup. This package enforces them by machine:

- **static rules** (:mod:`core` + the ``rules_*`` modules): small AST
  visitors, each with a rule ID, producing file/line findings. Pre-existing
  debt lives in a checked-in baseline file (:data:`core.DEFAULT_BASELINE`)
  so CI blocks only NEW violations; genuinely-fine-forever sites carry an
  inline ``# pitlint: ignore[RULE-ID]`` pragma with the reason on the line.
- **cross-checks** (:mod:`crosscheck`): CPU-only audits that need the real
  code imported — every ``parallel/sharding.py`` path-regex must match at
  least one param path in every ``models/presets.py`` preset tree, so a
  rename cannot silently strand a sharding rule.
- **runtime sanitizers** (:mod:`sanitizers`): ``no_recompile()`` (zero
  ``jax_compilations_total`` delta over a steady-state block),
  ``no_implicit_transfers()`` (``jax.transfer_guard`` armed around engine
  dispatch), and ``record_lock_order()`` (acquisition-graph recording with
  cycle detection — the deadlock linter tier-1 runs).

Entry points: ``tools/lint.py`` (one JSON line, nonzero exit on
non-baselined findings) and ``tests/test_lint.py`` (the tier-1 pass over
``perceiver_io_tpu/``, ``tools/``, and ``bench.py``).
"""

from perceiver_io_tpu.analysis.core import (
    Baseline,
    FileContext,
    Finding,
    Rule,
    all_rules,
    scan_paths,
)
from perceiver_io_tpu.analysis.sanitizers import (
    LockOrderRecorder,
    LockOrderViolation,
    RecompileDetected,
    no_implicit_transfers,
    no_recompile,
    record_lock_order,
)

__all__ = [
    "Baseline",
    "FileContext",
    "Finding",
    "LockOrderRecorder",
    "LockOrderViolation",
    "RecompileDetected",
    "Rule",
    "all_rules",
    "no_implicit_transfers",
    "no_recompile",
    "record_lock_order",
    "scan_paths",
]
