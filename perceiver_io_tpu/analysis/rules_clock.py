"""PIT-CLOCK: elapsed-time math uses a monotonic clock, never wall clock.

``time.time()`` steps under NTP slew/adjustment; a duration computed from it
can be negative or wildly wrong, and these durations feed SLO burn rates,
backoff, and bake windows. ``time.monotonic()`` / ``time.perf_counter()``
are the sanctioned duration clocks; ``time.time()`` remains correct ONLY as
a wall-clock *timestamp* (manifest fields, log correlation).

The rule flags subtractions involving wall-clock values:

- a direct ``time.time()`` operand in a ``-`` expression;
- a name assigned from ``time.time()`` in the same function used in a ``-``
  expression;
- a ``self.<attr>`` assigned from ``time.time()`` anywhere in the class,
  used in a ``-`` expression anywhere in that class.

Sites that subtract wall clocks to *produce another wall-clock timestamp*
(epoch arithmetic) are the rare legitimate exception — they carry the
inline pragma with their reasoning.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from perceiver_io_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    dotted_name,
)


def _is_wallclock_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and dotted_name(node.func) in (
        "time.time", "time.time_ns")


def _self_attr(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return ""


class DurationClockRule(Rule):
    rule_id = "PIT-CLOCK"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        self._check_scope(ctx, ctx.tree, "", findings, set())
        return findings

    def _check_scope(self, ctx: FileContext, scope_node: ast.AST,
                     scope: str, findings: List[Finding],
                     tainted_attrs: Set[str]) -> None:
        """Recurse per def/class scope so tracked names stay local; a class's
        tainted ``self.<attr>`` set is inherited by its methods."""
        if isinstance(scope_node, ast.ClassDef):
            tainted_attrs = tainted_attrs | self._tainted_self_attrs(
                scope_node)
        tainted_names = self._tainted_names(scope_node)

        def walk(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    child_scope = f"{scope}.{child.name}" if scope \
                        else child.name
                    self._check_scope(ctx, child, child_scope, findings,
                                      tainted_attrs)
                    continue
                if isinstance(child, ast.BinOp) \
                        and isinstance(child.op, ast.Sub):
                    for side in (child.left, child.right):
                        why = self._wallclock_operand(
                            side, tainted_names, tainted_attrs)
                        if why:
                            findings.append(self.finding(
                                ctx, child, scope,
                                f"elapsed-time subtraction over wall clock "
                                f"({why}) — use time.monotonic() for "
                                f"durations"))
                            break
                walk(child)

        walk(scope_node)

    def _wallclock_operand(self, node: ast.AST, names: Set[str],
                           attrs: Set[str]) -> str:
        if _is_wallclock_call(node):
            return "time.time() operand"
        if isinstance(node, ast.Name) and node.id in names:
            return f"{node.id!r} was assigned from time.time()"
        a = _self_attr(node)
        if a and a in attrs:
            return f"self.{a} was assigned from time.time()"
        return ""

    @staticmethod
    def _tainted_names(scope_node: ast.AST) -> Set[str]:
        """Names assigned from time.time() directly in this def scope (not
        descending into nested defs — their scopes are checked separately)."""
        out: Set[str] = set()

        def walk(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    continue
                if isinstance(child, ast.Assign) \
                        and _is_wallclock_call(child.value):
                    for t in child.targets:
                        if isinstance(t, ast.Name):
                            out.add(t.id)
                walk(child)

        walk(scope_node)
        return out

    @staticmethod
    def _tainted_self_attrs(cls: ast.ClassDef) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and _is_wallclock_call(node.value):
                for t in node.targets:
                    a = _self_attr(t)
                    if a:
                        out.add(a)
        return out
