"""PIT-SPAN: every literal span name at a record_span site is registered.

The PIT-FAULT pattern applied to distributed tracing: span names are string
literals scattered across router/replica/deploy code, and the assembler
(``obs.reqtrace.assemble_traces`` / ``tools/trace_assemble.py``), the tests,
and the docs all match on them — a renamed or typo'd span would silently
decouple its hop from every assembled trace. The runtime registry is
:data:`perceiver_io_tpu.obs.reqtrace.SPAN_NAMES` (ONE definition — this rule
imports it, stdlib-only at import, so the lint stays CPU-safe); the checked
shapes are ``record_span("name", ...)`` / ``obs.record_span`` /
``reqtrace.record_span`` string-literal first arguments.

The synthesized assembly-side names (``engine``, ``phase:<name>``) never
appear at a record site — they exist only inside the assembler — so the
registry stays exactly the set of *recorded* span names.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from perceiver_io_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    ScopedVisitor,
    dotted_name,
)


def _span_names():
    from perceiver_io_tpu.obs.reqtrace import SPAN_NAMES

    return SPAN_NAMES


def _name_error(name: str) -> Optional[str]:
    registered = _span_names()
    if name in registered:
        return None
    return (f"span name {name!r} is not registered in "
            f"obs.reqtrace.SPAN_NAMES ({', '.join(sorted(registered))})")


class _Visitor(ScopedVisitor):
    def __init__(self, rule: "SpanNameRule", ctx: FileContext):
        super().__init__()
        self.rule = rule
        self.ctx = ctx
        self.findings: List[Finding] = []

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func) or ""
        leaf = name.rsplit(".", 1)[-1]
        if leaf == "record_span" and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                err = _name_error(arg.value)
                if err:
                    self.findings.append(self.rule.finding(
                        self.ctx, arg, self.scope, err))
        self.generic_visit(node)


class SpanNameRule(Rule):
    rule_id = "PIT-SPAN"

    # the registry module itself (docstring examples) and the lint suite's
    # fixtures (strings that MUST contain invalid names for negative tests)
    SELF_EXCLUDED = ("obs/reqtrace.py", "tests/test_lint.py")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.relpath.endswith(self.SELF_EXCLUDED):
            return ()
        visitor = _Visitor(self, ctx)
        visitor.visit(ctx.tree)
        return visitor.findings
