"""PIT-FAULT: every fault-injection site and drill spec names a registered
site+kind.

The runtime half of this contract is r13's ``faults.parse_spec`` validation —
a typo'd ``PIT_FAULTS`` env drill fails loudly at install. This rule is the
static twin: the *instrumented call sites* (``faults.inject/fire/corrupt``,
``FaultSpec(site=...)``) and the *example specs* embedded in tests and docs
are checked against the registered :data:`~perceiver_io_tpu.resilience
.faults.SITES` and kind set at lint time, so a renamed site cannot leave a
dangling hook or a doc teaching a drill that silently injects nothing.

Checked shapes:

- ``faults.inject("site")`` / ``faults.fire("site", x)`` /
  ``faults.corrupt("site", x)`` string-literal first args (module alias or
  direct import);
- f-string sites (``f"engine.dispatch.{name}"``): the literal prefix must be
  a registered suffix-extensible site;
- ``FaultSpec(site="...", kind="...")`` keyword literals;
- ``PIT_FAULTS`` spec strings: env assignments/`setenv` calls in code, plus
  ``PIT_FAULTS="..."`` examples anywhere in the raw source (docstrings) —
  each is run through ``parse_spec``.

Validation imports :mod:`perceiver_io_tpu.resilience.faults` (numpy-only at
import; no backend touch), so the lint stays CPU-safe and there is exactly
ONE registry — the runtime's.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional

from perceiver_io_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    ScopedVisitor,
    dotted_name,
)

_HOOKS = {"inject", "fire", "corrupt"}
# doc examples: only CONCRETE specs (dotted site) are validated — grammar
# teaching text with meta-variables ("site:kind@WHEN") is not a drill
_SPEC_RE = re.compile(r"""PIT_FAULTS\s*=\s*["']([a-z_]+\.[^"'\n]+)["']""")


def _faults():
    from perceiver_io_tpu.resilience import faults

    return faults


def _site_error(site: str) -> Optional[str]:
    try:
        _faults().validate_site(site)
        return None
    except ValueError as e:
        return str(e)


def _prefix_error(prefix: str) -> Optional[str]:
    """An f-string site's literal head must extend a suffix-extensible site
    (``engine.dispatch.`` + runtime engine name)."""
    faults = _faults()
    if any(prefix == s + "." for s in faults._SUFFIXED):
        return None
    return (f"f-string fault site prefix {prefix!r} does not extend a "
            f"registered suffixed site ({', '.join(faults._SUFFIXED)})")


def _spec_error(spec: str) -> Optional[str]:
    try:
        _faults().parse_spec(spec)
        return None
    except ValueError as e:
        return str(e)


class _Visitor(ScopedVisitor):
    def __init__(self, rule: "FaultSiteRule", ctx: FileContext):
        super().__init__()
        self.rule = rule
        self.ctx = ctx
        self.findings: List[Finding] = []

    def _flag(self, node: ast.AST, message: str) -> None:
        self.findings.append(
            self.rule.finding(self.ctx, node, self.scope, message))

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func) or ""
        leaf = name.rsplit(".", 1)[-1]
        if leaf in _HOOKS and (name.startswith("faults.")
                               or name in _HOOKS) and node.args:
            self._check_site_arg(node.args[0])
        elif leaf == "FaultSpec":
            for kw in node.keywords:
                if kw.arg == "site" and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    err = _site_error(kw.value.value)
                    if err:
                        self._flag(kw.value, f"FaultSpec: {err}")
        elif leaf == "setenv" and len(node.args) >= 2:
            k, v = node.args[0], node.args[1]
            if (isinstance(k, ast.Constant) and k.value == "PIT_FAULTS"
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)):
                err = _spec_error(v.value)
                if err:
                    self._flag(v, f"PIT_FAULTS spec: {err}")
        self.generic_visit(node)

    def _check_site_arg(self, arg: ast.AST) -> None:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            err = _site_error(arg.value)
            if err:
                self._flag(arg, err)
        elif isinstance(arg, ast.JoinedStr) and arg.values:
            head = arg.values[0]
            if isinstance(head, ast.Constant) and isinstance(head.value, str):
                err = _prefix_error(head.value)
                if err:
                    self._flag(arg, err)

    def visit_Assign(self, node: ast.Assign) -> None:
        # env["PIT_FAULTS"] = "<spec>" / os.environ["PIT_FAULTS"] = ...
        for target in node.targets:
            if (isinstance(target, ast.Subscript)
                    and isinstance(target.slice, ast.Constant)
                    and target.slice.value == "PIT_FAULTS"
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                err = _spec_error(node.value.value)
                if err:
                    self._flag(node.value, f"PIT_FAULTS spec: {err}")
        self.generic_visit(node)

    def visit_Dict(self, node: ast.Dict) -> None:
        for k, v in zip(node.keys, node.values):
            if (isinstance(k, ast.Constant) and k.value == "PIT_FAULTS"
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)):
                err = _spec_error(v.value)
                if err:
                    self._flag(v, f"PIT_FAULTS spec: {err}")
        self.generic_visit(node)


class FaultSiteRule(Rule):
    rule_id = "PIT-FAULT"

    # the registry itself (docstring teaches the grammar, error paths embed
    # deliberately-invalid examples) and the lint suite's own fixtures
    # (strings that MUST contain invalid sites for the negative tests)
    SELF_EXCLUDED = ("resilience/faults.py", "tests/test_lint.py")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.relpath.endswith(self.SELF_EXCLUDED):
            return ()
        visitor = _Visitor(self, ctx)
        visitor.visit(ctx.tree)
        findings = visitor.findings
        findings.extend(self.check_text(ctx.relpath, ctx.source))
        return findings

    def check_text(self, relpath: str, text: str) -> List[Finding]:
        """``PIT_FAULTS="..."`` examples in raw text — docstrings here, and
        markdown docs when ``tools/lint.py`` feeds them through directly."""
        findings: List[Finding] = []
        for i, line in enumerate(text.splitlines(), start=1):
            for m in _SPEC_RE.finditer(line):
                err = _spec_error(m.group(1))
                if err:
                    findings.append(Finding(
                        self.rule_id, relpath, i, "",
                        f"PIT_FAULTS example: {err}"))
        return findings
