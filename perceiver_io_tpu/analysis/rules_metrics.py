"""PIT-METRIC: alert-rule and series-key metric-name literals resolve
against the registry's known instrument names.

The PIT-SPAN pattern applied to the time-series/alerting layer: an
``AlertRule(metric=...)`` or ``series_key("...")`` literal that names an
instrument nothing registers would build a rule that silently never fires
(the store's ``match()`` returns nothing forever) — exactly the failure
class a page-class alert cannot afford. Unlike span names there is no
single hand-maintained registry to import: instrument names ARE their
registration sites (``reg.counter("...")`` / ``.gauge`` / ``.histogram``
string literals scattered across the package), so the rule derives the
known set by scanning ``perceiver_io_tpu/`` once per process (cached) and
collecting every literal first argument of those calls.

Checked shapes: ``AlertRule(metric="...")`` (keyword or second positional)
and ``series_key("...")`` first arguments. Resolution strips the
``{label="v"}`` suffix and a trailing ``:p50``/``:p95``/``:p99``/``:count``
histogram field. Non-literal metrics (runtime-loaded rule files, dynamic
names like the trainer's sanitized scalar keys) are the runtime's problem —
``AlertEngine.health_status`` surfaces rules that never matched a series.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, List, Optional, Set

from perceiver_io_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    ScopedVisitor,
    dotted_name,
    iter_py_files,
)

_REGISTRATION_LEAVES = {"counter", "gauge", "histogram"}

_KNOWN: Optional[Set[str]] = None


def _package_root() -> str:
    # analysis/ sits inside the package; instruments register in package
    # code only, so the scan stays bounded to perceiver_io_tpu/
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def known_metric_names(root: Optional[str] = None) -> Set[str]:
    """Every literal instrument name registered anywhere in the package —
    the set a metric literal must resolve against. Cached per process
    (the lint pass visits every file; re-deriving per file would square
    the parse cost)."""
    global _KNOWN
    if _KNOWN is not None and root is None:
        return _KNOWN
    names: Set[str] = set()
    for path in iter_py_files([root or _package_root()]):
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
        except (SyntaxError, UnicodeDecodeError, OSError):
            continue  # PIT-PARSE owns unparseable files
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            name = dotted_name(node.func) or ""
            if "." not in name:  # bare counter()/gauge() is not the registry
                continue
            if name.rsplit(".", 1)[-1] not in _REGISTRATION_LEAVES:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                names.add(arg.value)
    if root is None:
        _KNOWN = names
    return names


def strip_series_key(literal: str) -> str:
    """Reduce a series key to the bare instrument name — ONE parse of the
    key grammar, imported lazily from its definition (the PIT-SPAN
    pattern; obs.timeseries is stdlib-only at import, so the lint pass
    stays CPU-safe)."""
    from perceiver_io_tpu.obs.timeseries import split_series_key

    return split_series_key(literal)[0]


def _name_error(literal: str) -> Optional[str]:
    base = strip_series_key(literal)
    if base in known_metric_names():
        return None
    return (f"metric {literal!r} does not resolve: no registry instrument "
            f"named {base!r} is registered anywhere in the package — a "
            f"typo'd rule would silently never fire")


class _Visitor(ScopedVisitor):
    def __init__(self, rule: "MetricNameRule", ctx: FileContext):
        super().__init__()
        self.rule = rule
        self.ctx = ctx
        self.findings: List[Finding] = []

    def _check_literal(self, node: ast.AST) -> None:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            err = _name_error(node.value)
            if err:
                self.findings.append(self.rule.finding(
                    self.ctx, node, self.scope, err))

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func) or ""
        leaf = name.rsplit(".", 1)[-1]
        if leaf == "AlertRule":
            metric = next((kw.value for kw in node.keywords
                           if kw.arg == "metric"), None)
            if metric is None and len(node.args) >= 2:
                metric = node.args[1]  # (name, metric, ...) positionally
            if metric is not None:
                self._check_literal(metric)
        elif leaf == "series_key" and node.args:
            self._check_literal(node.args[0])
        self.generic_visit(node)


class MetricNameRule(Rule):
    rule_id = "PIT-METRIC"

    # the lint suite's fixtures deliberately contain unresolvable names
    SELF_EXCLUDED = ("tests/test_lint.py",)

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.relpath.endswith(self.SELF_EXCLUDED):
            return ()
        visitor = _Visitor(self, ctx)
        visitor.visit(ctx.tree)
        return visitor.findings
