"""PIT-CONTRACT: the tools/ + bench.py stdout and device-probe contracts.

The driver parses ONE JSON line from the stdout of ``bench.py`` and the
``tools/`` benches (CLAUDE.md); everything human-readable rides stderr. And
on this container any bare first backend touch (``jax.devices()``,
``jax.default_backend()``) can hang forever when the axon tunnel wedges — so
tools must probe through a deadline (``utils.platform.probe_backend`` /
``utils.profiling.call_with_deadline``), never bare.

Flags, in files under ``tools/`` and in ``bench.py``:

- ``print(...)`` without an explicit ``file=`` destination (stdout is
  reserved for :func:`perceiver_io_tpu.utils.jsonline.emit_json_line`);
  ``print(..., file=sys.stderr)`` and prints into open file objects pass.
- ``sys.stdout.write(...)`` / writes through a ``sys.stdout`` alias.
- bare device/backend probes (``jax.devices``, ``jax.default_backend``,
  ``jax.local_devices``, ``jax.device_count``, ``jax.local_device_count``)
  — call sites must go through the sanctioned deadline-wrapped helper.
  Passing the probe *function* into ``call_with_deadline`` (no Call node)
  is the other sanctioned shape and is naturally not flagged.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from perceiver_io_tpu.analysis.core import (
    FileContext,
    Finding,
    Rule,
    ScopedVisitor,
    dotted_name,
)

SANCTIONED_EMITTERS = {"emit_json_line"}

_PROBES = {
    "jax.devices", "jax.local_devices", "jax.default_backend",
    "jax.device_count", "jax.local_device_count",
}

# helpers that already run their probe under a deadline: calls lexically
# inside these functions are the sanctioned implementation, not a violation
_DEADLINE_HELPERS = {"probe_backend", "_probe_backend"}


def _applies(relpath: str) -> bool:
    return relpath.startswith("tools/") or relpath == "bench.py"


class _Visitor(ScopedVisitor):
    def __init__(self, rule: "ToolContractRule", ctx: FileContext):
        super().__init__()
        self.rule = rule
        self.ctx = ctx
        self.findings: List[Finding] = []

    def visit_Call(self, node: ast.Call) -> None:
        name = dotted_name(node.func)
        if name == "print":
            file_kw = next(
                (kw for kw in node.keywords if kw.arg == "file"), None)
            if file_kw is None or dotted_name(file_kw.value) in (
                    "sys.stdout", "stdout"):
                self.findings.append(self.rule.finding(
                    self.ctx, node, self.scope,
                    "print() to stdout — tools reserve stdout for the one "
                    "JSON line; use utils.jsonline.emit_json_line for the "
                    "record and file=sys.stderr for logs"))
        elif name in ("sys.stdout.write", "stdout.write"):
            self.findings.append(self.rule.finding(
                self.ctx, node, self.scope,
                "writes sys.stdout directly — stdout is reserved for "
                "utils.jsonline.emit_json_line"))
        elif name in _PROBES:
            leaf = self.scope.rsplit(".", 1)[-1] if self.scope else ""
            if leaf not in _DEADLINE_HELPERS:
                self.findings.append(self.rule.finding(
                    self.ctx, node, self.scope,
                    f"bare {name}() — a wedged tunnel hangs this forever; "
                    f"use utils.platform.probe_backend() (deadline-wrapped)"))
        self.generic_visit(node)


class ToolContractRule(Rule):
    rule_id = "PIT-CONTRACT"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if not _applies(ctx.relpath):
            return ()
        visitor = _Visitor(self, ctx)
        visitor.visit(ctx.tree)
        return visitor.findings
