"""PIT-SHARD: every sharding path-regex matches every preset's param tree.

``parallel/sharding.py`` routes parameters to mesh axes by path regex; the
param-tree names mirror torch for golden parity (CLAUDE.md invariants). A
rename — say ``q_proj`` → ``query_proj`` — would break NOTHING loudly: the
regex simply stops matching, the tensor silently replicates, and tensor
parallelism quietly degrades to replication. This audit makes that failure
loud: each rule regex must match at least one parameter path in EACH
``models/presets.py`` preset tree.

CPU-only by construction: trees come from ``jax.eval_shape`` over
``model.init`` — shapes trace abstractly, nothing allocates, no backend
beyond CPU is touched. Runs inside the tier-1 lint test and (by default)
``tools/lint.py``.
"""

from __future__ import annotations

import re
from typing import Callable, Dict, List, Tuple

from perceiver_io_tpu.analysis.core import Finding

RULE_ID = "PIT-SHARD"


def _preset_builders() -> Dict[str, Tuple[Callable, int]]:
    """name -> (builder, max_seq_len). One entry per preset in
    ``models/presets.py`` — a new preset joins the audit by construction."""
    from perceiver_io_tpu.models import presets

    return {
        "tiny_mlm": (presets.tiny_mlm, 64),
        "flagship_mlm": (presets.flagship_mlm, 512),
        "flagship_tpu_mlm": (presets.flagship_tpu_mlm, 512),
        # the generative (Perceiver-AR) task presets: same leaf names by
        # construction, audited so a causal-path refactor cannot silently
        # strand a sharding rule either
        "tiny_ar": (presets.tiny_ar, 64),
        "flagship_ar": (presets.flagship_ar, 512),
    }


def preset_param_paths(builder: Callable, max_seq_len: int) -> List[str]:
    """The "/"-joined param paths of one preset, via shape-only tracing."""
    import jax
    import numpy as np

    from perceiver_io_tpu.utils.treepath import simple_keystr

    model = builder()
    ids = jax.ShapeDtypeStruct((1, max_seq_len), np.int32)
    pad = jax.ShapeDtypeStruct((1, max_seq_len), np.bool_)
    variables = jax.eval_shape(
        model.init,
        {"params": jax.random.key(0), "masking": jax.random.key(1)},
        ids, pad,
    )
    paths: List[str] = []
    jax.tree_util.tree_map_with_path(
        lambda path, leaf: paths.append(simple_keystr(path)),
        variables["params"],
    )
    return paths


def audit_sharding_rules() -> List[Finding]:
    """Findings for every (rule regex, preset) pair with zero matches."""
    from perceiver_io_tpu.parallel.sharding import PARAM_RULES

    findings: List[Finding] = []
    for preset_name, (builder, seq_len) in _preset_builders().items():
        paths = preset_param_paths(builder, seq_len)
        for pattern, _spec in PARAM_RULES:
            rx = re.compile(pattern)
            if not any(rx.search(p) for p in paths):
                findings.append(Finding(
                    RULE_ID, "perceiver_io_tpu/parallel/sharding.py", 0,
                    "PARAM_RULES",
                    f"rule regex {pattern!r} matches no param path in "
                    f"preset {preset_name!r} ({len(paths)} paths) — a "
                    f"param rename silently stranded this sharding rule"))
    return findings
