"""pitlint core: findings, rule protocol, file scanning, baseline, pragmas.

Deliberately jax-free and import-light: the static pass must parse ~130 files
well inside the tier-1 lint test's 20 s budget, and ``tools/lint.py
--changed`` must be a sub-second local loop. Rules get one parsed
:class:`FileContext` per file and return :class:`Finding`\\ s.

Suppression has two tiers with different lifetimes:

- ``# pitlint: ignore[RULE-ID] reason`` on the offending line — for sites
  that are CORRECT forever (e.g. a wall-clock subtraction that genuinely
  computes an epoch timestamp). The reason rides the code.
- the checked-in baseline file — for pre-existing DEBT that should not block
  CI but should not silently grow either. Baseline keys are line-number-free
  (``rule|path|scope|message``) so unrelated edits don't invalidate them;
  each line may carry a ``# justification`` suffix.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.txt")

# ONE definition of the lint scope, shared by tools/lint.py and the tier-1
# test (tests/test_lint.py) so the fast local loop, CI, and the baseline can
# never disagree about what is covered:
# - DEFAULT_TARGETS: the full rule set;
# - TEST_FAULT_TARGETS: tests/ runs ONLY the fault-site rule (PIT_FAULTS
#   drill specs in tests must name registered sites — the issue-r13
#   contract — but test code legitimately prints, reads wall clocks, etc.);
# - DOC_TARGETS: markdown whose concrete PIT_FAULTS examples are validated.
DEFAULT_TARGETS = ("perceiver_io_tpu", "tools", "bench.py")
TEST_FAULT_TARGETS = ("tests",)
DOC_TARGETS = ("README.md", "PERF.md", "ROADMAP.md", "CHANGES.md")

_PRAGMA = re.compile(r"#\s*pitlint:\s*(?:ignore|disable)\[([A-Za-z0-9*,\s-]+)\]")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one site."""

    rule: str      # "PIT-JIT", "PIT-LOCK", ...
    path: str      # repo-relative, "/"-separated
    line: int      # 1-based
    scope: str     # dotted qualname of the enclosing def/class ("" = module)
    message: str   # stable text (no line numbers — baseline keys survive edits)

    def key(self) -> str:
        return f"{self.rule}|{self.path}|{self.scope}|{self.message}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} [{self.scope or '<module>'}] {self.message}"


class FileContext:
    """One parsed source file as the rules see it."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # line -> set of suppressed rule ids ("*" suppresses every rule)
        self.pragmas: Dict[int, Set[str]] = {}
        for i, text in enumerate(self.lines, start=1):
            m = _PRAGMA.search(text)
            if m:
                self.pragmas[i] = {
                    r.strip() for r in m.group(1).split(",") if r.strip()
                }

    def suppressed(self, rule: str, line: int) -> bool:
        rules = self.pragmas.get(line)
        return rules is not None and (rule in rules or "*" in rules)


class Rule:
    """Base class: subclasses set ``rule_id`` and implement ``check``."""

    rule_id: str = "PIT-???"

    def check(self, ctx: FileContext) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, ctx: FileContext, node: ast.AST, scope: str,
                message: str) -> Finding:
        return Finding(self.rule_id, ctx.relpath,
                       getattr(node, "lineno", 0), scope, message)


def dotted_name(node: ast.AST) -> Optional[str]:
    """Render ``a.b.c`` attribute/name chains; None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class ScopedVisitor(ast.NodeVisitor):
    """NodeVisitor that maintains the dotted qualname of the current scope."""

    def __init__(self):
        self._scope: List[str] = []

    @property
    def scope(self) -> str:
        return ".".join(self._scope)

    def _visit_scoped(self, node):
        self._scope.append(node.name)
        try:
            self.generic_visit(node)
        finally:
            self._scope.pop()

    visit_FunctionDef = _visit_scoped
    visit_AsyncFunctionDef = _visit_scoped
    visit_ClassDef = _visit_scoped


class Baseline:
    """The checked-in suppression file: one finding key per line, optional
    ``# justification`` suffix. Keys are line-number-free (see
    :meth:`Finding.key`) so they survive unrelated edits."""

    def __init__(self, keys: Optional[Dict[str, str]] = None):
        self.keys: Dict[str, str] = dict(keys or {})  # key -> justification

    @classmethod
    def load(cls, path: str) -> "Baseline":
        keys: Dict[str, str] = {}
        if os.path.exists(path):
            with open(path) as f:
                for raw in f:
                    line = raw.strip()
                    if not line or line.startswith("#"):
                        continue
                    key, _, why = line.partition("  #")
                    keys[key.strip()] = why.strip()
        return cls(keys)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write("# pitlint baseline — pre-existing findings that do not "
                    "block CI.\n# One `rule|path|scope|message` key per line; "
                    "`  # justification` suffix.\n# Regenerate with: "
                    "python tools/lint.py --write-baseline\n")
            for key in sorted(self.keys):
                why = self.keys[key]
                f.write(f"{key}  # {why}\n" if why else f"{key}\n")

    def __contains__(self, finding: Finding) -> bool:
        return finding.key() in self.keys

    def split(self, findings: Sequence[Finding]
              ) -> Tuple[List[Finding], List[Finding]]:
        """``(new, baselined)`` partition, preserving order."""
        new = [f for f in findings if f not in self]
        old = [f for f in findings if f in self]
        return new, old

    def stale_keys(self, findings: Sequence[Finding]) -> List[str]:
        """Baseline entries no current finding matches (debt actually paid
        down — prune them so the file never protects future regressions)."""
        live = {f.key() for f in findings}
        return sorted(k for k in self.keys if k not in live)


def all_rules() -> List[Rule]:
    """The registered static rule set (import here, not at module scope, so
    ``core`` stays dependency-free for the rule modules themselves)."""
    from perceiver_io_tpu.analysis.rules_clock import DurationClockRule
    from perceiver_io_tpu.analysis.rules_contract import ToolContractRule
    from perceiver_io_tpu.analysis.rules_faults import FaultSiteRule
    from perceiver_io_tpu.analysis.rules_locks import LockDisciplineRule
    from perceiver_io_tpu.analysis.rules_metrics import MetricNameRule
    from perceiver_io_tpu.analysis.rules_purity import JitPurityRule
    from perceiver_io_tpu.analysis.rules_spans import SpanNameRule

    return [JitPurityRule(), ToolContractRule(), FaultSiteRule(),
            LockDisciplineRule(), DurationClockRule(), SpanNameRule(),
            MetricNameRule()]


def iter_py_files(paths: Sequence[str]) -> Iterable[str]:
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
        else:
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith(".")
                )
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        yield os.path.join(dirpath, name)


def scan_orphan_bytecode(root: str,
                         targets: Sequence[str] = DEFAULT_TARGETS,
                         ) -> List[Finding]:
    """PIT-BYTECODE: orphan bytecode that can shadow (or resurrect) a
    DELETED module.

    Python 3 imports sourceless ``mod.pyc`` files sitting where ``mod.py``
    would be — so a legacy-layout pyc left behind after its source is
    deleted keeps the dead module importable (stale code runs, renames
    half-apply). ``__pycache__`` pycs never load without their source, but
    an orphan there is residue from a deleted module all the same — the
    repo-hygiene check flags both so a deleted module is GONE."""
    findings: List[Finding] = []

    def finding(pyc_path: str, message: str) -> Finding:
        rel = os.path.relpath(pyc_path, root).replace(os.sep, "/")
        return Finding(rule="PIT-BYTECODE", path=rel, line=1, scope="",
                       message=message)

    for target in targets:
        top = os.path.join(root, target)
        if os.path.isfile(top) or not os.path.isdir(top):
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = sorted(d for d in dirnames if not d.startswith("."))
            in_cache = os.path.basename(dirpath) == "__pycache__"
            src_dir = os.path.dirname(dirpath) if in_cache else dirpath
            for name in sorted(filenames):
                if not name.endswith((".pyc", ".pyo")):
                    continue
                stem = name.split(".", 1)[0]
                has_src = os.path.exists(
                    os.path.join(src_dir, stem + ".py"))
                pyc = os.path.join(dirpath, name)
                if not in_cache:
                    findings.append(finding(
                        pyc, f"legacy-layout bytecode {name!r} is "
                             f"importable {'alongside' if has_src else 'in place of deleted'} "
                             f"'{stem}.py' — delete it (sourceless pycs "
                             f"shadow the package layout)"))
                elif not has_src:
                    findings.append(finding(
                        pyc, f"orphan bytecode for deleted module "
                             f"'{stem}.py' — delete the residue"))
    return findings


def scan_paths(paths: Sequence[str], rules: Optional[Sequence[Rule]] = None,
               root: Optional[str] = None) -> List[Finding]:
    """Run the static rules over every ``.py`` under ``paths``.

    ``root`` anchors the repo-relative paths findings (and baseline keys)
    carry; default is the common parent of ``paths``. Unparseable files
    surface as a ``PIT-PARSE`` finding rather than crashing the pass.
    """
    rules = list(rules) if rules is not None else all_rules()
    if root is None:
        root = os.path.commonpath([os.path.abspath(p) for p in paths])
        if os.path.isfile(root):
            root = os.path.dirname(root)
    findings: List[Finding] = []
    for file_path in iter_py_files(paths):
        relpath = os.path.relpath(os.path.abspath(file_path), root)
        try:
            with open(file_path, encoding="utf-8") as f:
                ctx = FileContext(file_path, relpath, f.read())
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            findings.append(Finding(
                "PIT-PARSE", relpath.replace(os.sep, "/"),
                getattr(e, "lineno", 0) or 0, "",
                f"unparseable: {type(e).__name__}"))
            continue
        for rule in rules:
            for f in rule.check(ctx):
                if not ctx.suppressed(f.rule, f.line):
                    findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
