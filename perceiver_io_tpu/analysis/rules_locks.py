"""PIT-LOCK: declared guarded attributes are touched only under their lock.

Classes declare the discipline themselves, C++-``GUARDED_BY`` style::

    class ServingEngine:
        _guarded_by = {"_stats": "_stats_lock", "_backlog": "_stats_lock"}
        _assumes_locked = ("deploy_once",)   # optional: callee runs with the
                                             # lock already held by its caller

The rule then checks, per method of the class (``__init__`` exempt — no
other thread can hold a reference yet), that every ``self.<attr>`` load or
store of a guarded attribute sits lexically inside ``with self.<lock>:``.
Methods named in ``_assumes_locked`` (or whose name ends ``_locked`` —
the naming convention the engine already uses, e.g. ``_rotate_locked``)
are treated as running under the lock.

Lexical containment is deliberately the whole analysis: it cannot prove a
``_locked`` helper is *only* called under the lock, but it turns "reviewer
remembers which fields need ``_stats_lock``" into "the class says so and a
machine checks every touch" — the same trade race detectors make. Genuinely
lock-free fast paths carry an inline ``# pitlint: ignore[PIT-LOCK]`` pragma
with their reasoning.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from perceiver_io_tpu.analysis.core import FileContext, Finding, Rule


def _literal_str_dict(node: ast.AST) -> Dict[str, str]:
    out: Dict[str, str] = {}
    if isinstance(node, ast.Dict):
        for k, v in zip(node.keys, node.values):
            if (isinstance(k, ast.Constant) and isinstance(k.value, str)
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)):
                out[k.value] = v.value
    return out


def _literal_str_seq(node: ast.AST) -> Tuple[str, ...]:
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


class LockDisciplineRule(Rule):
    rule_id = "PIT-LOCK"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(ctx, node))
        return findings

    def _check_class(self, ctx: FileContext,
                     cls: ast.ClassDef) -> Iterable[Finding]:
        guarded: Dict[str, str] = {}
        assumes: Tuple[str, ...] = ()
        for stmt in cls.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                if stmt.targets[0].id == "_guarded_by":
                    guarded = _literal_str_dict(stmt.value)
                elif stmt.targets[0].id == "_assumes_locked":
                    assumes = _literal_str_seq(stmt.value)
        if not guarded:
            return ()
        findings: List[Finding] = []
        for stmt in cls.body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if stmt.name == "__init__" or stmt.name in assumes \
                    or stmt.name.endswith("_locked"):
                continue
            qual = f"{cls.name}.{stmt.name}"
            self._scan(ctx, stmt, qual, guarded, frozenset(), findings)
        return findings

    def _scan(self, ctx: FileContext, node: ast.AST, qual: str,
              guarded: Dict[str, str], held: frozenset,
              findings: List[Finding]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.With):
                locks = set()
                for item in child.items:
                    e = item.context_expr
                    if isinstance(e, ast.Attribute) \
                            and isinstance(e.value, ast.Name) \
                            and e.value.id == "self":
                        locks.add(e.attr)
                # the with-items themselves evaluate OUTSIDE the lock
                for item in child.items:
                    self._scan(ctx, item, qual, guarded, held, findings)
                inner = held | locks
                for stmt in child.body:
                    self._scan(ctx, stmt, qual, guarded,
                               frozenset(inner), findings)
                continue
            if isinstance(child, ast.Attribute) \
                    and isinstance(child.value, ast.Name) \
                    and child.value.id == "self" \
                    and child.attr in guarded \
                    and guarded[child.attr] not in held:
                findings.append(self.finding(
                    ctx, child, qual,
                    f"self.{child.attr} touched outside "
                    f"'with self.{guarded[child.attr]}' "
                    f"(declared in _guarded_by)"))
            self._scan(ctx, child, qual, guarded, held, findings)
