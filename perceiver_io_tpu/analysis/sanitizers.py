"""Runtime sanitizers: recompiles, silent host transfers, lock ordering.

The static rules in this package catch what an AST can see; these catch what
only a running process can. All three are cheap enough to arm inside tier-1
tests (the lock recorder wraps ``threading.Lock`` creation only inside its
context; the other two are a counter read and a jax config scope).

- :func:`no_recompile` — a steady-state serving block must do ZERO XLA
  compiles (the bucket programs + AOT cache exist to guarantee it; a
  climbing ``jax_compilations_total`` during serving is the recompile bug).
- :func:`no_implicit_transfers` — ``jax.transfer_guard`` armed around engine
  dispatch: a silent device→host transfer (an un-fetched tracer leaking into
  numpy) costs a ~100 ms tunnel round trip per occurrence in production and
  raises here instead.
- :func:`record_lock_order` — wraps locks created inside the context,
  records the acquisition graph (every held lock → newly acquired lock,
  nodes keyed by creation site so all instances of e.g.
  ``ServingEngine._stats_lock`` collapse to one node, lockdep-style), and
  fails on cycles: two code paths taking the same two locks in opposite
  orders is a deadlock waiting for the right interleaving.
"""

from __future__ import annotations

import contextlib
import threading
import traceback
from typing import Dict, Iterator, List, Optional, Set, Tuple


class RecompileDetected(AssertionError):
    """Steady-state code compiled when it must not have."""


class LockOrderViolation(AssertionError):
    """The recorded lock-acquisition graph contains a cycle."""


@contextlib.contextmanager
def no_recompile(registry=None) -> Iterator[None]:
    """Assert ZERO ``jax_compilations_total`` delta across the block.

    Rides the process-wide ``jax.monitoring`` backend-compile listener
    (:func:`~perceiver_io_tpu.obs.watchdog.install_compile_counter`), which
    fires once per real XLA compilation and never for cache hits — so an AOT
    disk deserialize stays silent and a genuine recompile trips this.

    The counter is PROCESS-WIDE: wrap only blocks whose whole process should
    be compile-quiet. An engine still background-warming (``warmup(...,
    background=True)``) legitimately compiles on its warmup thread — wait
    for the warm pool (``engine_ready``) before arming this.
    """
    from perceiver_io_tpu.obs.watchdog import install_compile_counter

    counter = install_compile_counter(registry)
    before = counter.value
    yield
    delta = counter.value - before
    if delta:
        raise RecompileDetected(
            f"no_recompile(): {delta:g} XLA compilation(s) inside a "
            f"steady-state block (jax_compilations_total "
            f"{before:g} -> {counter.value:g})"
        )


@contextlib.contextmanager
def no_implicit_transfers(direction: str = "device_to_host",
                          guard: str = "disallow") -> Iterator[None]:
    """Arm jax's transfer guard PROCESS-WIDE for the block.

    Default scope is the DEVICE→HOST direction: that is the silent transfer
    that costs ~100 ms per occurrence over the tunnel (PERF.md — a stray
    ``np.asarray(device_array)`` or ``float(tracer_output)`` deep in a
    completion path). Explicit movement (``jax.device_get``) stays legal —
    the engine's result fetches are deliberate. Host→device stays free by
    default because feeding numpy micro-batches straight into the jitted
    dispatch IS the engine's staging path on CPU; pass
    ``direction="all"`` to arm every direction.

    Deliberately NOT ``jax.transfer_guard(...)`` the context manager: that
    config scope is THREAD-LOCAL, and the transfers this sanitizer exists
    to catch happen on the engine's worker thread, not the test thread
    arming it. The global ``jax.config.update`` default IS visible to
    threads outside any thread-local scope (verified empirically on this
    jax build), which makes the guard bite where the dispatch actually
    runs. Consequence: do not run concurrent jax work that must stay
    guard-free while armed.
    """
    import jax

    flags = {
        "all": "jax_transfer_guard",
        "device_to_host": "jax_transfer_guard_device_to_host",
        "host_to_device": "jax_transfer_guard_host_to_device",
    }
    if direction not in flags:
        raise ValueError(
            f"no_implicit_transfers: unknown direction {direction!r} "
            f"(one of {sorted(flags)}) — a typo here would silently arm "
            f"the wrong guard")
    flag = flags[direction]
    previous = getattr(jax.config, flag)  # None when never set (= allow)
    jax.config.update(flag, guard)
    try:
        yield
    finally:
        jax.config.update(flag, previous)


# -- lock-order recording -----------------------------------------------------

_FRAMEWORK_FILES = ("threading.py", "queue.py", "sanitizers.py")


def _creation_site() -> str:
    """First stack frame outside threading/queue/this module — the lock's
    declaration site, the node key that collapses per-instance locks."""
    for frame in reversed(traceback.extract_stack()):
        if not frame.filename.endswith(_FRAMEWORK_FILES):
            return f"{frame.filename.rsplit('/', 1)[-1]}:{frame.lineno}"
    return "<unknown>"


class _RecordingLock:
    """Duck-typed ``threading.Lock`` stand-in that reports acquisitions.

    Supports the full surface ``Condition``/``Event``/``queue.Queue`` use
    (``acquire(blocking, timeout)``, ``release``, ``locked``, context
    manager), so a recorder context can transparently wrap every lock the
    engine/router stack creates.
    """

    __slots__ = ("_lock", "_recorder", "site")

    def __init__(self, lock, recorder: "LockOrderRecorder", site: str):
        self._lock = lock
        self._recorder = recorder
        self.site = site

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._recorder._note_acquire(self.site)
        return got

    def release(self) -> None:
        self._lock.release()
        self._recorder._note_release(self.site)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class LockOrderRecorder:
    """Builds the lock-acquisition graph as wrapped locks are taken.

    Edge ``A -> B``: some thread acquired ``B`` while holding ``A``. A cycle
    in this graph means two orderings coexist — the deadlock precondition.
    ``check()`` raises :class:`LockOrderViolation` naming the cycle.
    """

    def __init__(self):
        self._graph_lock = threading.Lock()
        self._edges: Dict[str, Set[str]] = {}
        self._acquisitions = 0
        self._local = threading.local()

    def _held(self) -> List[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _note_acquire(self, site: str) -> None:
        held = self._held()
        if held:
            with self._graph_lock:
                for h in held:
                    if h != site:
                        self._edges.setdefault(h, set()).add(site)
        with self._graph_lock:
            self._acquisitions += 1
        held.append(site)

    def _note_release(self, site: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == site:
                del held[i]
                break

    def wrap(self, lock, site: Optional[str] = None) -> _RecordingLock:
        return _RecordingLock(lock, self, site or _creation_site())

    @property
    def edges(self) -> Dict[str, Set[str]]:
        with self._graph_lock:
            return {k: set(v) for k, v in self._edges.items()}

    @property
    def acquisitions(self) -> int:
        with self._graph_lock:
            return self._acquisitions

    def find_cycle(self) -> Optional[List[str]]:
        edges = self.edges
        WHITE, GRAY, BLACK = 0, 1, 2
        color: Dict[str, int] = {}
        path: List[str] = []

        def dfs(node: str) -> Optional[List[str]]:
            color[node] = GRAY
            path.append(node)
            for nxt in sorted(edges.get(node, ())):
                c = color.get(nxt, WHITE)
                if c == GRAY:
                    return path[path.index(nxt):] + [nxt]
                if c == WHITE:
                    cycle = dfs(nxt)
                    if cycle:
                        return cycle
            path.pop()
            color[node] = BLACK
            return None

        for node in sorted(edges):
            if color.get(node, WHITE) == WHITE:
                cycle = dfs(node)
                if cycle:
                    return cycle
        return None

    def check(self) -> None:
        cycle = self.find_cycle()
        if cycle:
            raise LockOrderViolation(
                "lock-order cycle (deadlock precondition): "
                + " -> ".join(cycle)
                + " — two code paths acquire these locks in opposite orders"
            )


@contextlib.contextmanager
def record_lock_order() -> Iterator[LockOrderRecorder]:
    """Record the acquisition order of every lock CREATED inside the block
    (``threading.Lock`` is patched for the duration — existing locks are
    untouched), then fail on cycles at exit.

    Construct the system under test inside the context so its locks are
    wrapped; drive it; the exit check raises :class:`LockOrderViolation` on
    any inconsistent ordering observed — even ones that didn't deadlock this
    run. The check is skipped when the body itself raised (the original
    error wins).
    """
    recorder = LockOrderRecorder()
    real_lock = threading.Lock

    def recording_lock():
        return recorder.wrap(real_lock(), _creation_site())

    threading.Lock = recording_lock
    try:
        yield recorder
    finally:
        threading.Lock = real_lock
    recorder.check()
