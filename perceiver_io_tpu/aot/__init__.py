"""Persistent ahead-of-time compilation: zero-recompile cold starts.

Perceiver IO's serving efficiency comes from a *family* of small specialized
XLA programs — one executable per (signature, batch-bucket) — and every
process start used to re-pay the full compile family through the tunneled
remote compiler before the first request could be answered. This subsystem
makes cold start near-zero:

- :class:`ExecutableCache` — tier 1: compiled executables serialized to disk
  (``jax.experimental.serialize_executable``), keyed by a content fingerprint
  (package/source identity of the traced callable, jax/jaxlib + PJRT
  platform/topology, abstract input shapes/dtypes, donation/static config).
  A warm start deserializes the executable directly — no trace, no lower,
  no compile. Corrupt entries and fingerprint mismatches fall back to a
  normal compile; a cache problem NEVER refuses traffic.
- :func:`enable_persistent_compilation_cache` — tier 2: jax's own persistent
  compilation cache (``jax_compilation_cache_dir``), for paths the AOT tier
  cannot cover (the trainer step, ad-hoc tools): tracing and lowering still
  run, but the expensive backend compile becomes a disk hit.

Both tiers are fail-soft by construction and export hit/miss/error counters
through the obs registry.
"""

from perceiver_io_tpu.aot.cache import (
    ExecutableCache,
    callable_sources,
    compile_via_cache,
    enable_persistent_compilation_cache,
    environment_fingerprint,
    fingerprint,
    maybe_enable_cache_from_env,
    resolve_cache,
)

__all__ = [
    "ExecutableCache",
    "callable_sources",
    "compile_via_cache",
    "enable_persistent_compilation_cache",
    "environment_fingerprint",
    "fingerprint",
    "maybe_enable_cache_from_env",
    "resolve_cache",
]
