"""Persistent executable cache: serialize compiled XLA programs to disk.

Tier 1 (:class:`ExecutableCache`): ``jax.experimental.serialize_executable``
round-trips a ``Compiled`` object through bytes. Entries are keyed by a
content *fingerprint* computed WITHOUT tracing or lowering — a warm start
goes straight from (shapes, config) to a loaded executable, skipping the
trace, the lower, and the remote backend compile entirely. The fingerprint
folds in everything that could change the compiled program:

- package version + best-effort source of the traced callable (closure
  functions recursed; non-function closure cells contribute their repr when
  it is address-free — a flax module repr carries the full hyperparameter
  tree, which is exactly the model identity we want);
- jax/jaxlib versions, backend platform, device kind and count (the PJRT
  topology a serialized executable is only valid for);
- the abstract shapes/dtypes AND pytree structure of every argument;
- static config: donation, quantization mode, compute dtype, caller salt.

Any fingerprint drift = a different file name = an honest MISS followed by a
normal compile; a corrupt or truncated entry deserializes into an exception,
which is caught, warned about, counted, and the entry deleted — then the
normal compile runs. A cache problem can slow a cold start back to baseline;
it can never refuse traffic or serve a wrong program.

Tier 2 (:func:`enable_persistent_compilation_cache`): jax's own persistent
compilation cache for everything that does not flow through an
:class:`ExecutableCache` (the trainer step, ad-hoc tools): tracing/lowering
still run, but the backend compile becomes a disk hit. Opt-in via
``--compile_cache`` on the CLIs or ``PIT_COMPILE_CACHE=DIR`` for the benches.

No jax import at module scope — entry points must stay free to pick their
platform (``ensure_cpu_only``) before anything initializes a backend.
"""

from __future__ import annotations

import hashlib
import inspect
import os
import pickle
import re
import sys
import tempfile
import threading
import warnings
from typing import Any, Dict, Iterable, List, Optional, Union

import numpy as np

import perceiver_io_tpu.obs as obs

_ENTRY_SUFFIX = ".pitx"
_ENTRY_FORMAT = 1  # bump when the on-disk pickle layout changes


# -- fingerprinting ----------------------------------------------------------


def callable_sources(fn: Any, max_depth: int = 4) -> List[str]:
    """Best-effort stable identity strings for a (possibly nested) callable.

    Walks ``fn`` and the functions captured in its closure cells up to
    ``max_depth``, collecting source text where ``inspect`` can see it and
    qualnames otherwise. Non-function cell contents contribute
    ``type.qualname`` plus their ``repr`` with memory addresses normalized
    out (``repr(flax_module)`` is a full hyperparameter tree — exactly the
    model identity we want — but any embedded default ``<obj at 0x...>``
    repr would poison the fingerprint with a per-process address).
    """
    out: List[str] = []
    seen: set = set()

    def visit(obj: Any, depth: int) -> None:
        if depth > max_depth or id(obj) in seen:
            return
        seen.add(id(obj))
        if callable(obj):
            qualname = getattr(obj, "__qualname__", type(obj).__qualname__)
            out.append(f"callable:{qualname}")
            try:
                out.append(inspect.getsource(obj))
            except (OSError, TypeError):
                pass
            closure = getattr(obj, "__closure__", None) or ()
            for cell in closure:
                try:
                    visit(cell.cell_contents, depth + 1)
                except ValueError:  # empty cell
                    continue
            # functools.partial / bound methods: follow the wrapped callable
            for attr in ("func", "__func__", "__wrapped__"):
                inner = getattr(obj, attr, None)
                if inner is not None:
                    visit(inner, depth + 1)
        else:
            r = re.sub(r"0x[0-9a-fA-F]+", "0xADDR", repr(obj))
            out.append(f"object:{type(obj).__qualname__}:{r[:100_000]}")

    visit(fn, 0)
    return out


def _aval_strings(avals) -> List[str]:
    """Stable strings for a pytree of ShapeDtypeStruct-likes: the treedef
    plus every leaf's dtype/shape."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(avals)
    out = [f"treedef:{treedef}"]
    # sharding is part of a compiled executable's input contract (a
    # Compiled object rejects differently-placed args) — its str form is
    # address-free and process-stable (axis names/sizes, spec, device ids)
    out.extend(
        f"leaf:{getattr(l, 'dtype', '?')}:{getattr(l, 'shape', '?')}:"
        f"{getattr(l, 'sharding', None)}"
        for l in leaves
    )
    return out


def fingerprint(base: Dict[str, Any], avals: Any = None,
                extra: Iterable[str] = ()) -> str:
    """sha256 hex digest over the static config dict, the abstract argument
    tree, and any extra identity strings."""
    h = hashlib.sha256()
    for k in sorted(base):
        h.update(f"{k}={base[k]}\x00".encode("utf-8", "backslashreplace"))
    if avals is not None:
        for s in _aval_strings(avals):
            h.update(s.encode("utf-8", "backslashreplace"))
            h.update(b"\x00")
    for s in extra:
        h.update(str(s).encode("utf-8", "backslashreplace"))
        h.update(b"\x00")
    return h.hexdigest()


def environment_fingerprint() -> Dict[str, Any]:
    """The per-process part of every fingerprint: package + jax/jaxlib
    versions, backend platform, device kind/count. Touches the backend —
    call only after the entry point has picked its platform."""
    import jax
    import jaxlib

    import perceiver_io_tpu

    dev = jax.devices()[0]
    return {
        "pkg": perceiver_io_tpu.__version__,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": jax.default_backend(),
        "device_kind": getattr(dev, "device_kind", "unknown"),
        "num_devices": jax.device_count(),
        "entry_format": _ENTRY_FORMAT,
    }


# -- the executable cache ----------------------------------------------------


class ExecutableCache:
    """A directory of serialized compiled executables, one file per
    fingerprint, with fail-soft reads and atomic writes.

    Construct via :meth:`open` (fail-soft: an unusable directory yields
    ``None`` + a warning instead of an exception) — serving must never be
    refused over a cache problem. Concurrent engines/processes may share one
    directory: writes go through a same-directory temp file + ``os.replace``
    (atomic on POSIX), so a reader sees either a complete entry or none, and
    a torn/corrupt read falls back to a normal compile.
    """

    def __init__(self, directory: str,
                 registry: Optional[obs.MetricsRegistry] = None):
        self.directory = directory
        reg = registry if registry is not None else obs.get_registry()
        self._m_hits = reg.counter(
            "aot_cache_hits_total",
            "compiled executables loaded from the persistent AOT cache")
        self._m_misses = reg.counter(
            "aot_cache_misses_total",
            "AOT cache lookups that fell back to a compile")
        self._m_errors = reg.counter(
            "aot_cache_errors_total",
            "corrupt/unreadable/unwritable AOT cache entries (each one "
            "degraded to a normal compile, never an outage)")
        self._m_stores = reg.counter(
            "aot_cache_stores_total",
            "compiled executables serialized into the AOT cache")

    # -- construction --------------------------------------------------------

    @classmethod
    def open(cls, directory: Optional[str],
             registry: Optional[obs.MetricsRegistry] = None,
             ) -> Optional["ExecutableCache"]:
        """Open (creating if needed) ``directory`` as an executable cache.

        Fail-soft: a missing-and-uncreatable or unwritable directory warns
        and returns ``None`` — the caller serves uncached. Never raises for
        environmental problems.
        """
        if not directory:
            return None
        try:
            os.makedirs(directory, exist_ok=True)
            # write probe: root can chmod past a read-only bit, but a path
            # through a regular file / dead mount / full disk fails here
            probe = tempfile.NamedTemporaryFile(
                dir=directory, prefix=".probe_", delete=True)
            probe.write(b"x")
            probe.close()
        except OSError as e:
            warnings.warn(
                f"compile cache {directory!r} is unusable "
                f"({type(e).__name__}: {e}) — serving UNCACHED (cold starts "
                "pay full compiles; traffic is unaffected)", stacklevel=2)
            return None
        return cls(directory, registry=registry)

    # -- entries -------------------------------------------------------------

    def path(self, fp: str) -> str:
        return os.path.join(self.directory, fp + _ENTRY_SUFFIX)

    def load(self, fp: str):
        """Deserialize the executable stored under fingerprint ``fp``.

        Returns the loaded ``Compiled`` on a hit, ``None`` on a miss.
        A corrupt/truncated entry (or a deserialize failure — e.g. an entry
        written by an incompatible runtime that still hashed to the same
        fingerprint) warns, deletes the entry, counts an error, and returns
        ``None`` so the caller compiles normally.
        """
        path = self.path(fp)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            self._m_misses.inc()
            return None
        except OSError as e:
            self._m_errors.inc()
            self._m_misses.inc()
            warnings.warn(
                f"compile cache entry {path} unreadable "
                f"({type(e).__name__}: {e}) — falling back to a fresh "
                "compile", stacklevel=2)
            return None
        try:
            from jax.experimental import serialize_executable

            entry = pickle.loads(blob)
            if entry["format"] != _ENTRY_FORMAT:
                raise ValueError(f"entry format {entry['format']} != "
                                 f"{_ENTRY_FORMAT}")
            compiled = serialize_executable.deserialize_and_load(
                entry["payload"], entry["in_tree"], entry["out_tree"]
            )
        except Exception as e:
            self._m_errors.inc()
            self._m_misses.inc()
            warnings.warn(
                f"compile cache entry {path} is corrupt or incompatible "
                f"({type(e).__name__}: {str(e)[:200]}) — deleting it and "
                "falling back to a fresh compile", stacklevel=2)
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        self._m_hits.inc()
        obs.event("aot_cache_hit", fingerprint=fp[:16])
        return compiled

    def store(self, fp: str, compiled) -> bool:
        """Serialize ``compiled`` under fingerprint ``fp`` (atomic replace).

        Fail-soft: serialization/write errors warn + count and return False
        (e.g. a backend whose executables don't serialize, or a disk that
        filled up mid-write) — the in-memory executable keeps serving.

        Refuses (once-warned) while jax's persistent compilation cache is
        active in this process: that cache already serialized this very
        executable for its own disk entry, and serializing it a SECOND time
        intermittently corrupts this jaxlib's CPU runtime (measured — the
        crash surfaces later, in unrelated compiles; PERF.md §Cold start
        negative result). Loads stay enabled; the two tiers simply must not
        both serialize the same compile.
        """
        if persistent_cache_active():
            global _DOUBLE_TIER_WARNED
            if not _DOUBLE_TIER_WARNED:
                _DOUBLE_TIER_WARNED = True
                warnings.warn(
                    "AOT executable store skipped: jax's persistent "
                    "compilation cache is active in this process, and "
                    "double-serializing an executable (both tiers) "
                    "destabilizes this jaxlib (PERF.md §Cold start). Use "
                    "the AOT tier for serving processes and the persistent "
                    "cache for trainer/tool processes, not both in one.",
                    stacklevel=2)
            return False
        path = self.path(fp)
        try:
            from jax.experimental import serialize_executable

            payload, in_tree, out_tree = serialize_executable.serialize(
                compiled)
            blob = pickle.dumps({
                "format": _ENTRY_FORMAT,
                "payload": payload,
                "in_tree": in_tree,
                "out_tree": out_tree,
            })
            fd, tmp = tempfile.mkstemp(
                dir=self.directory, prefix=".tmp_", suffix=_ENTRY_SUFFIX)
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(blob)
                os.replace(tmp, path)  # readers see all-or-nothing
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except Exception as e:
            self._m_errors.inc()
            warnings.warn(
                f"could not persist compiled executable to {path} "
                f"({type(e).__name__}: {str(e)[:200]}) — serving from the "
                "in-memory copy; the next cold start recompiles",
                stacklevel=2)
            return False
        self._m_stores.inc()
        obs.event("aot_cache_store", fingerprint=fp[:16],
                  bytes=len(blob))
        return True

    def entries(self) -> List[str]:
        """Fingerprints currently on disk (diagnostics/tests)."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        return sorted(
            n[: -len(_ENTRY_SUFFIX)] for n in names
            if n.endswith(_ENTRY_SUFFIX) and not n.startswith(".")
        )


def compile_via_cache(
    jitted: Any,
    example_args: Any,
    cache: Optional["ExecutableCache"],
    base: Dict[str, Any],
    extra: Iterable[str] = (),
):
    """Compile ``jitted`` at ``example_args``' abstract shapes, round-
    tripping the executable through ``cache`` when one is given.

    The shared lower-once path for engines that manage their OWN program
    tables (the continuous-batching arena, ad-hoc tools): avals are derived
    from the example arguments (shape/dtype/sharding — never values, so
    passing live donated buffers is safe: nothing executes here), the
    fingerprint folds ``base`` + avals + ``extra``, and a hit skips
    trace/lower/compile entirely. ``cache=None`` degrades to a plain
    ``lower().compile()`` so callers need no branching."""
    import jax

    avals = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(
            np.shape(x), np.asarray(x).dtype if np.isscalar(x)
            else x.dtype, sharding=getattr(x, "sharding", None)),
        tuple(example_args))
    if cache is None:
        return jitted.lower(*avals).compile()
    fp = fingerprint(base, avals=avals, extra=extra)
    compiled = cache.load(fp)
    if compiled is None:
        compiled = jitted.lower(*avals).compile()
        cache.store(fp, compiled)
    return compiled


def resolve_cache(
    spec: Union[None, str, ExecutableCache],
    registry: Optional[obs.MetricsRegistry] = None,
) -> Optional[ExecutableCache]:
    """Normalize a ``compile_cache`` argument: a directory path opens
    (fail-soft), an :class:`ExecutableCache` passes through, None disables."""
    if spec is None or isinstance(spec, ExecutableCache):
        return spec
    return ExecutableCache.open(spec, registry=registry)


# -- tier 2: jax's persistent compilation cache ------------------------------

_TIER2_LOCK = threading.Lock()
_TIER2_DIR: Optional[str] = None
_DOUBLE_TIER_WARNED = False


def persistent_cache_active() -> bool:
    """True when jax's persistent compilation cache is on in this process
    (whether enabled here or by the caller's own jax config)."""
    with _TIER2_LOCK:
        if _TIER2_DIR is not None:
            return True
    try:
        import jax

        return bool(jax.config.jax_compilation_cache_dir)
    except Exception:
        return False


def enable_persistent_compilation_cache(directory: str) -> bool:
    """Point jax's persistent compilation cache at ``directory`` (min compile
    time 0, no size floor) so every backend compile in this process becomes a
    disk write/hit — the second tier, for paths the AOT executable cache
    can't cover (trainer steps, ad-hoc tools).

    Fail-soft and idempotent; returns True when the cache is active. Safe to
    call after the backend initialized (jax caches its "is the cache used"
    decision at first compile, so we reset it).
    """
    global _TIER2_DIR
    with _TIER2_LOCK:
        if _TIER2_DIR == directory:
            return True
        try:
            os.makedirs(directory, exist_ok=True)
            import jax

            jax.config.update("jax_compilation_cache_dir", directory)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
            try:
                # private but load-bearing: jax latches its cache-enabled
                # decision at the first compile; a process that already
                # compiled something (backend probe) must re-evaluate
                from jax._src import compilation_cache as _cc

                _cc.reset_cache()
            except Exception:
                pass
        except Exception as e:
            warnings.warn(
                f"persistent compilation cache {directory!r} unavailable "
                f"({type(e).__name__}: {e}) — compiles will not persist "
                "(everything still runs)", stacklevel=2)
            return False
        _TIER2_DIR = directory
    print(f"[aot] persistent compilation cache: {directory}",
          file=sys.stderr)
    return True


def maybe_enable_cache_from_env() -> Optional[str]:
    """Bench/tool opt-in: ``PIT_COMPILE_CACHE=DIR`` enables the tier-2
    persistent compilation cache so repeat sessions skip remote recompiles.
    Returns the directory when enabled. Never touches stdout (the one-JSON-
    line contracts) and never raises."""
    directory = os.environ.get("PIT_COMPILE_CACHE")
    if not directory:
        return None
    return directory if enable_persistent_compilation_cache(directory) else None
